# Developer entry points mirroring the reference's Makefile targets
# (SURVEY §4: make test-unit / test-integration-hermetic / bench-*).
# No linter is baked into this image; py_compile stands in for `make format`.

PY ?= python

.PHONY: test test-fast test-unit test-dist test-chaos bench bench-flowcontrol \
	bench-router-sse bench-decisions bench-sched bench-sched-offload \
	bench-scaleout bench-slo bench-overload bench-kvobs bench-multiturn \
	bench-timeline bench-fleet-chaos bench-shadow bench-rebalance \
	bench-forecast bench-autoscale bench-tails bench-pd-pipeline \
	dryrun render-chart \
	compile-check \
	verify-metrics verify-decisions verify-hotpath verify-threadsafe \
	verify-vectorized verify-slo verify-debug verify-fleet

# Full hermetic suite (virtual 8-device CPU mesh; no TPU or cluster needed —
# the reference needs envtest + kind for the equivalent coverage).
test: verify-metrics verify-decisions verify-hotpath verify-threadsafe verify-vectorized verify-slo verify-debug
	$(PY) -m pytest tests/ -q

# Everything except the spawned-process distributed tests (the slow tail)
# and the slow-marked multi-process fleet drills (those ride
# make test-chaos / make verify-fleet).
test-fast: verify-metrics verify-decisions verify-hotpath verify-threadsafe verify-vectorized verify-debug
	$(PY) -m pytest tests/ -q -m "not slow" \
		--deselect tests/test_multihost.py \
		--deselect tests/test_multihost_pd.py

# Static registry lint: duplicate family names / high-cardinality labels /
# missing pinned families across the router, engine, and sidecar metrics
# registries (also hooked into pytest via tests/test_observability.py).
verify-metrics:
	$(PY) scripts/verify_metrics.py

# Decision flight-recorder coverage lint: every registered
# filter/scorer/picker type must appear in a recorded decision
# (also hooked into pytest via tests/test_decisions.py).
verify-decisions:
	$(PY) scripts/verify_decisions.py

# Scheduling hot-path lint: no router module may call chain_block_hashes
# directly — everything goes through the prefix-hash memo
# (also hooked into pytest via tests/test_hashmemo.py).
verify-hotpath:
	$(PY) scripts/verify_hotpath.py

# Thread-safety declaration lint: every registered filter/scorer/picker
# must declare its THREAD_SAFE audit result — undeclared plugins would be
# silently trampolined onto the event loop, defeating the scheduler-pool
# offload (also hooked into pytest via tests/test_schedpool.py).
verify-threadsafe:
	$(PY) scripts/verify_threadsafe.py

# Vectorized-kernel coverage lint: every registered filter/scorer/picker
# must define its columnar batch kernel or be explicitly declared
# scalar-fallback — a silently-lost kernel costs the whole vectorized
# hot-path win with no error anywhere (also hooked into pytest via
# tests/test_vectorized.py).
verify-vectorized:
	$(PY) scripts/verify_vectorized.py

# SLO-ledger terminal-path check: success, shed, retry-exhausted, deadline,
# and mid-stream abort must ALL stamp an slo_met outcome on the decision
# record — absent rows overcount attainment (also hooked into pytest via
# tests/test_slo.py).
verify-slo:
	$(PY) scripts/verify_slo.py

# Debug-surface lint: every registered /debug route (gateway + fleet
# supervisor) must answer JSON and have a row in docs/observability.md's
# "Debug surfaces" index table — the debug-plane twin of verify-metrics'
# docs-sync lint (also hooked into pytest via tests/test_kvobs.py).
verify-debug:
	$(PY) scripts/verify_debug.py

# Fleet failover drill: boot a 2-worker fleet, SIGKILL the datalayer
# leader, and fail unless the supervisor promotes the follower and it is
# SERVING snapshots (its epoch advancing) within the bound, with the
# ex-leader rejoining as a follower (also hooked into pytest via
# tests/test_fleet.py, slow-marked).
verify-fleet:
	$(PY) scripts/verify_fleet.py

# Recorder-overhead microbench on the flow-control dispatch path (CPU-only;
# writes benchmarks/DECISIONS_MICRO.json — target <3%, kill-switch ~0%).
bench-decisions:
	$(PY) bench.py --sched-microbench --micro-only

# Pool-scale scheduling hot-path sweep (8/32/128 endpoints × 16/64/128
# blocks, recorder on/off, memoized vs pre-memo legacy emulation); writes
# benchmarks/SCHED_HOTPATH.json — target ≥30% lower cost at 128×64.
bench-sched:
	$(PY) bench.py --sched-microbench --sweep-only

# Concurrent-scheduling offload bench (CPU-only): event-loop stall p50/p99
# + streamed-token inter-arrival gap while 32 concurrent 128-endpoint
# scheduling cycles churn, offload on vs off; plus offloaded per-cycle cost
# and inline-vs-offload pick parity. Writes benchmarks/SCHED_OFFLOAD.json —
# target ≥5x lower p99 loop stall with offload on.
bench-sched-offload:
	$(PY) bench.py --sched-offload

# Multi-process scale-out bench (CPU-only): aggregate scheduling throughput
# under saturation churn in 1/2/4 worker processes over disjoint flow
# shards (the fleet's own flow_shard partitioner), plus cross-shard pick
# parity vs a single-process run (scheduling.pickSeed). Writes
# benchmarks/SCHED_SCALEOUT.json — target ≥2.5x aggregate cycles/sec at 4
# workers with bit-identical picks.
bench-scaleout:
	$(PY) bench.py --sched-scaleout

# SLO observability bench (CPU-only): per-chunk ledger-hook cost vs the 5ms
# token cadence (kill-switch ~0%) plus a rate ramp past saturation showing
# goodput vs raw throughput divergence and predictor MAE by load band.
# Writes benchmarks/SLO_OBS.json — the baseline ROADMAP item 5 (goodput-max
# admission) will be judged against.
bench-slo:
	$(PY) bench.py --slo-ramp

# Overload-control bench (CPU-only): the --slo-ramp machinery driven at
# 1x/2x/4x measured capacity with the goodput-max overload controller ON
# (predictive admission + degrade ladder + Retry-After shedding) and again
# with the kill-switch OFF (the PR 6 goodput collapse shape). Writes
# benchmarks/OVERLOAD.json — target: goodput at 2x/4x within 30% of 1x and
# overload wasted-token fraction < 0.15, with every shed explained.
bench-overload:
	$(PY) bench.py --overload-ramp

# KV-cache observability bench (CPU-only): the cache ledger's per-request
# hook cost vs the scheduling-cycle floor (kill-switch ~0%), then a
# shared-prefix workload (cold round, warm round) through a real gateway +
# sim engines reporting hit-prediction MAE warm vs cold and the actual hit
# ratio the engines confirmed. Writes benchmarks/KV_OBS.json — the
# measurement groundwork ROADMAP item 2's prefill classifier is judged
# against.
bench-kvobs:
	$(PY) bench.py --kv-obs

# Fleet flight recorder bench (CPU-only): sampler tick cost vs the
# scheduling-cycle floor (kill-switch ~0%), an overload-ramp replay whose
# 4x band must trip exactly ONE burn-rate incident (dedup/cooldown) with
# the shed excursion + a shed DecisionRecord in its snapshot, and a
# 2-worker fleet whose merged /debug/timeline gap-marks a worker restart.
# Writes benchmarks/TIMELINE.json.
bench-timeline:
	$(PY) bench.py --timeline

# Traffic forecaster & capacity observatory (CPU-only): observe() micro
# cost vs the scheduling-cycle floor + a compressed diurnal+burst replay
# judging forecast skill vs persistence (docs/forecast.md).
bench-forecast:
	$(PY) bench.py --forecast

# Tail-latency attribution observatory (CPU-only): the per-request
# waterfall lifecycle cost vs the scheduling-cycle floor (kill-switch
# ~0%), two injected-skew scenarios (one slow transfer pair via the
# per-peer sim pull map; one delay-chaos endpoint) where /debug/tails
# must attribute >= 60% of the tail cohort's excess to the injected
# stage with the correct culprit named, and a kill-switch parity arm
# (zero stamps, identical /debug/decisions). Writes
# benchmarks/TAILS.json (docs/tails.md).
bench-tails:
	$(PY) bench.py --tails

# Multi-turn conversation scenario (CPU-only): N users x M turns with a
# shared system prompt and per-user history growth through the full
# gateway -> sidecar -> P/D sim topology, session-sticky via
# x-session-token. Compares warm-turn TTFT with the session-aware prefill
# classifier (skip the P/D hop) against the always-disagg baseline,
# best-of-N reps per the shared-box precedent. Writes
# benchmarks/MULTITURN.json — targets: warm-turn TTFT p50 >= 25% better,
# cold turns within noise, classifier precision >= 0.9 judged against the
# CacheLedger's engine-confirmed actual hit depths.
bench-multiturn:
	$(PY) bench.py --multi-turn

# Pipelined P/D disaggregation bench (CPU-only): chunk-streamed KV
# handoff (decode pulls chunk k while prefill computes chunk k+1) vs the
# serial 2-phase protocol, on a sim pair whose per-peer pull map prices
# the transfer >= 0.5x the prefill cost. Writes benchmarks/PD_PIPELINE.json
# — gates: pipelined TTFT p50 >= 25% below serial at token parity, the
# pipeline_enabled: false arm bit-identical to the pre-pipeline protocol.
bench-pd-pipeline:
	$(PY) bench.py --pd-pipeline

# Shadow-policy evaluation bench (CPU-only): the live-path hook cost vs
# the scheduling-cycle floor (kill-switch ~0%), then a skewed transfer
# topology (per-peer sim pull maps: 2 fast pairs, N slow) where the
# transfer-pair shadow policy's estimated regret is validated against a
# live A/B arm running transfer-aware-pair-scorer for real — sign
# agreement + the documented error band, every divergent pick explained
# at /debug/decisions?divergent=1. Writes benchmarks/SHADOW.json.
bench-shadow:
	$(PY) bench.py --shadow

# Self-balancing pool bench (CPU-only): an open-loop ramp whose
# prefill:decode mix swings hard prefill-heavy -> hard decode-heavy
# mid-run through the full gateway -> sidecar -> P/D sim topology.
# Three arms: a balanced-mix static baseline, the static-split
# kill-switch arm (the drowning role's attainment collapses per phase),
# and the rebalancer arm (drain-cycle role flips hold BOTH roles'
# attainment within the acceptance band of the balanced baseline) —
# every flip drains with zero client-visible errors and is explained at
# /debug/rebalance. Writes benchmarks/REBALANCE.json.
bench-rebalance:
	$(PY) bench.py --rebalance

# Kill-the-leader chaos bench (CPU-only): a 3-worker fleet with
# confirmed-index replication under live traffic — SIGKILL the datalayer
# leader and gate on failover window <= bound, zero non-balancer client
# errors, post-promotion divergence ~0, exactly one divergence incident
# with the outage gap-marked on the merged timeline; then the
# SCHED_SCALEOUT churn cell re-run with the replication stream live vs
# off (gate: >=0.9x aggregate throughput). Writes
# benchmarks/FLEET_CHAOS.json.
bench-fleet-chaos:
	$(PY) bench.py --fleet-chaos

# Guarded elastic-fleet actuator bench (CPU-only): a diurnal ramp
# through four arms on the same trace — predictive (forecast-qualified
# spawns land BEFORE saturation and attainment holds through the
# plateau), reactive (the late trigger sheds into the cold-start
# window), chaos (six drills: spawn failure, retry, burn-rate rollback
# + freeze, advice flap, stuck drain force-finalized by the watchdog,
# leadership flip mid-action — zero client errors throughout), and the
# kill-switch arm (zero ticks, zero actions, bit-identical gateway).
# Writes benchmarks/AUTOSCALE.json.
bench-autoscale:
	$(PY) bench.py --autoscale

test-unit: test-fast

# The multi-process jax.distributed suites only.
test-dist:
	$(PY) -m pytest tests/test_multihost.py tests/test_multihost_pd.py -q

# Fault-injection suite with a fixed seed: chaos decisions hash
# (CHAOS_SEED, fault kind, request id), so reruns are bit-identical; the
# fleet leader-kill drill (3 workers, election + divergence recovery +
# /debug/fleet role table) rides along via tests/test_fleet.py, and the
# actuator's lifecycle drills (spawn_fail / stall_drain / slow_start)
# via tests/test_autoscale.py.
test-chaos: verify-metrics
	CHAOS_SEED=11 $(PY) -m pytest tests/test_resilience.py \
		tests/test_engine_robustness.py tests/test_fleet.py -q -k chaos
	CHAOS_SEED=11 $(PY) -m pytest tests/test_autoscale.py -q \
		-k TestLifecycleChaos

# Serving benchmark on the real chip (one JSON line; the driver's entry).
bench:
	$(PY) bench.py

bench-flowcontrol:
	$(PY) scripts/flowcontrol_bench.py

bench-router-sse:
	$(PY) scripts/profile_router_sse.py

# Driver-contract checks without hardware.
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('ok')"

compile-check:
	$(PY) -c "import jax, __graft_entry__ as g; fn, a = g.entry(); \
		jax.jit(fn)(*a); print('ok')"

render-chart:
	$(PY) scripts/render_chart.py deploy/charts/tpu-stack

# Syntax sweep (no linter in this image).
format:
	$(PY) -m compileall -q llm_d_inference_scheduler_tpu scripts tests
