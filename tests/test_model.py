"""Numerics tests for the Llama engine model: decode path == prefill path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_inference_scheduler_tpu.models import TINY, llama


@pytest.fixture(scope="module")
def params():
    return llama.init_params(TINY, jax.random.key(0), dtype=jnp.float32)


def test_forward_shapes(params):
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, TINY.vocab_size)
    logits, kv = llama.forward(params, TINY, tokens, want_kv=True)
    assert logits.shape == (2, 8, TINY.vocab_size)
    k, v = kv
    assert k.shape == (TINY.n_layers, 2, 8, TINY.n_kv_heads, TINY.head_dim)
    assert not np.isnan(np.asarray(logits)).any()


def test_causality(params):
    """Changing a later token must not change earlier logits."""
    t1 = jax.random.randint(jax.random.key(2), (1, 8), 0, TINY.vocab_size)
    t2 = t1.at[0, 5].set((t1[0, 5] + 1) % TINY.vocab_size)
    l1, _ = llama.forward(params, TINY, t1)
    l2, _ = llama.forward(params, TINY, t2)
    np.testing.assert_allclose(np.asarray(l1[0, :5]), np.asarray(l2[0, :5]), rtol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 5:]), np.asarray(l2[0, 5:]))


def test_paged_decode_matches_full_forward(params):
    """Prefill + paged decode must reproduce full-sequence forward logits."""
    cfg = TINY
    B, prompt_len, gen = 2, 7, 5
    total = prompt_len + gen
    block = cfg.kv_block_size
    max_blocks = -(-cfg.max_seq_len // block)
    n_blocks = 1 + B * max_blocks  # block 0 = trash

    key = jax.random.key(3)
    tokens = jax.random.randint(key, (B, total), 0, cfg.vocab_size)

    # Reference: full forward over the whole sequence.
    ref_logits, _ = llama.forward(params, cfg, tokens)

    # Paged path: prefill prompt, then decode token by token.
    kshape = (cfg.n_layers, n_blocks, block, cfg.n_kv_heads, cfg.head_dim)
    k_pages = jnp.zeros(kshape, jnp.float32)
    v_pages = jnp.zeros(kshape, jnp.float32)
    block_tables = jnp.arange(1, 1 + B * max_blocks, dtype=jnp.int32).reshape(B, max_blocks)

    prefill_logits, (k_new, v_new) = llama.forward(params, cfg, tokens[:, :prompt_len], want_kv=True)
    seq_lens = jnp.full((B,), prompt_len, jnp.int32)
    k_pages, v_pages = llama.write_prefill_kv(k_pages, v_pages, k_new, v_new, block_tables, seq_lens)
    np.testing.assert_allclose(
        np.asarray(prefill_logits), np.asarray(ref_logits[:, :prompt_len]), rtol=2e-4, atol=2e-4
    )

    for i in range(gen):
        pos = jnp.full((B,), prompt_len + i, jnp.int32)
        step_logits, k_pages, v_pages = llama.decode_step(
            params, cfg, tokens[:, prompt_len + i], pos, k_pages, v_pages, block_tables
        )
        np.testing.assert_allclose(
            np.asarray(step_logits),
            np.asarray(ref_logits[:, prompt_len + i]),
            rtol=2e-4,
            atol=2e-4,
        )


def test_decode_crosses_block_boundary(params):
    """Decode positions that span multiple KV blocks stay consistent."""
    cfg = TINY
    B = 1
    block = cfg.kv_block_size
    total = block + 4  # forces a second block
    tokens = jax.random.randint(jax.random.key(4), (B, total), 0, cfg.vocab_size)
    ref_logits, _ = llama.forward(params, cfg, tokens)

    max_blocks = 4
    n_blocks = 1 + max_blocks
    kshape = (cfg.n_layers, n_blocks, block, cfg.n_kv_heads, cfg.head_dim)
    k_pages = jnp.zeros(kshape, jnp.float32)
    v_pages = jnp.zeros(kshape, jnp.float32)
    block_tables = jnp.arange(1, 1 + max_blocks, dtype=jnp.int32).reshape(1, max_blocks)

    prompt_len = 2
    _, (k_new, v_new) = llama.forward(params, cfg, tokens[:, :prompt_len], want_kv=True)
    k_pages, v_pages = llama.write_prefill_kv(
        k_pages, v_pages, k_new, v_new, block_tables, jnp.array([prompt_len], jnp.int32)
    )
    for i in range(prompt_len, total):
        pos = jnp.array([i], jnp.int32)
        step_logits, k_pages, v_pages = llama.decode_step(
            params, cfg, tokens[:, i], pos, k_pages, v_pages, block_tables
        )
    np.testing.assert_allclose(
        np.asarray(step_logits), np.asarray(ref_logits[:, -1]), rtol=2e-4, atol=2e-4
    )
