"""Router core: scheduler loop, scorers/filters/pickers, config loader, extractor."""

import pytest

from llm_d_inference_scheduler_tpu.router import plugins  # noqa: F401 (registers)
from llm_d_inference_scheduler_tpu.router.config.loader import Handle, load_config
from llm_d_inference_scheduler_tpu.router.datalayer.data_graph import (
    DataDependencyError,
    validate_and_order_producers,
)
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.datalayer.extractor import CoreMetricsExtractor
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.plugin import TypedName
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
)
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    PREFIX_ATTRIBUTE_KEY,
    PrefixCacheMatchInfo,
)


def ep(addr, port=8200, role=None, waiting=0, kv=0.0, running=0, fresh=True):
    labels = {"llm-d.ai/role": role} if role else {}
    e = Endpoint(EndpointMetadata(name=addr, address=addr, port=port, labels=labels))
    e.metrics.waiting_queue_size = waiting
    e.metrics.kv_cache_usage_percent = kv
    e.metrics.running_requests_size = running
    if fresh:
        import time
        e.metrics.update_time = time.monotonic()
    return e


def req(model="m", prompt="hello", headers=None):
    return InferenceRequest(
        request_id="r1", target_model=model,
        body=InferenceRequestBody(completions={"model": model, "prompt": prompt}),
        headers=headers or {})


def test_default_config_schedules_least_loaded():
    handle = Handle(datastore=Datastore())
    cfg = load_config(None, handle)
    eps = [ep("10.0.0.1", waiting=10, kv=0.9),
           ep("10.0.0.2", waiting=0, kv=0.1),
           ep("10.0.0.3", waiting=5, kv=0.5)]
    result = cfg.scheduler.schedule(None, req(), eps)
    picked = result.primary().target_endpoints
    assert len(picked) == 1
    assert picked[0].metadata.address == "10.0.0.2"


def test_prefix_scorer_dominates_when_weighted():
    handle = Handle(datastore=Datastore())
    cfg = load_config(None, handle)  # prefix weight 3 vs queue/kv 2 each
    hot = ep("10.0.0.1", waiting=3, kv=0.5)
    hot.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(9, 10, 16))
    cold = ep("10.0.0.2", waiting=2, kv=0.4)
    cold.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(0, 10, 16))
    result = cfg.scheduler.schedule(None, req(), [hot, cold])
    # hot: queue 0*2 + kv 0.5*2 + prefix 0.9*3 = 3.7 ; cold: 2 + 1.2 + 0 = 3.2
    assert result.primary().target_endpoints[0].metadata.address == "10.0.0.1"


def test_role_filters():
    from llm_d_inference_scheduler_tpu.router.plugins.filters import (
        DecodeFilter, EncodeFilter, PrefillFilter)

    eps = [ep("1", role="prefill"), ep("2", role="decode"), ep("3"),
           ep("4", role="both"), ep("5", role="encode")]
    d = DecodeFilter("d").filter(None, None, req(), eps)
    assert {e.metadata.address for e in d} == {"2", "3", "4"}
    p = PrefillFilter("p").filter(None, None, req(), eps)
    assert {e.metadata.address for e in p} == {"1", "4"}
    enc = EncodeFilter("e").filter(None, None, req(), eps)
    assert {e.metadata.address for e in enc} == {"5"}


def test_custom_config_yaml():
    yaml_text = """
featureGates: {flowControl: false}
pool:
  endpoints:
    - address: 127.0.0.1
      port: 9001
      labels: {llm-d.ai/role: decode}
plugins:
  - type: load-aware-scorer
    parameters: {queueDepthThreshold: 10}
  - type: weighted-random-picker
    parameters: {maxNumOfEndpoints: 2}
schedulingProfiles:
  - name: default
    plugins:
      - pluginRef: load-aware-scorer
        weight: 1
      - pluginRef: weighted-random-picker
"""
    handle = Handle(datastore=Datastore())
    cfg = load_config(yaml_text, handle)
    assert cfg.static_endpoints[0].port == 9001
    eps = [ep("a", waiting=0), ep("b", waiting=0), ep("c", waiting=100)]
    result = cfg.scheduler.schedule(None, req(), eps)
    picked = result.primary().target_endpoints
    assert len(picked) == 2  # maxNumOfEndpoints honored
    assert {e.metadata.address for e in picked} <= {"a", "b", "c"}


def test_session_affinity_roundtrip():
    handle = Handle(datastore=Datastore())
    cfg = load_config("""
plugins:
  - type: session-affinity-scorer
  - type: queue-scorer
schedulingProfiles:
  - name: default
    plugins:
      - pluginRef: session-affinity-scorer
        weight: 10
      - pluginRef: queue-scorer
""", handle)
    eps = [ep("a", waiting=0), ep("b", waiting=5)]
    r1 = req()
    result = cfg.scheduler.schedule(None, r1, eps)
    chosen = result.primary().target_endpoints[0].metadata.address_port
    for p in cfg.pre_request_plugins:
        p.pre_request(None, r1, result)
    import base64

    # The stamped token is OPAQUE (base64 endpoint identity, reference
    # session_affinity.go), not a raw address echo.
    token = r1.headers["x-session-token"]
    assert token != chosen
    assert base64.standard_b64decode(token).decode() == chosen
    # A follow-up presenting the token sticks even if the other endpoint is
    # less loaded.
    r2 = req(headers={"x-session-token":
                      base64.standard_b64encode(b"b:8200").decode()})
    result2 = cfg.scheduler.schedule(None, r2, eps)
    assert result2.primary().target_endpoints[0].metadata.address_port == "b:8200"
    # Garbage tokens degrade to fresh placement, not errors.
    r3 = req(headers={"x-session-token": "!!not-base64!!"})
    result3 = cfg.scheduler.schedule(None, r3, eps)
    assert result3.primary().target_endpoints[0].metadata.address_port == "a:8200"


def test_extractor_parses_jetstream_and_vllm():
    text = """# HELP jetstream:num_requests_waiting w
# TYPE jetstream:num_requests_waiting gauge
jetstream:num_requests_waiting 7.0
jetstream:num_requests_running 3.0
jetstream:kv_cache_usage_perc 0.42
jetstream:lora_requests_info{max_lora="4",running_lora_adapters="a,b",waiting_lora_adapters="c"} 1.0
jetstream:cache_config_info{block_size="16",num_gpu_blocks="1000"} 1.0
"""
    e = ep("x", fresh=False)
    CoreMetricsExtractor("core").extract(text, e)
    m = e.metrics
    assert m.waiting_queue_size == 7 and m.running_requests_size == 3
    assert abs(m.kv_cache_usage_percent - 0.42) < 1e-9
    assert m.active_models == {"a": 1, "b": 1} and m.waiting_models == {"c": 1}
    assert m.max_active_models == 4
    assert m.kv_cache_max_token_capacity == 16000
    assert m.fresh

    vllm_text = "vllm:num_requests_waiting 9\nvllm:num_requests_running 1\nvllm:kv_cache_usage_perc 0.5\n"
    e2 = ep("y", fresh=False)
    e2.metadata.labels["llm-d.ai/engine-type"] = "vllm"
    CoreMetricsExtractor("core").extract(vllm_text, e2)
    assert e2.metrics.waiting_queue_size == 9


def test_data_graph_ordering_and_cycles():
    class P:
        def __init__(self, name, produces, consumes):
            self._n, self._p, self._c = name, produces, consumes

        def typed_name(self):
            return TypedName("producer", self._n)

        def produces(self):
            return self._p

        def consumes(self):
            return self._c

    a = P("a", ["k1"], [])
    b = P("b", ["k2"], ["k1"])
    c = P("c", [], ["k2"])
    order = validate_and_order_producers([c, b, a])
    assert order.index(a) < order.index(b) < order.index(c)

    x = P("x", ["k3"], ["k4"])
    y = P("y", ["k4"], ["k3"])
    with pytest.raises(DataDependencyError):
        validate_and_order_producers([x, y])


def test_model_rewrite_weighted():
    from llm_d_inference_scheduler_tpu.router.datalayer.datastore import (
        InferenceModelRewrite, ModelRewriteTarget)
    import random

    rw = InferenceModelRewrite("rw", "base", [
        ModelRewriteTarget("a", 3), ModelRewriteTarget("b", 1)])
    rng = random.Random(7)
    picks = [rw.pick_target(rng) for _ in range(400)]
    assert 0.6 < picks.count("a") / 400 < 0.9


def test_no_hit_lru_scorer_spreads_cold_traffic():
    from llm_d_inference_scheduler_tpu.router.plugins.scorers import NoHitLruScorer
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        ProfileRunResult, SchedulingResult)

    s = NoHitLruScorer("lru")
    eps = [ep("a"), ep("b"), ep("c")]
    for e in eps:
        e.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(0, 10, 16))

    # All cold, no history: never-cold endpoints rank by candidate order
    # (reference no_hit_lru.go:197-206: 1 - i/(N-1)).
    r1 = req()
    scores = s.score(None, None, r1, eps)
    assert scores["a:8200"] == 1.0
    assert scores["b:8200"] == 0.5
    assert scores["c:8200"] == 0.0

    # Record a cold route to "a" (same request whose score marked it cold):
    # "a" becomes most-recently-cold → lowest score; b/c (never used) lead.
    res = SchedulingResult({"default": ProfileRunResult([eps[0]])}, "default")
    s.pre_request(None, r1, res)
    scores = s.score(None, None, req(), eps)
    assert scores["b:8200"] == 1.0
    assert scores["c:8200"] == 0.5
    assert scores["a:8200"] == 0.0

    # Cold-route "b" too: LRU order now a (older) then b → a outranks b.
    r2 = req()
    s.score(None, None, r2, eps)
    s.pre_request(None, r2, SchedulingResult(
        {"default": ProfileRunResult([eps[1]])}, "default"))
    scores = s.score(None, None, req(), eps)
    assert scores["c:8200"] == 1.0          # never cold-routed
    assert scores["a:8200"] == 0.5          # oldest cold route
    assert scores["b:8200"] == 0.0          # most recent cold route

    # A "prefill" profile pick also counts as cache growth (P/D split).
    r3 = req()
    s.score(None, None, r3, eps)
    s.pre_request(None, r3, SchedulingResult(
        {"default": ProfileRunResult([eps[1]]),
         "prefill": ProfileRunResult([eps[2]])}, "default"))
    scores = s.score(None, None, req(), eps)
    assert scores["a:8200"] == 1.0          # now the least-recently cold
    assert scores["b:8200"] == 0.5
    assert scores["c:8200"] == 0.0

    # With a prefix hit somewhere, the scorer goes neutral.
    eps[1].attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(5, 10, 16))
    scores = s.score(None, None, req(), eps)
    assert set(scores.values()) == {0.5}


def test_no_hit_lru_cold_flag_not_erased_across_profiles():
    """A warm pass in one profile must not wipe a cold decision recorded by
    another profile's pass (one scorer instance shared via pluginRef), and
    the primary profile's decision wins when it scored."""
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        CycleState, ProfileRunResult, SchedulingResult)
    from llm_d_inference_scheduler_tpu.router.plugins.scorers import NoHitLruScorer

    cold_eps = [ep("a"), ep("b")]
    for e in cold_eps:
        e.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(0, 10, 16))
    warm_eps = [ep("c")]
    warm_eps[0].attributes.put(PREFIX_ATTRIBUTE_KEY,
                               PrefixCacheMatchInfo(4, 10, 16))

    def run(primary_warm: bool, order):
        s = NoHitLruScorer("lru")
        r = req()
        state = CycleState()
        name = str(s.typed_name())
        raw = {}
        for profile in order:
            state.write("current_profile", profile)
            eps_for = warm_eps if (profile == "default") == primary_warm \
                else cold_eps
            raw[profile] = s.score(None, state, r, eps_for)
        res = SchedulingResult(
            {"default": ProfileRunResult([cold_eps[0]],
                                         raw_scores={name: raw["default"]}),
             "prefill": ProfileRunResult([cold_eps[1]],
                                         raw_scores={name: raw["prefill"]})},
            "default")
        s.pre_request(None, r, res)
        return list(s._lru)

    # Primary warm (hit), prefill cold — primary decision wins: no touch,
    # regardless of which profile scored last.
    assert run(primary_warm=True, order=["prefill", "default"]) == []
    assert run(primary_warm=True, order=["default", "prefill"]) == []
    # Primary cold, prefill warm — cold decision survives a later warm pass.
    assert run(primary_warm=False, order=["default", "prefill"]) \
        == ["a:8200", "b:8200"]


def test_vertexai_parser():
    from llm_d_inference_scheduler_tpu.router.handlers.parsers import VertexAIParser
    import json

    p = VertexAIParser("v")
    res = p.parse(json.dumps({
        "model": "m", "instances": [{"prompt": "hello"}],
        "parameters": {"maxOutputTokens": 7, "temperature": 0.5}}).encode(), {})
    assert res.error is None and not res.skip
    assert res.body.completions["prompt"] == "hello"
    assert res.body.completions["max_tokens"] == 7

    res = p.parse(json.dumps({
        "model": "m",
        "instances": [{"messages": [{"role": "user", "content": "hi"}]}]}).encode(), {})
    assert res.body.chat_completions is not None

    res = p.parse(b'{"no": "instances"}', {})
    assert res.error


def test_header_based_testing_filter_and_served_verifier():
    from llm_d_inference_scheduler_tpu.router.plugins.testing import (
        DestinationEndpointServedVerifier, HeaderBasedTestingFilter)

    eps = [ep("a"), ep("b"), ep("c")]
    f = HeaderBasedTestingFilter("t")
    out = f.filter(None, None, req(headers={"test-epp-endpoint-selection": "b:8200"}), eps)
    assert [e.metadata.address_port for e in out] == ["b:8200"]
    assert f.filter(None, None, req(), eps) == eps  # no header: pass-through
    # unknown endpoint named: fail open
    out = f.filter(None, None, req(headers={"test-epp-endpoint-selection": "zz:1"}), eps)
    assert out == eps

    v = DestinationEndpointServedVerifier("v")
    r1 = req(headers={"x-gateway-destination-endpoint": "a:8200,b:8200"})
    v.response_received(None, r1, eps[0], 200)   # served a -> ok
    assert v.mismatches == 0
    v.response_received(None, r1, eps[2], 200)   # served c -> mismatch
    assert v.mismatches == 1


def test_example_configs_load():
    """Every shipped examples/*.yaml must instantiate cleanly."""
    import pathlib

    ex_dir = pathlib.Path(__file__).resolve().parent.parent / "examples"
    assert ex_dir.is_dir()
    loaded = 0
    for path in sorted(ex_dir.glob("*.yaml")):
        cfg = load_config(path.read_text(), Handle())
        assert cfg.scheduler is not None, path.name
        loaded += 1
    assert loaded >= 3  # monolithic, disagg, slo_aware


def test_response_streaming_plugins_run_async_but_ordered():
    """Streaming plugins run off the hot path on a per-request worker
    (reference director.go:92-134), and completion runs strictly AFTER all
    queued chunks."""
    import asyncio

    from llm_d_inference_scheduler_tpu.router.requestcontrol.director import (
        Director,
    )

    events = []

    class SlowStreamPlugin:
        def typed_name(self):
            return ("t", "slow")

        def response_streaming(self, ctx, request, endpoint, chunk):
            events.append(("chunk", chunk))

        def response_complete(self, ctx, request, endpoint, usage):
            events.append(("complete", usage.get("n")))

    async def body():
        plugin = SlowStreamPlugin()
        d = Director(Datastore(), None, admission=None,
                     response_streaming=[plugin], response_complete=[plugin])
        r = req()
        t0 = __import__("time").monotonic()
        for i in range(5):
            d.handle_response_streaming(None, r, None, f"c{i}".encode())
        # Enqueue is non-blocking regardless of plugin cost.
        assert __import__("time").monotonic() - t0 < 0.05
        d.handle_response_complete(None, r, None, {"n": 7})
        await asyncio.sleep(0.1)  # worker drains
        assert events == [("chunk", b"c0"), ("chunk", b"c1"), ("chunk", b"c2"),
                          ("chunk", b"c3"), ("chunk", b"c4"), ("complete", 7)]

    asyncio.run(body())


def test_decode_batch_bucket():
    from llm_d_inference_scheduler_tpu.engine.config import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    eng = TpuEngine(EngineConfig(model="tiny", max_batch=8, kv_events_port=0))
    assert eng._batch_bucket(1) == 1
    assert eng._batch_bucket(2) == 2
    assert eng._batch_bucket(3) == 4
    assert eng._batch_bucket(5) == 8
    assert eng._batch_bucket(8) == 8


def test_disagg_headers_handler_prerequest_wiring():
    """Deprecated header-only PreRequest variant (reference
    disagg_headers_handler.go): writes/clears the disagg routing headers from
    named profile results without orchestrating the profiles itself."""
    from llm_d_inference_scheduler_tpu.router.framework.plugin import global_registry
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        ProfileRunResult,
        SchedulingResult,
    )
    from llm_d_inference_scheduler_tpu.router.requestcontrol.director import (
        H_ENCODERS,
        H_PREFILLER,
    )

    h = global_registry.instantiate(
        "disagg-headers-handler", "h", {"prefillProfile": "pf"}, Handle())
    r = req(headers={H_PREFILLER: "stale:1", H_ENCODERS: "stale:2"})
    res = SchedulingResult(
        profile_results={
            "decode": ProfileRunResult(target_endpoints=[ep("d")]),
            "pf": ProfileRunResult(target_endpoints=[ep("p")]),
            "encode": ProfileRunResult(target_endpoints=[ep("e1"), ep("e2")]),
        },
        primary_profile_name="decode")
    h.pre_request(None, r, res)
    assert r.headers[H_PREFILLER] == "p:8200"
    assert r.headers[H_ENCODERS] == "e1:8200,e2:8200"

    # No prefill/encode results: stale headers are cleared, not preserved.
    r2 = req(headers={H_PREFILLER: "stale:1", H_ENCODERS: "stale:2"})
    h.pre_request(None, r2, SchedulingResult(
        profile_results={"decode": ProfileRunResult(target_endpoints=[ep("d")])},
        primary_profile_name="decode"))
    assert H_PREFILLER not in r2.headers
    assert H_ENCODERS not in r2.headers

    # prefill-header-handler is a registered alias.
    alias = global_registry.instantiate("prefill-header-handler", "a", {}, Handle())
    assert alias is not None


def test_sse_has_token_classifier():
    """Gateway TTFT must ignore token-free chunks (role-only chat deltas)."""
    from llm_d_inference_scheduler_tpu.router.gateway import _sse_scan_for_token

    def has_token(chunk):
        found, _ = _sse_scan_for_token(b"", chunk)
        return found

    role_only = (b'data: {"choices": [{"delta": {"role": "assistant"}}]}\n\n')
    content = (b'data: {"choices": [{"delta": {"content": "hi"}}]}\n\n')
    completion = b'data: {"choices": [{"text": "hi"}]}\n\n'
    done = b"data: [DONE]\n\n"
    unparseable = b"data: not-json\n\n"
    assert not has_token(role_only)
    assert not has_token(done)
    assert has_token(content)
    assert has_token(completion)
    assert has_token(unparseable)  # fail open
    assert has_token(role_only + content)  # mixed chunk counts

    # Events split across transport chunks reassemble via the carry instead
    # of misclassifying (truncated role-only must NOT fail open mid-event).

    first, second = role_only[:20], role_only[20:]
    found, carry = _sse_scan_for_token(b"", first)
    assert not found and carry  # partial line buffered, not counted
    found, carry = _sse_scan_for_token(carry, second)
    assert not found  # reassembled role-only delta still token-free
    found, carry = _sse_scan_for_token(carry, content[:15])
    assert not found
    found, _ = _sse_scan_for_token(carry, content[15:])
    assert found  # reassembled content delta counts
