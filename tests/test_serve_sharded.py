"""TP-sharded serving path (parallel/serve.py) on the virtual 8-CPU mesh.

Covers the driver's `dryrun_multichip` serving leg plus the engine running
with tp_size>1 end-to-end — the stepping stone to BASELINE.md config 4
(TP-sharded decode). Reference analogue: vLLM's --tensor-parallel-size,
orchestrated but never implemented by the router (SURVEY §2.12).
"""

import asyncio

import jax
import numpy as np
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
from llm_d_inference_scheduler_tpu.models import TINY
from llm_d_inference_scheduler_tpu.parallel.serve import (
    dryrun_serve,
    make_serve_mesh,
    validate_tp,
)


def test_dryrun_serve_matches_single_device():
    dryrun_serve(TINY, jax.devices()[:8], tp=2)


def test_validate_tp_rejects_bad_factor():
    with pytest.raises(ValueError):
        validate_tp(TINY, 3)  # n_kv_heads=2 not divisible


def test_make_serve_mesh_shape():
    mesh = make_serve_mesh(jax.devices()[:8], tp=2)
    assert mesh.shape == {"dp": 4, "tp": 2, "ep": 1}
    moe_mesh = make_serve_mesh(jax.devices()[:8], tp=2, ep=2)
    assert moe_mesh.shape == {"dp": 2, "tp": 2, "ep": 2}


def test_engine_tp_sharded_decode_matches_unsharded():
    """Same seed/request through tp=2 and tp=1 engines → identical tokens
    (greedy), proving the sharded serving jits are numerically faithful."""

    async def run(tp_size: int) -> list[int]:
        cfg = EngineConfig(model="tiny", max_batch=2, max_model_len=128,
                           tp_size=tp_size, enable_prefix_caching=False,
                           kv_events_port=0)
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            req = EngineRequest(
                request_id="tp-test",
                prompt_token_ids=[1] + [(i * 7) % 400 + 3 for i in range(40)],
                max_tokens=8, temperature=0.0, ignore_eos=True)
            out = eng.submit(req)
            toks = []
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=60)
                if ev.token_id is not None:
                    toks.append(ev.token_id)
                if ev.finish_reason is not None:
                    return toks
        finally:
            await eng.stop()

    sharded = asyncio.run(run(2))
    plain = asyncio.run(run(1))
    assert len(sharded) == 8 and len(plain) == 8
    # bf16 matmul reduction order differs across shardings, so a mid-stream
    # argmax tie-flip would cascade through the autoregressive tail — only the
    # first token is a stable cross-engine invariant here. The rigorous
    # numeric equivalence check (full logits, every step, f32) is
    # test_dryrun_serve_matches_single_device.
    assert sharded[0] == plain[0]


def test_engine_tp_rejects_invalid():
    with pytest.raises(ValueError):
        TpuEngine(EngineConfig(model="tiny", tp_size=3, kv_events_port=0))


def test_moe_serve_dryrun_tp_ep():
    from llm_d_inference_scheduler_tpu.models.configs import TINY_MOE

    dryrun_serve(TINY_MOE, jax.devices()[:8], tp=2, ep=2)


def test_moe_engine_serves_end_to_end():
    """tiny-moe through the full continuous-batching engine (the FFN hook
    covers prefill, paged decode, and prefix reuse unchanged)."""

    async def run() -> list[int]:
        cfg = EngineConfig(model="tiny-moe", max_batch=2, max_model_len=128,
                           kv_events_port=0)
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            req = EngineRequest(
                request_id="moe-test",
                prompt_token_ids=[1] + [(i * 5) % 400 + 3 for i in range(30)],
                max_tokens=6, temperature=0.0, ignore_eos=True)
            out = eng.submit(req)
            toks = []
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=60)
                if ev.token_id is not None:
                    toks.append(ev.token_id)
                if ev.finish_reason is not None:
                    return toks
        finally:
            await eng.stop()

    toks = asyncio.run(run())
    assert len(toks) == 6


def test_validate_ep_constraints():
    from llm_d_inference_scheduler_tpu.models.configs import TINY, TINY_MOE

    with pytest.raises(ValueError):
        validate_tp(TINY, 1, ep=2)       # dense model can't expert-shard
    with pytest.raises(ValueError):
        validate_tp(TINY_MOE, 1, ep=3)   # 4 experts % 3 != 0
    validate_tp(TINY_MOE, 2, ep=2)       # ok


def test_pipeline_forward_matches_single_device():
    from llm_d_inference_scheduler_tpu.parallel.pipeline import dryrun_pipeline

    dryrun_pipeline(TINY, jax.devices()[:2], pp=2, n_microbatches=4)


def test_pipeline_moe_and_bad_layer_split():
    from llm_d_inference_scheduler_tpu.models import llama
    from llm_d_inference_scheduler_tpu.models.configs import TINY_MOE
    from llm_d_inference_scheduler_tpu.parallel.pipeline import (
        dryrun_pipeline,
        make_pp_mesh,
        shard_params_pp,
    )

    dryrun_pipeline(TINY_MOE, jax.devices()[:2], pp=2, n_microbatches=2)
    # TINY has 2 layers: a 4-stage pipeline cannot split them evenly.
    mesh4 = make_pp_mesh(jax.devices()[:4], pp=4)
    params = llama.init_params(TINY, jax.random.key(0))
    with pytest.raises(ValueError):
        shard_params_pp(params, TINY, mesh4)


def test_engine_tp_sharded_qwen_decode():
    """tiny-qwen (QK-norm + head_dim override) through a tp=2 engine: the
    q_norm/k_norm params shard (replicated) and the decode-step hook runs
    under the tp shard_map."""

    async def run(tp_size: int) -> list[int]:
        cfg = EngineConfig(model="tiny-qwen", max_batch=2, max_model_len=128,
                           tp_size=tp_size, enable_prefix_caching=False,
                           kv_events_port=0)
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            req = EngineRequest(
                request_id="tp-qwen",
                prompt_token_ids=[1] + [(i * 5) % 400 + 3 for i in range(24)],
                max_tokens=6, temperature=0.0, ignore_eos=True)
            out = eng.submit(req)
            toks = []
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=120)
                if ev.token_id is not None:
                    toks.append(ev.token_id)
                if ev.finish_reason is not None:
                    return toks
        finally:
            await eng.stop()

    sharded = asyncio.run(run(2))
    plain = asyncio.run(run(1))
    assert len(sharded) == 6 and len(plain) == 6
    assert sharded[0] == plain[0]  # see bf16 tie-flip note above
