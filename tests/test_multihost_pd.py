"""Multi-host sharded KV handoff (VERDICT r2 missing #6): a 2-process
prefill group stages per-process shard descriptors; a 2-process decode
group runs the leader-coordinated pull op — every process fetches its page
shards from its counterpart and scatters in lockstep. Greedy tokens must
match a single-process tp=2 monolithic engine.

Reference analogue: NIXL multi-rank transfer descriptors relayed through
kv_transfer_params (connector_nixlv2.go:191-253).
"""

from __future__ import annotations

import asyncio
import multiprocessing as mp
import os

PROMPT = [1] + [(i * 7) % 350 + 3 for i in range(40)]
N_GEN = 6

COORD_PRE = "127.0.0.1:19911"
COORD_DEC = "127.0.0.1:19913"
INSTR_PRE = 19912
INSTR_DEC = 19914


def _cfg(**kw):
    from llm_d_inference_scheduler_tpu.engine import EngineConfig

    base = dict(model="tiny", backend="tpu", max_batch=2, max_model_len=64,
                tp_size=2, decode_chunk=4, kv_events_port=0, seed=3,
                warmup=False,
                # 4 processes share one CI core: a compile burst can starve
                # a ping thread past the 30 s production deadline, killing
                # the prefill follower (and its staged KV shard server)
                # before the decode group pulls.
                dist_recv_timeout_s=600.0)
    base.update(kw)
    return EngineConfig(**base)


async def _collect(eng, req):
    out = eng.submit(req)
    toks, ktp = [], None
    while True:
        ev = await asyncio.wait_for(out.get(), timeout=300)
        if ev.token_id is not None:
            toks.append(ev.token_id)
        if ev.finish_reason is not None:
            return toks, ev.kv_transfer_params


def _child_env():
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")


def _prefill_worker(pid, ktp_q, done_q, err_q, overrides=None):
    _child_env()
    try:
        from llm_d_inference_scheduler_tpu.engine import EngineRequest
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        # The decode group compiles for minutes on the single-core CI box;
        # the default 60 s export TTL would expire (and drain) the staged
        # shards first, and a pull of a drained uuid blocks forever.
        import llm_d_inference_scheduler_tpu.engine.core as core

        core.KV_EXPORT_TTL_S = 1200.0

        ov = dict(overrides or {})
        coord = ov.pop("coord", COORD_PRE)
        instr = ov.pop("instr", INSTR_PRE)
        cfg = _cfg(dist_coordinator=coord, dist_num_processes=2,
                   dist_process_id=pid, dist_instr_port=instr, **ov)
        maybe_init_distributed(cfg)
        eng = TpuEngine(cfg)

        if pid != 0:
            run_follower(eng)
            return

        async def lead():
            await eng.start()
            req = EngineRequest(
                request_id="pd-pre", prompt_token_ids=list(PROMPT),
                max_tokens=1, temperature=0.0, ignore_eos=True,
                kv_transfer_params={"do_remote_decode": True})
            toks, ktp = await _collect(eng, req)
            ktp_q.put(ktp)

            # Keep the staged export alive until the decode group pulled it.
            # A Queue, not an mp.Event: only the parent ever writes it, so a
            # crashed reader can never leave the write path's lock held —
            # an Event.set() in the parent deadlocked forever when a child
            # died inside Event.wait() holding the shared condition lock.
            def _await_done():
                try:
                    done_q.get(timeout=240)
                except Exception:
                    pass

            await asyncio.get_running_loop().run_in_executor(None, _await_done)
            await eng.stop()

        asyncio.run(lead())
    except Exception as e:
        import traceback

        err_q.put(f"prefill pid{pid}: {e}\n{traceback.format_exc()[-2000:]}")


def _decode_worker(pid, ktp_q, tok_q, err_q, overrides=None):
    _child_env()
    try:
        from llm_d_inference_scheduler_tpu.engine import EngineRequest
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        ov = dict(overrides or {})
        coord = ov.pop("coord", COORD_DEC)
        instr = ov.pop("instr", INSTR_DEC)
        cfg = _cfg(dist_coordinator=coord, dist_num_processes=2,
                   dist_process_id=pid, dist_instr_port=instr, **ov)
        maybe_init_distributed(cfg)
        eng = TpuEngine(cfg)

        if pid != 0:
            run_follower(eng)
            return

        async def lead():
            await eng.start()
            ktp = ktp_q.get(timeout=240)
            req = EngineRequest(
                request_id="pd-dec", prompt_token_ids=list(PROMPT),
                max_tokens=N_GEN, temperature=0.0, ignore_eos=True,
                kv_transfer_params=ktp)
            toks, _ = await _collect(eng, req)
            tok_q.put({"tokens": toks,
                       "device_imports": eng.kv_import_device_count,
                       "host_imports": eng.kv_import_host_count})
            await eng.stop()

        asyncio.run(lead())
    except Exception as e:
        import traceback

        err_q.put(f"decode pid{pid}: {e}\n{traceback.format_exc()[-2000:]}")


def test_dist_pd_sharded_handoff_matches_monolithic():
    # Reference tokens: single-process tp=2 monolithic engine.
    _sharded_handoff_roundtrip({})


def test_dist_pd_pp_sharded_handoff_matches_monolithic():
    """Disaggregation across HOST-SPANNING pp groups: a 2-process pp2×tp2
    prefill group stages layer-axis page shards, the pp decode group runs
    the coordinated pull — tokens match a single-process pp2×tp2 engine.
    (The BASELINE config-4 deployment: deep pipeline spanning hosts, P/D
    split on top.)"""
    _sharded_handoff_roundtrip(
        {"pp_size": 2, "tp_size": 2},
        coord_pre="127.0.0.1:19931", instr_pre=19932,
        coord_dec="127.0.0.1:19933", instr_dec=19934)


def _sharded_handoff_roundtrip(shape_kw, coord_pre=COORD_PRE,
                               instr_pre=INSTR_PRE, coord_dec=COORD_DEC,
                               instr_dec=INSTR_DEC):
    from llm_d_inference_scheduler_tpu.engine import EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    async def mono():
        eng = TpuEngine(_cfg(**shape_kw))
        await eng.start()
        try:
            toks, _ = await _collect(eng, EngineRequest(
                request_id="mono", prompt_token_ids=list(PROMPT),
                max_tokens=N_GEN, temperature=0.0, ignore_eos=True))
            return toks
        finally:
            await eng.stop()

    expected = asyncio.run(mono())
    assert len(expected) == N_GEN

    pre_ov = {"coord": coord_pre, "instr": instr_pre, **shape_kw}
    dec_ov = {"coord": coord_dec, "instr": instr_dec, **shape_kw}
    ctx = mp.get_context("spawn")
    ktp_q, tok_q, err_q = ctx.Queue(), ctx.Queue(), ctx.Queue()
    done_q = ctx.Queue()
    ktp_relay = ctx.Queue()
    pre_procs = [
        ctx.Process(target=_prefill_worker,
                    args=(pid, ktp_q, done_q, err_q, pre_ov),
                    daemon=True) for pid in range(2)]
    dec_procs = [
        ctx.Process(target=_decode_worker,
                    args=(pid, ktp_relay, tok_q, err_q, dec_ov),
                    daemon=True) for pid in range(2)]
    procs = pre_procs + dec_procs

    import queue as _queue

    def wait_for(q, what, seconds):
        for _ in range(seconds):
            try:
                return q.get(timeout=1)
            except _queue.Empty:
                if not err_q.empty():
                    raise AssertionError(err_q.get())
        raise AssertionError(f"timed out waiting for {what}")

    for p in pre_procs:
        p.start()
    try:
        ktp = wait_for(ktp_q, "prefill kv_transfer_params", 600)
        # Per-process shard descriptors are on the wire.
        assert len(ktp.get("transfer_shards") or []) == 2
        assert all(a for a in ktp["transfer_shards"])
        assert ktp["kv_mesh"]["n_procs"] == 2

        # Stagger the decode group AFTER the export exists: halves peak
        # compile contention on the single-core CI box (the prefill pair
        # idles, keeping the staged shards alive).
        for p in dec_procs:
            p.start()
        ktp_relay.put(ktp)
        result = wait_for(tok_q, "decode tokens", 600)
        done_q.put(True)
        # kv_wire auto resolves to the host shard wire on the cpu backend:
        # jax.experimental.transfer cannot carry same-host cross-process
        # pulls there (fatal local-transport check / socket-transport hang —
        # engine/shard_wire.py docstring). The coordinated sharded pull op,
        # descriptors, and lockstep scatter are identical for both wires;
        # the device wire itself is exercised by test_kv_device_transfer
        # (same-process) and on real TPU meshes.
        assert result["device_imports"] == 0
        assert result["host_imports"] == 1
        assert result["tokens"] == expected
    finally:
        done_q.put(True)  # idempotent release; put never blocks here
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.terminate()
        for p in procs:
            if p.is_alive():
                p.join(timeout=10)
                if p.is_alive():
                    p.kill()
    assert err_q.empty(), err_q.get() if not err_q.empty() else ""


COORD_DEG = "127.0.0.1:19921"
INSTR_DEG = 19922


def _decode_degrade_worker(pid, tok_q, err_q):
    """Decode group on the HOST wire receives a mixed-wire ktp: sharded
    descriptors from a device-wire-only exporter (no shard_wire_addrs).
    The fetch preflight must reject it and degrade to local prefill —
    reference fallback-to-decode semantics (connector_nixlv2.go:160-177)."""
    _child_env()
    try:
        from llm_d_inference_scheduler_tpu.engine import EngineRequest
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.kv_shards import (
            mesh_descriptor,
        )
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        cfg = _cfg(dist_coordinator=COORD_DEG, dist_num_processes=2,
                   dist_process_id=pid, dist_instr_port=INSTR_DEG)
        maybe_init_distributed(cfg)
        eng = TpuEngine(cfg)

        if pid != 0:
            run_follower(eng)
            return

        async def lead():
            await eng.start()
            # The ktp a device-wire exporter with matching page geometry
            # would relay: transfer_shards present, shard_wire_addrs ABSENT.
            # This decode group's wire is host (kv_wire=auto on cpu), so the
            # preflight has no usable addresses and must not touch
            # transfer_shards (port 1 would refuse anyway).
            mesh, spec = eng._page_layout()
            assert mesh is not None and eng._kv_wire == "host"
            ktp = {
                "remote_host": "127.0.0.1", "remote_port": 1,
                "remote_request_id": "degrade-src",
                "transfer_uuid": 7,
                "kv_mesh": mesh_descriptor(mesh, spec),
                "transfer_shards": ["127.0.0.1:1", "127.0.0.1:1"],
            }
            req = EngineRequest(
                request_id="pd-degrade", prompt_token_ids=list(PROMPT),
                max_tokens=N_GEN, temperature=0.0, ignore_eos=True,
                kv_transfer_params=ktp)
            toks, _ = await _collect(eng, req)
            tok_q.put({"tokens": toks,
                       "device_imports": eng.kv_import_device_count,
                       "host_imports": eng.kv_import_host_count})
            await eng.stop()

        asyncio.run(lead())
    except Exception as e:
        import traceback

        err_q.put(f"degrade pid{pid}: {e}\n{traceback.format_exc()[-2000:]}")


def test_dist_pd_mixed_wire_degrades_to_local_prefill():
    """VERDICT r4 weak #7 / NEXT item 6: a host-wire decode group handed a
    ktp without shard_wire_addrs must fall back to local prefill — no wire
    traffic, no deadlock, tokens identical to a monolithic engine."""
    from llm_d_inference_scheduler_tpu.engine import EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    async def mono():
        eng = TpuEngine(_cfg())
        await eng.start()
        try:
            toks, _ = await _collect(eng, EngineRequest(
                request_id="mono-deg", prompt_token_ids=list(PROMPT),
                max_tokens=N_GEN, temperature=0.0, ignore_eos=True))
            return toks
        finally:
            await eng.stop()

    expected = asyncio.run(mono())
    assert len(expected) == N_GEN

    ctx = mp.get_context("spawn")
    tok_q, err_q = ctx.Queue(), ctx.Queue()
    procs = [ctx.Process(target=_decode_degrade_worker,
                         args=(pid, tok_q, err_q), daemon=True)
             for pid in range(2)]

    import queue as _queue

    for p in procs:
        p.start()
    try:
        result = None
        for _ in range(600):
            try:
                result = tok_q.get(timeout=1)
                break
            except _queue.Empty:
                if not err_q.empty():
                    raise AssertionError(err_q.get())
        assert result is not None, "timed out waiting for degraded decode"
        # Zero imports on either wire: the request was served by local
        # prefill, not a transfer.
        assert result["device_imports"] == 0
        assert result["host_imports"] == 0
        assert result["tokens"] == expected
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.terminate()
        for p in procs:
            if p.is_alive():
                p.join(timeout=10)
                if p.is_alive():
                    p.kill()
    assert err_q.empty(), err_q.get() if not err_q.empty() else ""


def test_shard_wire_roundtrip():
    """ShardWireServer protocol: register → pull → byte-exact arrays,
    unknown uuid errors, unregister drops."""
    import numpy as np
    import pytest

    from llm_d_inference_scheduler_tpu.engine.shard_wire import (
        ShardWireServer,
        pull_shards,
    )

    srv = ShardWireServer("127.0.0.1")
    try:
        a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        b = np.arange(6, dtype=np.int32).reshape(3, 2)
        srv.register(42, [a, b])
        got = pull_shards(srv.address(), 42)
        assert len(got) == 2
        np.testing.assert_array_equal(got[0], a)
        np.testing.assert_array_equal(got[1], b)

        # bfloat16 shards survive the dtype header roundtrip
        import ml_dtypes

        c = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
        srv.register(43, [c])
        np.testing.assert_array_equal(pull_shards(srv.address(), 43)[0], c)

        with pytest.raises(KeyError):
            pull_shards(srv.address(), 999)
        srv.unregister(42)
        with pytest.raises(KeyError):
            pull_shards(srv.address(), 42)
    finally:
        srv.close()
