"""Direct unit coverage for the AdmitRequest plugins
(requestcontrol/admitters.py).

LatencySloAdmitter: the full cold/idle/valid prediction decision matrix,
including every fail-open rule. ProbabilisticAdmitter: the saturation →
P(reject) curve measured with a seeded RNG (deterministic — the same knob
`make test-chaos` pins via CHAOS_SEED)."""

import asyncio
import itertools
import random

import pytest

from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    Objectives,
)
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    LATENCY_ATTRIBUTE_KEY,
    LatencyPredictionInfo,
)
from llm_d_inference_scheduler_tpu.router.requestcontrol.admitters import (
    LatencySloAdmitter,
    ProbabilisticAdmitter,
)


def _ep(port, *, kv=0.5, running=2, queue=0, info=None) -> Endpoint:
    ep = Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1",
                                   port=port))
    ep.metrics.kv_cache_usage_percent = kv
    ep.metrics.running_requests_size = running
    ep.metrics.waiting_queue_size = queue
    if info is not None:
        ep.attributes.put(LATENCY_ATTRIBUTE_KEY, info)
    return ep


def _req(priority=-1, headers=None) -> InferenceRequest:
    return InferenceRequest(
        request_id="r", target_model="m",
        body=InferenceRequestBody(completions={"prompt": "x"}),
        headers=headers if headers is not None else {"x-slo-ttft-ms": "100"},
        objectives=Objectives(priority=priority))


def _info(valid: bool) -> LatencyPredictionInfo:
    h = 10.0 if valid else -10.0
    return LatencyPredictionInfo(ttft_ms=50, tpot_ms=2,
                                 ttft_headroom_ms=h, tpot_headroom_ms=h,
                                 ttft_valid=valid, tpot_valid=valid)


def _admit(adm, req, eps):
    return asyncio.run(adm.admit(None, req, eps))


# ---- LatencySloAdmitter: the full decision matrix ----------------------


def test_latency_slo_admitter_matrix():
    """Reject ONLY when all of (sheddable, SLO set, predictions exist, no
    valid, no idle, no cold) hold — every other combination admits."""
    adm = LatencySloAdmitter()
    for has_valid, has_idle, has_cold in itertools.product(
            (False, True), repeat=3):
        eps = [
            # Busy warm endpoint carrying the (in)valid prediction.
            _ep(1, kv=0.5, running=2, info=_info(has_valid)),
            # Optional idle endpoint (warm, invalid prediction).
            _ep(2, kv=0.5, running=0 if has_idle else 3, info=_info(False)),
            # Optional cold endpoint (KV below the 2% threshold).
            _ep(3, kv=0.001 if has_cold else 0.5, running=4,
                info=_info(False)),
        ]
        ok, reason = _admit(adm, _req(-1), eps)
        expect = has_valid or has_idle or has_cold
        assert ok is expect, (has_valid, has_idle, has_cold, reason)
        if not ok:
            assert "SLO" in reason


def test_latency_slo_admitter_fail_open_rules():
    adm = LatencySloAdmitter()
    hopeless = [_ep(1, kv=0.5, running=2, info=_info(False))]
    # 1. Non-sheddable (priority >= 0): never rejected.
    assert _admit(adm, _req(0), hopeless)[0]
    assert _admit(adm, _req(10), hopeless)[0]
    # 2. No SLO header on either axis: admitted.
    assert _admit(adm, _req(-1, headers={}), hopeless)[0]
    # TPOT-only SLO still arms the check.
    ok, _ = _admit(adm, _req(-1, headers={"x-slo-tpot-ms": "5"}), hopeless)
    assert not ok
    # 3. No endpoint carries a prediction attribute at all: fail open.
    bare = [_ep(1, kv=0.5, running=2), _ep(2, kv=0.6, running=1)]
    assert _admit(adm, _req(-1), bare)[0]
    # 4. A single valid prediction anywhere admits, even beside invalid.
    mixed = [_ep(1, kv=0.5, running=2, info=_info(False)),
             _ep(2, kv=0.5, running=1, info=_info(True))]
    assert _admit(adm, _req(-1), mixed)[0]


def test_latency_slo_admitter_cold_threshold_boundary():
    adm = LatencySloAdmitter()
    # KV exactly at the threshold is NOT cold (strict <); just below is.
    at = [_ep(1, kv=LatencySloAdmitter.COLD_KV_THRESHOLD, running=2,
              info=_info(False))]
    below = [_ep(1, kv=LatencySloAdmitter.COLD_KV_THRESHOLD - 1e-6,
                 running=2, info=_info(False))]
    assert not _admit(adm, _req(-1), at)[0]
    assert _admit(adm, _req(-1), below)[0]


# ---- ProbabilisticAdmitter: seeded saturation curve --------------------


def _sat_pool(sat: float) -> list[Endpoint]:
    """One endpoint whose KV utilization alone produces the target
    saturation (kv/threshold with the default kvCacheUtilThreshold=0.8 —
    continuous, unlike the integer queue depth)."""
    return [_ep(1, kv=sat * 0.8, queue=0)]


def test_probabilistic_admitter_seed_param_is_deterministic():
    a, b = ProbabilisticAdmitter(), ProbabilisticAdmitter()
    a.configure({"seed": 1234}, None)
    b.configure({"seed": 1234}, None)
    eps = _sat_pool(0.25)
    seq_a = [_admit(a, _req(-1), eps)[0] for _ in range(64)]
    seq_b = [_admit(b, _req(-1), eps)[0] for _ in range(64)]
    assert seq_a == seq_b
    assert False in seq_a  # P(reject) at 0.25 saturation ≈ 0.29: both seen
    assert True in seq_a


def test_probabilistic_admitter_chaos_seed_env(monkeypatch):
    monkeypatch.setenv("CHAOS_SEED", "11")
    a, b = ProbabilisticAdmitter(), ProbabilisticAdmitter()
    eps = _sat_pool(0.25)
    assert ([_admit(a, _req(-1), eps)[0] for _ in range(64)]
            == [_admit(b, _req(-1), eps)[0] for _ in range(64)])


def test_probabilistic_admitter_saturation_reject_curve():
    """P(reject) = min(sat^5 * 300, 1): ~0 well below saturation, steeply
    rising through the 0.2-0.32 knee, certain from ~0.32 up. Measured with
    a seeded RNG so the observed frequencies are reproducible."""
    adm = ProbabilisticAdmitter()
    adm.configure({"seed": 7}, None)
    n = 400
    freq = {}
    for sat in (0.1, 0.2, 0.25, 0.3, 1.0):
        eps = _sat_pool(sat)
        rejected = sum(1 - _admit(adm, _req(-1), eps)[0] for _ in range(n))
        freq[sat] = rejected / n
        expected = min(sat ** 5 * 300, 1.0)
        assert freq[sat] == pytest.approx(expected, abs=0.08), (sat, freq)
    # Monotone in saturation.
    assert freq[0.1] < freq[0.25] < freq[0.3] <= freq[1.0] == 1.0
    # Non-sheddable traffic is never probabilistically shed, even saturated.
    assert _admit(adm, _req(0), _sat_pool(2.0))[0]


def test_probabilistic_admitter_unseeded_default_still_works():
    """Without seed/CHAOS_SEED the RNG is unseeded (production default):
    behavior is still correct, just not reproducible."""
    import os

    assert "CHAOS_SEED" not in os.environ or os.environ["CHAOS_SEED"]
    adm = ProbabilisticAdmitter()
    assert isinstance(adm._rng, random.Random)
    assert _admit(adm, _req(-1), _sat_pool(0.0))[0]  # zero saturation admits
    ok, reason = _admit(adm, _req(-1), _sat_pool(3.0))  # P(reject)=1
    assert not ok and "saturation" in reason
