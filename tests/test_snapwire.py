"""Binary zero-copy snapshot wire (ISSUE 19): router/snapwire.py framing,
the AttrSanitizer probe cache, corrupt-frame robustness (counted and
skipped, never a subscriber crash), direct column install on the follower
datastore, delta base-matching, promotion-time materialization, and the
publisher's delta-eligibility logic — plus an end-to-end binary
publisher→subscriber round trip with a corrupt frame injected mid-stream.
"""

import asyncio
import pickle
import threading

import numpy as np
import pytest

from llm_d_inference_scheduler_tpu.router import snapwire
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.fleet import (
    _FRAME_LEN,
    SnapshotPublisher,
    SnapshotSubscriber,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.metrics import SNAPSHOT_FRAME_ERRORS
from llm_d_inference_scheduler_tpu.router.snapshot import (
    NUMERIC_FIELDS,
    ColumnMetrics,
)


def run(coro):
    return asyncio.run(coro)


def mk_leader(n=4, epoch_bump=0):
    ds = Datastore()
    ds.SNAPSHOT_MIN_REFRESH_S = 0.0  # tests re-snapshot immediately
    for i in range(n):
        meta = EndpointMetadata(
            name=f"pod-{i}", address=f"10.1.0.{i}", port=8000 + i,
            namespace="infer", metrics_port=9090 if i % 2 else None,
            labels={"llm-d.ai/role": "decode", "zone": f"z{i % 2}"})
        ds.endpoint_add_or_update(meta)
        ep = ds.endpoint_get(meta.address_port)
        ep.metrics.waiting_queue_size = i * 3
        ep.metrics.kv_cache_usage_percent = i / 10.0
        ep.metrics.running_requests_size = i
        ep.attributes.put("warm", True)
        ep.attributes.put("tier", i)
    for _ in range(epoch_bump):
        ds.mark_snapshot_dirty()
        ds.snapshot()  # mint an epoch per bump
    return ds


def encode_snapshot(snap):
    cols = snap.columns()
    blob = snapwire.AttrSanitizer().blob(cols.attrs, cols.models)
    return cols, snapwire.encode_full(snap.epoch, cols, blob)


# ---- framing round trips --------------------------------------------------


def test_full_frame_round_trip():
    snap = mk_leader().snapshot()
    cols, frame = encode_snapshot(snap)
    kind, epoch, got = snapwire.decode(frame)
    assert kind == "full" and epoch == snap.epoch
    assert got.n == cols.n and got.base_id == snap.epoch
    assert list(got.keys) == list(cols.keys)
    for f in NUMERIC_FIELDS:
        np.testing.assert_array_equal(got.num[f], cols.num[f])
    np.testing.assert_array_equal(got.role_code, cols.role_code)
    np.testing.assert_array_equal(got.draining, cols.draining)
    for a, b in zip(got.metas, cols.metas):
        assert (a.name, a.address, a.port, a.namespace, a.metrics_port,
                a.scheme, a.labels) == (b.name, b.address, b.port,
                                        b.namespace, b.metrics_port,
                                        b.scheme, b.labels)
    assert got.attrs == cols.attrs and got.models == cols.models
    # Zero-copy contract: decoded numeric columns are read-only views over
    # the frame buffer, not copies.
    assert not got.num[NUMERIC_FIELDS[0]].flags.writeable


def test_full_frame_handles_nan_and_none_metrics_port():
    ds = mk_leader(n=3)
    ep = ds.endpoint_get("10.1.0.0:8000")
    ep.metrics.kv_cache_usage_percent = float("nan")
    ds.mark_snapshot_dirty()
    snap = ds.snapshot()
    cols, frame = encode_snapshot(snap)
    _, _, got = snapwire.decode(frame)
    np.testing.assert_array_equal(
        got.num["kv_cache_usage_percent"], cols.num["kv_cache_usage_percent"])
    assert got.metas[0].metrics_port is None
    assert got.metas[1].metrics_port == 9090


def test_delta_frame_round_trip():
    snap = mk_leader().snapshot()
    cols = snap.columns()
    frame = snapwire.encode_delta(snap.epoch + 1, snap.epoch, cols.num)
    kind, epoch, base_id, num = snapwire.decode(frame)
    assert kind == "delta"
    assert epoch == snap.epoch + 1 and base_id == snap.epoch
    assert set(num) == set(NUMERIC_FIELDS)
    for f in NUMERIC_FIELDS:
        np.testing.assert_array_equal(num[f], cols.num[f])
    # Delta is the steady-state frame: numeric columns only, far smaller
    # than the full frame with its string table and attr blob.
    _, full = encode_snapshot(snap)
    assert len(frame) < len(full)


# ---- corruption: every reason, always FrameError --------------------------


def _corrupt(frame, reason):
    buf = bytearray(frame)
    if reason == "truncated":
        return bytes(buf[:20])  # shorter than the fixed header
    if reason == "truncated-body":
        return bytes(buf[:-7])  # header intact, payload short of its claim
    if reason == "version":
        buf[4] = snapwire.VERSION + 1
        return bytes(buf)
    if reason == "checksum":
        buf[-1] ^= 0xFF
        return bytes(buf)
    if reason == "malformed-kind":
        # Valid header + checksum, unknown frame kind.
        kind, epoch, _, num = ("x", 0, 0, None)
        body = frame[snapwire._HEADER.size:]
        return snapwire._pack_frame(9, 1, body)
    raise AssertionError(reason)


@pytest.mark.parametrize("mutation, reason", [
    ("truncated", "truncated"),
    ("truncated-body", "truncated"),
    ("version", "version"),
    ("checksum", "checksum"),
    ("malformed-kind", "malformed"),
])
def test_corrupt_frames_raise_typed_frame_error(mutation, reason):
    snap = mk_leader().snapshot()
    _, frame = encode_snapshot(snap)
    with pytest.raises(snapwire.FrameError) as ei:
        snapwire.decode(_corrupt(frame, mutation))
    assert ei.value.reason == reason


def test_garbage_payload_inside_valid_envelope_is_malformed():
    # Checksum passes (it covers whatever bytes are there) but the payload
    # doesn't parse: decode must degrade to FrameError, not raise raw
    # struct/pickle errors at the subscriber.
    frame = snapwire._pack_frame(snapwire.KIND_FULL, 7, b"\x00" * 11)
    with pytest.raises(snapwire.FrameError) as ei:
        snapwire.decode(frame)
    assert ei.value.reason == "malformed"


# ---- attr sanitizer probe cache -------------------------------------------


def test_sanitizer_drops_unpicklable_and_caches_verdicts():
    san = snapwire.AttrSanitizer()
    lock = threading.Lock()
    attrs = [{"warm": True, "lock": lock}, {"warm": False}]
    models = [("m",), ("m",)]
    blob = san.blob(attrs, models)
    got_attrs, got_models = pickle.loads(blob)
    assert got_attrs == [{"warm": True}, {"warm": False}]
    assert got_models == models
    # Verdicts memoized by (key, id(value)): steady-state frames skip the
    # probe pass entirely.
    assert san.probe("lock", lock) is False
    assert ("lock", id(lock)) in san._verdicts
    assert san._verdicts[("lock", id(lock))] is False
    assert san.probe("warm", True) is True


# ---- follower datastore: direct column install ----------------------------


def test_apply_remote_columns_and_delta():
    snap = mk_leader().snapshot()
    cols, frame = encode_snapshot(snap)
    _, epoch, got = snapwire.decode(frame)
    follower = Datastore()
    follower.apply_remote_columns(epoch, got)
    assert follower.snapshot().epoch == epoch
    ep = follower.endpoint_get("10.1.0.2:8002")
    assert ep is not None
    assert isinstance(ep.metrics, ColumnMetrics)
    assert ep.metrics.waiting_queue_size == 6
    assert ep.attributes.get("warm") is True and ep.attributes.get("tier") == 2

    # Metrics-only delta: live endpoint proxies see the new values through
    # one columns-pointer swap — no per-endpoint re-marshal.
    num = {f: snap.columns().num[f].copy() for f in NUMERIC_FIELDS}
    num["waiting_queue_size"] = num["waiting_queue_size"] + 100
    dframe = snapwire.encode_delta(epoch + 1, epoch, num)
    _, depoch, base_id, dnum = snapwire.decode(dframe)
    assert follower.apply_remote_delta(depoch, base_id, dnum) is True
    assert follower.snapshot().epoch == depoch
    assert ep.metrics.waiting_queue_size == 106  # same proxy object

    # A delta whose base is NOT the installed columns is dropped (False):
    # the next full frame re-anchors.
    assert follower.apply_remote_delta(depoch + 1, base_id + 999, dnum) is False
    assert follower.snapshot().epoch == depoch

    # A pickle-path snapshot clears the columns anchor: deltas no longer
    # apply until the next binary full frame.
    follower.apply_remote_snapshot(
        depoch + 1, [(e.metadata, e.metrics, dict(e.attributes._data))
                     for e in mk_leader(n=4).snapshot().view()])
    assert follower.apply_remote_delta(depoch + 2, epoch, dnum) is False


def test_resume_local_snapshots_materializes_column_metrics():
    snap = mk_leader().snapshot()
    _, frame = encode_snapshot(snap)
    _, epoch, got = snapwire.decode(frame)
    follower = Datastore()
    follower.apply_remote_columns(epoch, got)
    ep = follower.endpoint_get("10.1.0.1:8001")
    assert isinstance(ep.metrics, ColumnMetrics)
    # Promotion to leader: column-backed proxies must become plain mutable
    # Metrics so local scrape collectors can write in place (the decoded
    # arrays are read-only frame views).
    follower.resume_local_snapshots()
    assert not isinstance(ep.metrics, ColumnMetrics)
    before = ep.metrics.waiting_queue_size
    ep.metrics.waiting_queue_size = before + 1
    assert ep.metrics.waiting_queue_size == before + 1
    assert follower._columns_ref is None


# ---- subscriber robustness: count + skip, never crash ---------------------


def _frame_errors(reason):
    return SNAPSHOT_FRAME_ERRORS.labels(reason=reason)._value.get()


def test_subscriber_counts_and_skips_corrupt_frames():
    snap = mk_leader().snapshot()
    _, frame = encode_snapshot(snap)
    follower = Datastore()
    sub = SnapshotSubscriber(follower, "/nonexistent")
    for mutation, reason in [("truncated", "truncated"),
                             ("checksum", "checksum"),
                             ("version", "version"),
                             ("malformed-kind", "malformed")]:
        before = _frame_errors(reason)
        sub._handle_binary(_corrupt(frame, mutation))
        assert _frame_errors(reason) == before + 1, reason
        assert sub.applied_epoch == 0  # nothing applied
        assert follower.endpoint_get("10.1.0.0:8000") is None
    # The very next good frame still applies — the subscriber survived.
    sub._handle_binary(frame)
    assert sub.applied_epoch == snap.epoch
    assert follower.endpoint_get("10.1.0.0:8000") is not None


# ---- publisher: delta eligibility + wire selection ------------------------


def _inner_kind(frame):
    inner = frame[_FRAME_LEN.size:]
    assert inner[:4] == snapwire.MAGIC
    return inner[5]


def test_publisher_delta_eligibility(tmp_path):
    ds = mk_leader()
    pub = SnapshotPublisher(ds, str(tmp_path / "s.sock"))
    f1 = pub._encode_snapshot(ds.snapshot())
    assert _inner_kind(f1) == snapwire.KIND_FULL

    # Metrics-only change → delta riding the cached full frame.
    ds.endpoint_get("10.1.0.0:8000").metrics.waiting_queue_size = 99
    ds.mark_snapshot_dirty()
    f2 = pub._encode_snapshot(ds.snapshot())
    assert _inner_kind(f2) == snapwire.KIND_DELTA
    assert pub._delta_frame == f2 and pub._frame == f1

    # Attr change breaks blob equality → full again.
    ds.endpoint_get("10.1.0.0:8000").attributes.put("tier", 77)
    ds.mark_snapshot_dirty()
    f3 = pub._encode_snapshot(ds.snapshot())
    assert _inner_kind(f3) == snapwire.KIND_FULL
    assert pub._delta_frame is None

    # Membership change → full.
    ds.endpoint_add_or_update(EndpointMetadata(
        name="new", address="10.1.0.9", port=8009))
    f4 = pub._encode_snapshot(ds.snapshot())
    assert _inner_kind(f4) == snapwire.KIND_FULL


def test_publisher_pickle_wire_opt_out(tmp_path):
    ds = mk_leader()
    pub = SnapshotPublisher(ds, str(tmp_path / "s.sock"), wire="pickle")
    frame = pub._encode_snapshot(ds.snapshot())
    inner = frame[_FRAME_LEN.size:]
    assert not snapwire.is_binary_frame(inner)
    kind, epoch, entries = pickle.loads(inner)
    assert kind == "snap" and epoch == ds.snapshot().epoch
    assert len(entries) == 4


# ---- end-to-end over a unix socket ----------------------------------------


def test_binary_ipc_end_to_end(tmp_path):
    async def body():
        path = str(tmp_path / "snap.sock")
        leader, follower = mk_leader(), Datastore()
        pub = SnapshotPublisher(leader, path, interval_s=0.01)
        await pub.start()
        sub = SnapshotSubscriber(follower, path, retry_s=0.02)
        sub.start()
        try:
            for _ in range(300):
                if follower.endpoint_get("10.1.0.3:8003") is not None:
                    break
                await asyncio.sleep(0.01)
            fep = follower.endpoint_get("10.1.0.3:8003")
            assert fep is not None and fep.metrics.waiting_queue_size == 9
            assert fep.attributes.get("warm") is True
            assert follower.snapshot().epoch == leader.snapshot().epoch
            # Metrics-only scrape → delta frame updates the same proxies.
            leader.endpoint_get("10.1.0.3:8003").metrics.waiting_queue_size = 42
            leader.mark_snapshot_dirty()
            for _ in range(300):
                if fep.metrics.waiting_queue_size == 42:
                    break
                await asyncio.sleep(0.01)
            assert fep.metrics.waiting_queue_size == 42
            # Membership deletion → full frame drops the endpoint.
            leader.endpoint_delete("10.1.0.0:8000")
            for _ in range(300):
                if follower.endpoint_get("10.1.0.0:8000") is None:
                    break
                await asyncio.sleep(0.01)
            assert follower.endpoint_get("10.1.0.0:8000") is None
        finally:
            await sub.stop()
            await pub.stop()

    run(body())


def test_subscriber_survives_corrupt_frame_mid_stream(tmp_path):
    """A hand-rolled publisher sends good → corrupt → newer-epoch good over
    one connection: the subscriber must count + skip the corrupt frame and
    apply the follow-up, without ever reconnecting or crashing."""

    async def body():
        path = str(tmp_path / "snap.sock")
        snap1 = mk_leader().snapshot()
        leader2 = mk_leader(epoch_bump=3)
        leader2.endpoint_get("10.1.0.1:8001").metrics.waiting_queue_size = 77
        leader2.mark_snapshot_dirty()
        snap2 = leader2.snapshot()
        assert snap2.epoch > snap1.epoch
        _, good1 = encode_snapshot(snap1)
        _, good2 = encode_snapshot(snap2)
        bad = _corrupt(good1, "checksum")
        conns = []

        async def on_client(reader, writer):
            conns.append(writer)
            for inner in (good1, bad, good2):
                writer.write(_FRAME_LEN.pack(len(inner)) + inner)
            await writer.drain()

        server = await asyncio.start_unix_server(on_client, path=path)
        follower = Datastore()
        sub = SnapshotSubscriber(follower, path, retry_s=0.02)
        before = _frame_errors("checksum")
        sub.start()
        try:
            for _ in range(300):
                if sub.applied_epoch == snap2.epoch:
                    break
                await asyncio.sleep(0.01)
            assert sub.applied_epoch == snap2.epoch
            assert (follower.endpoint_get("10.1.0.1:8001")
                    .metrics.waiting_queue_size) == 77
            assert _frame_errors("checksum") == before + 1
            # One connection: the corrupt frame caused a skip, NOT a
            # reconnect (the length prefix already re-aligned the stream).
            assert len(conns) == 1
        finally:
            await sub.stop()
            server.close()
            await server.wait_closed()

    run(body())
