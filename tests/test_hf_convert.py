"""HF checkpoint conversion: logits parity vs transformers + tokenizer registry.

The strongest correctness evidence the engine half can have: our stacked-layer
JAX forward must reproduce a real HuggingFace Llama/Mixtral's logits from the
converted weights (RoPE convention, GQA, SwiGLU, RMSNorm eps all verified at
once). Reference behavior analogue: the reference router serves whatever vLLM
loaded from the same HF checkpoints (SURVEY.md preamble).
"""

from __future__ import annotations

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
torch = pytest.importorskip("torch")

from llm_d_inference_scheduler_tpu.models import llama
from llm_d_inference_scheduler_tpu.models.convert_hf import (
    config_from_hf,
    convert_state_dict,
)


def _parity(hf_model, hf_cfg, tokens_np, atol=2e-4):
    cfg = config_from_hf(hf_cfg)
    params = convert_state_dict(hf_model.state_dict(), cfg, dtype="float32")

    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens_np)).logits.float().numpy()

    ours, _ = llama.forward(params, cfg, jnp.asarray(tokens_np))
    ours = np.asarray(ours)

    assert ours.shape == ref.shape
    # Normalize scale: compare log-softmax (absolute logit offsets are
    # irrelevant to sampling and can differ by accumulation order).
    def lsm(x):
        x = x - x.max(axis=-1, keepdims=True)
        return x - np.log(np.exp(x).sum(axis=-1, keepdims=True))

    np.testing.assert_allclose(lsm(ours), lsm(ref), atol=atol, rtol=0)


def test_llama_logits_parity():
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(0)
    hf_cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10_000.0, max_position_embeddings=128,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
    )
    model = LlamaForCausalLM(hf_cfg).eval().float()
    tokens = np.random.default_rng(0).integers(0, 256, size=(2, 9), dtype=np.int64)
    _parity(model, hf_cfg, tokens)


def test_llama_tied_embeddings():
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(1)
    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        rms_norm_eps=1e-6, rope_theta=10_000.0, tie_word_embeddings=True,
        attention_bias=False, mlp_bias=False,
    )
    model = LlamaForCausalLM(hf_cfg).eval().float()
    sd = {k: v for k, v in model.state_dict().items() if k != "lm_head.weight"}
    cfg = config_from_hf(hf_cfg)
    params = convert_state_dict(sd, cfg, dtype="float32")
    # Tied head == embed transpose.
    np.testing.assert_allclose(np.asarray(params["lm_head"]),
                               np.asarray(params["embed"]).T)
    tokens = np.random.default_rng(1).integers(0, 128, size=(1, 5), dtype=np.int64)
    _parity(model, hf_cfg, tokens)


def test_mixtral_logits_parity():
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(2)
    hf_cfg = MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        rms_norm_eps=1e-5, rope_theta=10_000.0, tie_word_embeddings=False,
    )
    model = MixtralForCausalLM(hf_cfg).eval().float()
    cfg = config_from_hf(hf_cfg)
    assert cfg.n_experts == 4 and cfg.experts_per_token == 2
    tokens = np.random.default_rng(2).integers(0, 128, size=(2, 7), dtype=np.int64)
    _parity(model, hf_cfg, tokens, atol=5e-4)


def test_convert_cli_roundtrip(tmp_path):
    """CLI writes an Orbax checkpoint the engine's loader restores."""
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(3)
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=16, intermediate_size=32,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=1,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
    )
    src = tmp_path / "hf"
    LlamaForCausalLM(hf_cfg).eval().save_pretrained(src, safe_serialization=True)

    from llm_d_inference_scheduler_tpu.models.convert_hf import main

    out = tmp_path / "orbax"
    main([str(src), str(out), "--dtype", "float32"])

    import json

    mc = json.loads((out / "model_config.json").read_text())
    assert mc["d_model"] == 16 and mc["n_layers"] == 1

    from llm_d_inference_scheduler_tpu.engine.checkpoint import load_params
    from llm_d_inference_scheduler_tpu.models.configs import ModelConfig

    cfg = ModelConfig(**{k: v for k, v in mc.items()})
    params = load_params(str(out), cfg)
    assert params["embed"].shape == (64, 16)


def test_engine_serves_converted_checkpoint(tmp_path):
    """Greedy decode through the full engine (paged KV, chunked decode)
    matches HF generate on a converted checkpoint — token-exact."""
    import asyncio

    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(4)
    hf_cfg = LlamaConfig(
        vocab_size=300, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False, attention_bias=False, mlp_bias=False,
        rope_theta=10_000.0,
    )
    model = LlamaForCausalLM(hf_cfg).eval().float()
    src = tmp_path / "hf"
    model.save_pretrained(src, safe_serialization=True)

    from llm_d_inference_scheduler_tpu.models.convert_hf import main

    out = tmp_path / "orbax"
    main([str(src), str(out), "--dtype", "float32"])

    prompt = [5, 17, 42, 99, 7]
    n_gen = 6
    with torch.no_grad():
        ref = model.generate(
            torch.tensor([prompt]), max_new_tokens=n_gen, do_sample=False,
            pad_token_id=0)[0, len(prompt):].tolist()

    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    cfg = EngineConfig(model=str(out), backend="tpu", max_batch=2,
                       max_model_len=64, decode_chunk=4)
    assert cfg.checkpoint_path == ""  # discovered by the engine, not preset

    async def run():
        eng = TpuEngine(cfg)
        assert eng.cfg.checkpoint_path == str(out)
        await eng.start()
        try:
            req = EngineRequest(request_id="hf-e2e", prompt_token_ids=prompt,
                                max_tokens=n_gen, temperature=0.0,
                                ignore_eos=True)
            outq = eng.submit(req)
            got = []
            while True:
                ev = await outq.get()
                if ev.token_id is not None:
                    got.append(ev.token_id)
                if ev.finish_reason is not None:
                    break
            return got
        finally:
            await eng.stop()

    got = asyncio.run(run())
    assert got == ref


def test_hf_tokenizer_registry(tmp_path):
    """A saved HF fast tokenizer loads via get_tokenizer and round-trips."""
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    from tokenizers.trainers import BpeTrainer

    trainer = BpeTrainer(
        vocab_size=300, special_tokens=["<s>", "</s>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet())
    tok.train_from_iterator(
        ["hello world", "hello there", "the quick brown fox"], trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok, bos_token="<s>", eos_token="</s>")
    d = tmp_path / "tok"
    fast.save_pretrained(d)

    from llm_d_inference_scheduler_tpu.engine.tokenizer import get_tokenizer

    t = get_tokenizer(f"hf:{d}", vocab_size=1024)
    assert t.eos_id is not None
    ids = t.encode("hello world", add_bos=True)
    assert ids[0] == t.bos_id
    assert t.decode(ids) == "hello world"

    # Vocab larger than the model's is rejected.
    with pytest.raises(ValueError):
        get_tokenizer(f"hf:{d}", vocab_size=10)


def test_qwen3_logits_parity():
    """Qwen3 family: per-head QK-norm + explicit head_dim (the reference's
    own benchmark harness targets Qwen/Qwen3-32B —
    config/manifests/benchmark/benchmark.yaml:19-47)."""
    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(5)
    hf_cfg = Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=24,  # decoupled from hidden/heads = 16
        rms_norm_eps=1e-6, rope_theta=10_000.0, max_position_embeddings=128,
        tie_word_embeddings=False, attention_bias=False,
    )
    model = Qwen3ForCausalLM(hf_cfg).eval().float()
    cfg = config_from_hf(hf_cfg)
    assert cfg.qk_norm and cfg.head_dim == 24
    tokens = np.random.default_rng(2).integers(0, 256, size=(2, 7), dtype=np.int64)
    _parity(model, hf_cfg, tokens)


def test_qwen3_engine_serves_token_exact(tmp_path):
    """Greedy decode through the full engine (paged KV, QK-norm in the
    decode-step scan) matches HF generate on a converted Qwen3 checkpoint."""
    import asyncio

    from transformers import Qwen3Config, Qwen3ForCausalLM

    torch.manual_seed(6)
    hf_cfg = Qwen3Config(
        vocab_size=300, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-6, rope_theta=10_000.0,
        tie_word_embeddings=True,
    )
    model = Qwen3ForCausalLM(hf_cfg).eval().float()
    src = tmp_path / "hf"
    model.save_pretrained(src, safe_serialization=True)

    from llm_d_inference_scheduler_tpu.models.convert_hf import main

    out = tmp_path / "orbax"
    main([str(src), str(out), "--dtype", "float32"])

    prompt = [5, 17, 42, 99, 7, 211]
    n_gen = 6
    with torch.no_grad():
        ref = model.generate(
            torch.tensor([prompt]), max_new_tokens=n_gen, do_sample=False,
            pad_token_id=0)[0, len(prompt):].tolist()

    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    cfg = EngineConfig(model=str(out), backend="tpu", max_batch=2,
                       max_model_len=64, decode_chunk=4)

    async def run():
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            outq = eng.submit(EngineRequest(
                request_id="qwen-e2e", prompt_token_ids=prompt,
                max_tokens=n_gen, temperature=0.0, ignore_eos=True))
            got = []
            while True:
                ev = await outq.get()
                if ev.token_id is not None:
                    got.append(ev.token_id)
                if ev.finish_reason is not None:
                    break
            return got
        finally:
            await eng.stop()

    assert asyncio.run(run()) == ref
