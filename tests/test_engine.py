"""Engine tests: continuous batching core, HTTP surface, telemetry, P/D handoff."""

import asyncio
import json

import httpx
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
from llm_d_inference_scheduler_tpu.engine.server import EngineServer


def run(coro):
    return asyncio.run(coro)


def _cfg(backend, port, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(backend=backend, port=port, **kw)


# ---------- TpuEngine core (runs on CPU backend via conftest) ----------

def test_tpu_engine_generates_and_batches():
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0))
        await eng.start()
        try:
            reqs = [EngineRequest(request_id=f"r{i}", prompt_token_ids=[1] + [10 + i] * 5,
                                  max_tokens=6) for i in range(3)]
            outs = [eng.submit(r) for r in reqs]

            async def drain(out):
                evs = []
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=30)
                    evs.append(ev)
                    if ev.finish_reason is not None:
                        return evs

            results = await asyncio.gather(*[drain(o) for o in outs])
            for r, evs in zip(reqs, results):
                toks = [e.token_id for e in evs if e.token_id is not None]
                assert 1 <= len(toks) <= r.max_tokens
                assert evs[-1].finish_reason is not None
            # all blocks returned
            assert eng.allocator.free_blocks == eng.n_blocks - 1
        finally:
            await eng.stop()

    run(body())


def test_tpu_engine_greedy_matches_across_batching():
    """The same prompt decoded alone and alongside others yields the same tokens
    (continuous batching must not change results; greedy, f32-tolerant)."""
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0))
        await eng.start()
        try:
            prompt = [1] + [42, 17, 9] * 3

            async def gen(rid, prompt):
                out = eng.submit(EngineRequest(request_id=rid, prompt_token_ids=prompt,
                                               max_tokens=5))
                toks = []
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=30)
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                    if ev.finish_reason is not None:
                        return toks

            solo = await gen("solo", prompt)
            batched = await asyncio.gather(
                gen("a", prompt), gen("b", [1, 99, 98, 97]), gen("c", prompt))
            assert batched[0] == solo and batched[2] == solo
        finally:
            await eng.stop()

    run(body())


# ---------- HTTP surface (sim backend) ----------

def test_sim_server_openai_surface():
    async def body():
        cfg = _cfg("sim", 18301)
        server = EngineServer(cfg)
        await server.start()
        try:
            async with httpx.AsyncClient(base_url="http://127.0.0.1:18301") as c:
                r = await c.post("/v1/completions",
                                 json={"model": "tiny", "prompt": "hello", "max_tokens": 4})
                assert r.status_code == 200
                body_ = r.json()
                assert body_["choices"][0]["finish_reason"] == "length"
                assert body_["usage"]["completion_tokens"] == 4

                r = await c.post("/v1/chat/completions", json={
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "hi"}], "max_tokens": 3})
                assert r.json()["choices"][0]["message"]["role"] == "assistant"

                r = await c.get("/v1/models")
                assert r.json()["data"][0]["id"] == "tiny"

                r = await c.post("/v1/completions/render", json={"prompt": "abc"})
                assert len(r.json()["token_ids"]) == 4  # BOS + 3 bytes

                r = await c.get("/metrics")
                text = r.text
                for name in ("jetstream:num_requests_waiting",
                             "jetstream:num_requests_running",
                             "jetstream:kv_cache_usage_perc",
                             "jetstream:cache_config_info",
                             "jetstream:lora_requests_info"):
                    assert name in text, f"missing metric {name}"

                # streaming
                async with c.stream("POST", "/v1/completions",
                                    json={"prompt": "s", "max_tokens": 3,
                                          "stream": True}) as r:
                    chunks = []
                    async for line in r.aiter_lines():
                        if line.startswith("data: "):
                            chunks.append(line[6:])
                    assert chunks[-1] == "[DONE]"
                    assert len(chunks) >= 4  # 3 tokens + final + DONE
        finally:
            await server.stop()

    run(body())


# ---------- P/D KV handoff between two real engines ----------

def test_pd_handoff_between_tpu_engines():
    """Prefill on engine A with do_remote_decode, decode on engine B importing
    A's KV over HTTP; result must equal a monolithic decode on one engine."""
    async def body():
        prompt = [1] + [33, 44, 55] * 4
        max_tokens = 6

        mono = EngineServer(_cfg("tpu", 18311))
        await mono.start()
        try:
            async with httpx.AsyncClient() as c:
                r = await c.post("http://127.0.0.1:18311/v1/completions",
                                 json={"prompt": prompt, "max_tokens": max_tokens,
                                       "temperature": 0},
                                 timeout=60)
                mono_text = r.json()["choices"][0]["text"]
        finally:
            await mono.stop()

        pre = EngineServer(_cfg("tpu", 18312, role="prefill"))
        dec = EngineServer(_cfg("tpu", 18313, role="decode"))
        await pre.start()
        await dec.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r1 = await c.post("http://127.0.0.1:18312/v1/completions", json={
                    "prompt": prompt, "max_tokens": 1, "stream": False,
                    "temperature": 0,
                    "kv_transfer_params": {"do_remote_decode": True}})
                assert r1.status_code == 200
                ktp = r1.json()["kv_transfer_params"]
                assert ktp["remote_seq_len"] == len(prompt)

                r2 = await c.post("http://127.0.0.1:18313/v1/completions", json={
                    "prompt": prompt, "max_tokens": max_tokens,
                    "temperature": 0, "kv_transfer_params": ktp})
                assert r2.status_code == 200
                disagg_text = r2.json()["choices"][0]["text"]
                assert disagg_text == mono_text
                # export released after pull
                assert not pre.engine.kv_exports
        finally:
            await pre.stop()
            await dec.stop()

    run(body())


def test_engine_warmup_compiles_before_serving():
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0, warmup=True))
        assert eng.warming
        await eng.start()
        try:
            # warm-up must complete and not corrupt state: a normal request
            # works afterwards and all blocks stay accounted for.
            out = eng.submit(EngineRequest(request_id="w", prompt_token_ids=[1, 2, 3],
                                           max_tokens=2, ignore_eos=True))
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=120)
                if ev.finish_reason is not None:
                    break
            assert not eng.warming  # warm-up ran (serving happens after it)
            assert ev.finish_reason.value == "length"
            for _ in range(50):
                if eng.allocator.free_blocks == eng.n_blocks - 1:
                    break
                await asyncio.sleep(0.05)
            assert eng.allocator.free_blocks == eng.n_blocks - 1
        finally:
            await eng.stop()

    run(body())


def test_decode_ctx_buckets_token_parity():
    """Pow2 context-bucketed block tables (decode_ctx_buckets) must be
    token-identical to full-width tables, across mixed request lengths and
    a width drop when the long request finishes first."""
    import jax
    import jax.numpy as jnp

    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
    from llm_d_inference_scheduler_tpu.models import llama
    from llm_d_inference_scheduler_tpu.models.configs import get_config

    params = llama.init_params(get_config("tiny"), jax.random.key(11),
                               dtype=jnp.float32)

    async def serve(ctx_buckets: bool):
        eng = TpuEngine(EngineConfig(
            model="tiny", backend="tpu", max_batch=4, max_model_len=128,
            decode_chunk=4, seed=11, kv_events_port=0,
            enable_prefix_caching=False, decode_ctx_buckets=ctx_buckets),
            params=params)
        await eng.start()
        try:
            async def one(rid, n_prompt, n_gen):
                req = EngineRequest(
                    request_id=rid,
                    prompt_token_ids=[1] + [(i * 3) % 400 + 5
                                            for i in range(n_prompt - 1)],
                    max_tokens=n_gen, temperature=0.0, ignore_eos=True)
                out = eng.submit(req)
                toks = []
                while True:
                    ev = await out.get()
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                    if ev.finish_reason is not None:
                        return toks

            # short (3 blocks) + long (7 blocks) concurrently: W=8 while both
            # live, drops to 4 after the long one finishes first.
            long_t, short_t = await asyncio.gather(
                one("long", 100, 6), one("short", 40, 24))
            return long_t, short_t
        finally:
            await eng.stop()

    bucketed = asyncio.run(serve(True))
    full = asyncio.run(serve(False))
    assert bucketed == full
    assert len(bucketed[0]) == 6 and len(bucketed[1]) == 24


def test_batched_prefill_token_parity():
    """prefill_batch > 1: same-bucket plain prompts admitted together run
    as ONE [K, S] fused prefill (padded to K) — greedy tokens must match
    the per-prompt path exactly, including the prefix-cache-hit rerun
    (hits route back to the O(prefix) single path)."""
    import asyncio

    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    prompts = [[1] + [(i * 13 + j * 7) % 400 + 3 for j in range(40)]
               for i in range(6)]
    base = dict(model="tiny", backend="tpu", max_batch=8, max_model_len=64,
                decode_chunk=4, kv_events_port=0, seed=5)

    async def serve(cfg, tag, rounds=1):
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            async def one(rid, prompt):
                out = eng.submit(EngineRequest(
                    request_id=rid, prompt_token_ids=list(prompt),
                    max_tokens=5, temperature=0.0, ignore_eos=True))
                toks = []
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=120)
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                    if ev.finish_reason is not None:
                        return toks

            out = []
            for r in range(rounds):
                out.append(await asyncio.gather(
                    *[one(f"{tag}{r}-{i}", p) for i, p in enumerate(prompts)]))
            return out
        finally:
            await eng.stop()

    single = asyncio.run(serve(EngineConfig(**base), "s"))[0]
    cold, warm = asyncio.run(serve(
        EngineConfig(**base, prefill_batch=4), "b", rounds=2))
    assert cold == single
    assert warm == single  # prefix-cache hits take the single path


def test_batched_prefill_in_group_duplicates_share_prefix():
    """K identical prompts admitted in ONE group: the first prefills in the
    batch, the duplicates reroute to the prefix path AFTER the batch commits
    its hashes — same tokens, and the duplicates report cached tokens."""
    import asyncio

    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    prompt = [1] + [(j * 11) % 400 + 3 for j in range(40)]

    async def body():
        eng = TpuEngine(EngineConfig(model="tiny", backend="tpu", max_batch=8,
                                     max_model_len=64, decode_chunk=4,
                                     kv_events_port=0, seed=5,
                                     prefill_batch=4))
        await eng.start()
        try:
            async def one(rid):
                out = eng.submit(EngineRequest(
                    request_id=rid, prompt_token_ids=list(prompt),
                    max_tokens=4, temperature=0.0, ignore_eos=True))
                toks, cached = [], 0
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=120)
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                        cached = max(cached, ev.cached_tokens or 0)
                    if ev.finish_reason is not None:
                        return toks, cached

            results = await asyncio.gather(*[one(f"d{i}") for i in range(4)])
            toks = [t for t, _ in results]
            cached = [c for _, c in results]
            assert all(t == toks[0] for t in toks)
            # At least the rerouted duplicates hit the freshly-committed
            # prefix blocks (2 complete 16-token blocks of the 41-token
            # prompt).
            assert sum(1 for c in cached if c >= 32) >= 3
        finally:
            await eng.stop()

    asyncio.run(body())


def test_incremental_prefill_token_parity_and_no_stall():
    """prefill_chunk: a long prompt prefills in block-aligned windows, one
    per engine step, interleaved with other lanes. Greedy tokens must match
    whole-prompt prefill exactly; the warm rerun prefix-hits the deferred
    commit; and a short request admitted alongside a long one gets its
    first token BEFORE the long one (whole-prompt prefill would serve the
    long prompt's token first) — the observable no-stall property."""
    import asyncio
    import time as _time

    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    LONG = [1] + [(j * 17) % 450 + 3 for j in range(120)]
    SHORT = [1] + [(j * 5) % 450 + 3 for j in range(30)]
    base = dict(model="tiny", backend="tpu", max_batch=4, max_model_len=256,
                decode_chunk=4, kv_events_port=0, seed=7)

    async def serve(cfg):
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            first_at: dict[str, float] = {}

            async def one(rid, prompt, n):
                out = eng.submit(EngineRequest(
                    request_id=rid, prompt_token_ids=list(prompt),
                    max_tokens=n, temperature=0.0, ignore_eos=True))
                toks, cached = [], 0
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=180)
                    if ev.token_id is not None:
                        if rid not in first_at:
                            first_at[rid] = _time.monotonic()
                        toks.append(ev.token_id)
                        cached = max(cached, ev.cached_tokens or 0)
                    if ev.finish_reason is not None:
                        return toks, cached

            # LONG submitted first: whole-prompt prefill serves it first;
            # incremental prefill lets SHORT through between windows.
            (lt, _), (st, _) = await asyncio.gather(
                one("L", LONG, 6), one("S", SHORT, 12))
            return lt, st, first_at
        finally:
            await eng.stop()

    lt_w, st_w, order_w = asyncio.run(serve(EngineConfig(**base)))
    lt_c, st_c, order_c = asyncio.run(serve(
        EngineConfig(**base, prefill_chunk=32)))
    assert (lt_c, st_c) == (lt_w, st_w)
    assert order_w["L"] <= order_w["S"]   # whole prefill: long lands first
    assert order_c["S"] < order_c["L"]    # chunked: short slips through

    async def warm_rerun():
        # warmup=True also exercises the chunked-shape precompile ladder.
        eng = TpuEngine(EngineConfig(**base, prefill_chunk=32, warmup=True))
        await eng.start()
        try:
            async def one(rid):
                out = eng.submit(EngineRequest(
                    request_id=rid, prompt_token_ids=list(LONG),
                    max_tokens=6, temperature=0.0, ignore_eos=True))
                toks, cached = [], 0
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=180)
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                        cached = max(cached, ev.cached_tokens or 0)
                    if ev.finish_reason is not None:
                        return toks, cached

            a, _ = await one("a")
            b, cached = await one("b")
            return a, b, cached
        finally:
            await eng.stop()

    a, b, cached = asyncio.run(warm_rerun())
    assert a == b == lt_w
    assert cached >= 112  # 7 complete blocks committed by the chunked path



def test_note_kv_import_dedupes_eviction_ring():
    """A re-dispatched request id overwrites its kv_import_stats entry; the
    eviction ring must not gain a duplicate slot, or a later cap eviction
    pops the LIVE entry when the stale first occurrence reaches the front
    (the decode response then silently loses its x-kv-pull-ms stamp)."""
    import collections
    import time as _time

    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    class Stub:
        KV_IMPORT_STATS_CAP = TpuEngine.KV_IMPORT_STATS_CAP

    s = Stub()
    s.kv_import_stats = {}
    s._kv_import_order = collections.deque()
    for _ in range(3):
        TpuEngine._note_kv_import(s, "r1", _time.monotonic(), 10, "host")
    assert len(s._kv_import_order) == 1
    assert s.kv_import_stats["r1"]["bytes"] == 10
