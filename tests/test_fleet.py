"""Multi-process sharded gateway fleet (ISSUE 9, router/fleet.py).

Hermetic tiers: pure-function units (flow sharding, seeded picks, the
exposition/SLO mergers), the snapshot-IPC pub/sub loop in one process, the
fan-in admin plane against stub workers, and one real 2-worker fleet e2e
(spawned processes, hash balancer, snapshot IPC, sim engines).
"""

import asyncio
import json
import os
import sys

import httpx
import pytest
from aiohttp import web

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.fleet import (
    FleetAdmin,
    FleetConfig,
    SnapshotPublisher,
    SnapshotSubscriber,
    flow_shard,
    merge_expositions,
    merge_slo,
    merge_transfers,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    EndpointMetadata,
    Metrics,
)

GW, E1, E2 = 19070, 19071, 19072
ADMIN = 19080
STUB_A, STUB_B, STUB_ADMIN = 19060, 19061, 19062


def run(coro):
    return asyncio.run(coro)


# ---- flow sharding ------------------------------------------------------

def test_flow_shard_stable_and_disjoint():
    # Deterministic across calls (and, because it's xxh64 not hash(),
    # across processes — the property the balancer and bench rely on).
    assert flow_shard("flow-a", 4) == flow_shard("flow-a", 4)
    assert flow_shard("anything", 1) == 0
    # Every flow owned by exactly one shard; a 64-flow population touches
    # every shard of a 4-way fleet.
    owners = {f"flow-{i}": flow_shard(f"flow-{i}", 4) for i in range(64)}
    assert set(owners.values()) == {0, 1, 2, 3}
    assert all(0 <= s < 4 for s in owners.values())


# ---- seeded picker (scheduling.pickSeed satellite) ----------------------

def _scored(n=8, score=1.0):
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        ScoredEndpoint,
    )

    class _Ep:
        def __init__(self, i):
            self.metadata = EndpointMetadata(name=f"e{i}",
                                             address=f"10.0.0.{i}", port=80)

    return [ScoredEndpoint(_Ep(i), score) for i in range(n)]


def test_pick_seed_is_per_request_deterministic():
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        InferenceRequest,
        InferenceRequestBody,
    )
    from llm_d_inference_scheduler_tpu.router.plugins.pickers import (
        MaxScorePicker,
    )

    def req(rid):
        return InferenceRequest(request_id=rid, target_model="m",
                                body=InferenceRequestBody())

    a, b = MaxScorePicker("a"), MaxScorePicker("b")
    a.configure({"pickSeed": 7}, None)
    b.configure({"pickSeed": 7}, None)
    # All-tied scores: the pick is pure tie-break RNG. Same (seed,
    # request_id) must pick identically NO MATTER the draw order — picker b
    # burns draws on other requests first (the sharded-fleet situation:
    # each worker sees a different interleaving of the stream).
    for other in ("r-x", "r-y", "r-z"):
        b.pick(None, None, req(other), _scored())
    for rid in ("r-1", "r-2", "r-3"):
        pa = a.pick(None, None, req(rid), _scored())
        pb = b.pick(None, None, req(rid), _scored())
        assert [e.metadata.name for e in pa] == [e.metadata.name for e in pb]
    # Unseeded pickers keep the historical shared-RNG behavior (the
    # kill-switch: pick_seed defaults to None).
    assert MaxScorePicker("c").pick_seed is None


def test_pick_seed_flows_from_config():
    import llm_d_inference_scheduler_tpu.router.plugins  # noqa: F401
    from llm_d_inference_scheduler_tpu.router.config.loader import (
        Handle,
        load_config,
    )
    from llm_d_inference_scheduler_tpu.router.datalayer.runtime import (
        DataLayerRuntime,
    )

    ds = Datastore()
    cfg = load_config("scheduling: {pickSeed: 42}\n",
                      Handle(datastore=ds, dl_runtime=DataLayerRuntime(ds)))
    assert cfg.scheduler.profiles["default"].picker.pick_seed == 42
    # A per-picker pickSeed parameter wins over the profile-wide knob.
    ds2 = Datastore()
    cfg2 = load_config(
        "scheduling: {pickSeed: 42}\n"
        "plugins:\n"
        "  - {type: max-score-picker, parameters: {pickSeed: 9}}\n"
        "schedulingProfiles:\n"
        "  - name: default\n"
        "    plugins: [{pluginRef: max-score-picker}]\n",
        Handle(datastore=ds2, dl_runtime=DataLayerRuntime(ds2)))
    assert cfg2.scheduler.profiles["default"].picker.pick_seed == 9


# ---- fleet config -------------------------------------------------------

def test_fleet_config_spec():
    cfg = FleetConfig.from_spec(None)
    assert (cfg.workers, cfg.balancer, cfg.snapshot_ipc) == (1, "reuseport",
                                                            True)
    # Replication + election default ON; `replication: off` /
    # `election: off` are the ISSUE 13 kill-switches.
    assert (cfg.replication, cfg.election) == (True, True)
    assert cfg.kv_checkpoint_s == 2.0
    cfg = FleetConfig.from_spec({"workers": 4, "balancer": "hash",
                                 "snapshotIpc": False, "adminPort": 9911,
                                 "replication": False, "election": False,
                                 "kvCheckpointS": 0.5})
    assert (cfg.workers, cfg.balancer, cfg.snapshot_ipc,
            cfg.admin_port) == (4, "hash", False, 9911)
    assert (cfg.replication, cfg.election, cfg.kv_checkpoint_s) == (
        False, False, 0.5)
    with pytest.raises(ValueError):
        FleetConfig.from_spec({"balancer": "round-robin"})
    with pytest.raises(ValueError):
        FleetConfig.from_spec({"kvCheckpointS": 0})
    # The cadence renews follower replicas: at or beyond half the
    # confirmed TTL it must be rejected, not silently sawtooth divergence.
    with pytest.raises(ValueError):
        FleetConfig.from_spec({"kvCheckpointS": 6.0})


def test_fleet_cli_workers_1_override_pins_single_process(monkeypatch):
    """`…router.fleet --workers 1 --poll-interval …` against a config
    declaring workers: 4 must run ONE plain gateway (not re-enter fleet
    mode via the config) and honor the poll interval."""
    import llm_d_inference_scheduler_tpu.router.fleet as fleet_mod
    import llm_d_inference_scheduler_tpu.router.gateway as gateway_mod

    captured: dict = {}

    def fake_build(text, *, host, port, poll_interval, **kw):
        captured.update(host=host, port=port, poll_interval=poll_interval)
        return "gw"

    async def fake_run(gw, drain_timeout_s):
        captured["ran"] = gw

    monkeypatch.setattr(gateway_mod, "build_gateway", fake_build)
    monkeypatch.setattr(gateway_mod, "run_gateway", fake_run)
    monkeypatch.setattr(
        fleet_mod, "FleetSupervisor",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("fleet mode entered despite --workers 1")))
    fleet_mod.main(["--workers", "1", "--poll-interval", "1.0",
                    "--config-text", "fleet: {workers: 4}\n"])
    assert captured["ran"] == "gw"
    assert captured["poll_interval"] == 1.0


# ---- exposition merge ---------------------------------------------------

def test_merge_expositions_sums_and_dedupes():
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )
    from prometheus_client.parser import text_string_to_metric_families

    from verify_metrics import lint_exposition

    def worker(v):
        r = CollectorRegistry()
        c = Counter("req", "Requests", ("model",), registry=r)
        c.labels("a").inc(v)
        g = Gauge("inference_pool_ready_pods", "Pods", registry=r)
        g.set(2)  # replicated: every worker sees the same pool
        q = Gauge("queued", "Queued", registry=r)
        q.set(v)  # per-worker: sums
        h = Histogram("lat", "Latency", registry=r, buckets=(0.1, 1))
        h.observe(v / 10)
        return generate_latest(r).decode()

    merged = merge_expositions([worker(3), worker(4)])
    assert lint_exposition(merged) == []
    fams = {f.name: f for f in text_string_to_metric_families(merged)}
    assert [s.value for s in fams["req"].samples
            if s.name == "req_total"] == [7.0]
    assert fams["req"].type == "counter"
    assert [s.value for s in fams["inference_pool_ready_pods"].samples] == [2.0]
    assert [s.value for s in fams["queued"].samples] == [7.0]
    assert [s.value for s in fams["lat"].samples
            if s.name == "lat_count"] == [2.0]
    assert [s.value for s in fams["lat"].samples
            if s.name == "lat_sum"] == [0.7]


def test_merge_bounded_gauges_take_max_not_sum():
    """Ratio and enum gauges must never leave their domain on the merged
    exposition: two workers at 0.9 attainment is 0.9 fleet-wide (worst/
    best-state view; the request-weighted merge lives in /debug/slo), and
    two open breakers (state 2) are state 2, not 4."""
    from prometheus_client import CollectorRegistry, Gauge, generate_latest
    from prometheus_client.parser import text_string_to_metric_families

    def worker(attain, breaker):
        r = CollectorRegistry()
        a = Gauge("router_slo_attainment", "A", ("endpoint",), registry=r)
        a.labels("10.0.0.1:8000").set(attain)
        b = Gauge("router_endpoint_circuit_breaker_state", "B",
                  ("endpoint",), registry=r)
        b.labels("10.0.0.1:8000").set(breaker)
        return generate_latest(r).decode()

    merged = merge_expositions([worker(0.9, 2), worker(0.8, 1)])
    fams = {f.name: f for f in text_string_to_metric_families(merged)}
    assert [s.value for s in fams["router_slo_attainment"].samples] == [0.9]
    assert [s.value for s in
            fams["router_endpoint_circuit_breaker_state"].samples] == [2.0]


def test_balancer_flow_id_parses_bare_colon_headers():
    """RFC 7230 allows 'name:value' with no space after the colon; the
    balancer must still see the flow header (falling back to the peer
    address would fragment the flow across shards per-connection)."""
    from llm_d_inference_scheduler_tpu.router.fleet import HashBalancer

    bal = HashBalancer("127.0.0.1", 0, [("127.0.0.1", 1)])
    head = (b"POST /v1/completions HTTP/1.1\r\n"
            b"host: x\r\n"
            b"x-gateway-inference-fairness-id:flow-7\r\n\r\n")
    assert bal._flow_id(head, ("1.2.3.4", 55555)) == "flow-7"
    head_spaced = head.replace(b"id:flow-7", b"id: flow-7")
    assert bal._flow_id(head_spaced, ("1.2.3.4", 55555)) == "flow-7"
    # Anonymous fallback: peer ADDRESS only — the ephemeral port would
    # randomize shard affinity per connection.
    assert bal._flow_id(b"GET / HTTP/1.1\r\n\r\n",
                        ("1.2.3.4", 55555)) == "1.2.3.4"


# ---- /debug/slo merge ---------------------------------------------------

def _slo_doc(requests, met, tokens, ep="10.0.0.1:8000", n_pred=0):
    agg = {"requests": requests, "slo_met": met, "shed": 0,
           "attainment": round(met / requests, 4) if requests else None,
           "output_tokens": tokens, "goodput_tokens": tokens,
           "predictor": {"ttft": ({"n": n_pred, "mae_ms": 100.0,
                                   "mean_signed_ms": -10.0} if n_pred
                                  else {"n": 0}),
                         "tpot": {"n": 0}}}
    return {"enabled": True, "since_unix": 1000.0, "totals": dict(agg),
            "endpoints": {ep: dict(agg)}, "bands": {"0": {
                "requests": requests, "slo_met": met, "shed": 0,
                "output_tokens": tokens, "goodput_tokens": tokens}},
            "miss_reasons": {"ttft": requests - met}, "shed_reasons": {}}


def test_merge_slo_equals_sum_of_ledgers():
    merged = merge_slo([_slo_doc(4, 3, 40, n_pred=2),
                        _slo_doc(6, 6, 60, n_pred=4)])
    t = merged["totals"]
    assert (t["requests"], t["slo_met"], t["output_tokens"]) == (10, 9, 100)
    assert t["attainment"] == 0.9          # recomputed, never averaged
    assert t["goodput_ratio"] == 1.0
    assert t["predictor"]["ttft"]["n"] == 6
    assert t["predictor"]["ttft"]["mae_ms"] == 100.0
    ep = merged["endpoints"]["10.0.0.1:8000"]
    assert (ep["requests"], ep["slo_met"]) == (10, 9)
    assert merged["bands"]["0"]["requests"] == 10
    assert merged["miss_reasons"] == {"ttft": 1}
    assert merged["workers"] == 2


# ---- remote snapshots (datastore unit) ----------------------------------

def _entries(*specs):
    out = []
    for addr, queue in specs:
        meta = EndpointMetadata(name=addr, address=addr.split(":")[0],
                                port=int(addr.split(":")[1]))
        out.append((meta, Metrics(waiting_queue_size=queue), {"warm": True}))
    return out


def test_apply_remote_snapshot_installs_leader_epoch():
    ds = Datastore()
    ds.apply_remote_snapshot(42, _entries(("10.0.0.1:8000", 5)))
    assert ds.snapshot().epoch == 42
    ep = ds.endpoint_get("10.0.0.1:8000")
    assert ep is not None and ep.metrics.waiting_queue_size == 5
    view = ds.snapshot().view()
    assert view[0].attributes.get("warm") is True
    # Remote mode: local dirty flags no longer mint local epochs (the
    # leader's numbering is authoritative)...
    ds.mark_snapshot_dirty()
    assert ds.snapshot().epoch == 42
    # ...membership follows the NEXT frame, including deletions.
    ds.apply_remote_snapshot(43, _entries(("10.0.0.2:8000", 1)))
    assert ds.snapshot().epoch == 43
    assert ds.endpoint_get("10.0.0.1:8000") is None
    assert ds.endpoint_get("10.0.0.2:8000") is not None
    assert len(ds.snapshot()) == 1


def test_snapshot_ipc_round_trip(tmp_path):
    async def body():
        path = str(tmp_path / "snap.sock")
        leader, follower = Datastore(), Datastore()
        leader.endpoint_add_or_update(EndpointMetadata(
            name="e1", address="10.0.0.1", port=8000))
        leader.endpoint_get("10.0.0.1:8000").metrics.waiting_queue_size = 5
        pub = SnapshotPublisher(leader, path, interval_s=0.01)
        await pub.start()
        sub = SnapshotSubscriber(follower, path, retry_s=0.02)
        sub.start()
        try:
            for _ in range(200):
                if follower.endpoint_get("10.0.0.1:8000") is not None:
                    break
                await asyncio.sleep(0.01)
            fep = follower.endpoint_get("10.0.0.1:8000")
            assert fep is not None and fep.metrics.waiting_queue_size == 5
            assert follower.snapshot().epoch == leader.snapshot().epoch
            # A scrape landing publishes a NEW epoch with the new metrics.
            leader.endpoint_get("10.0.0.1:8000").metrics.waiting_queue_size = 9
            leader.mark_snapshot_dirty()
            for _ in range(200):
                if (follower.endpoint_get("10.0.0.1:8000")
                        .metrics.waiting_queue_size == 9):
                    break
                await asyncio.sleep(0.01)
            assert (follower.endpoint_get("10.0.0.1:8000")
                    .metrics.waiting_queue_size) == 9
            # Membership deletions propagate too.
            leader.endpoint_delete("10.0.0.1:8000")
            for _ in range(200):
                if follower.endpoint_get("10.0.0.1:8000") is None:
                    break
                await asyncio.sleep(0.01)
            assert follower.endpoint_get("10.0.0.1:8000") is None
            assert len(follower.snapshot()) == 0
        finally:
            await sub.stop()
            await pub.stop()

    run(body())


# ---- confirmed-index replication (ISSUE 13a) ----------------------------

def _kv_indexes():
    from llm_d_inference_scheduler_tpu.router.plugins.precise_prefix import (
        KvBlockIndex,
    )

    return KvBlockIndex(), KvBlockIndex()


def test_kv_replication_round_trip(tmp_path):
    """Leader-confirmed KvBlockIndex deltas (add/remove/drop) ride the
    snapshot stream and land in the follower's index; the engines' 1s
    idempotent re-publication produces NO delta traffic (change-only)."""
    from llm_d_inference_scheduler_tpu.router.fleet import (
        KvReplicationSource,
    )

    async def body():
        path = str(tmp_path / "snap.sock")
        leader, follower = Datastore(), Datastore()
        leader.endpoint_add_or_update(EndpointMetadata(
            name="e1", address="10.0.0.1", port=8000))
        lidx, fidx = _kv_indexes()
        src = KvReplicationSource(lidx)
        lidx.add("10.0.0.1:8000", [1, 2, 3])
        pub = SnapshotPublisher(leader, path, interval_s=0.01,
                                kv_source=src, kv_checkpoint_s=0.2)
        await pub.start()
        sub = SnapshotSubscriber(follower, path, retry_s=0.02,
                                 kv_index=fidx)
        sub.start()
        try:
            # The pre-connect adds arrive via the periodic checkpoint (a
            # mid-stream joiner's resync path — checkpoints are NOT sent
            # on connect, deliberately: the checkpoint cadence is the
            # joiner's bounded divergence window).
            for _ in range(300):
                if fidx.pod_block_count("10.0.0.1:8000") == 3:
                    break
                await asyncio.sleep(0.01)
            assert fidx.pod_block_count("10.0.0.1:8000") == 3
            # Live deltas: adds/removes propagate within ~one poll.
            lidx.add("10.0.0.1:8000", [4, 5])
            lidx.remove("10.0.0.1:8000", [1])
            for _ in range(300):
                c = fidx.counts().get("10.0.0.1:8000") or {}
                if c.get("confirmed") == 4 and not fidx.holds(
                        "10.0.0.1:8000", 1):
                    break
                await asyncio.sleep(0.01)
            assert fidx.pod_block_count("10.0.0.1:8000") == 4
            assert fidx.holds("10.0.0.1:8000", 4)
            assert not fidx.holds("10.0.0.1:8000", 1)
            # Idempotent re-add (the engine snapshot re-publication) is
            # change-free: no new delta sequence is minted for it.
            seq_before = src.seq
            lidx.add("10.0.0.1:8000", [2, 3, 4, 5])
            assert src.drain() is None and src.seq == seq_before
            # drop_pod replicates.
            lidx.drop_pod("10.0.0.1:8000")
            for _ in range(300):
                if fidx.pod_block_count("10.0.0.1:8000") == 0:
                    break
                await asyncio.sleep(0.01)
            assert fidx.pod_block_count("10.0.0.1:8000") == 0
        finally:
            await sub.stop()
            await pub.stop()

    run(body())


def test_kv_gap_parks_deltas_until_checkpoint():
    """A sequence gap means deltas were lost: the follower must stop
    applying onto the uncertain base (counting a resync) and heal at the
    next full-index checkpoint."""
    from llm_d_inference_scheduler_tpu.router.fleet import (
        SnapshotSubscriber,
    )

    _, fidx = _kv_indexes()
    sub = SnapshotSubscriber(Datastore(), "/nonexistent", kv_index=fidx)
    sub._apply_kv_deltas(1, [("add", "p:1", [1, 2])])
    assert fidx.pod_block_count("p:1") == 2 and not sub.kv_dirty
    # seq 3 after seq 1: gap — the add must NOT apply.
    sub._apply_kv_deltas(3, [("add", "p:1", [9])])
    assert sub.kv_dirty
    assert not fidx.holds("p:1", 9)
    # Checkpoint resyncs: full replace, continuity re-anchored.
    sub._apply_kv_checkpoint(7, {"p:1": [1, 2, 9], "p:2": [5]})
    assert not sub.kv_dirty
    assert fidx.holds("p:1", 9) and fidx.pod_block_count("p:2") == 1
    sub._apply_kv_deltas(8, [("remove", "p:2", [5])])
    assert fidx.pod_block_count("p:2") == 0


def test_subscriber_retarget_mid_backoff(tmp_path):
    """The promotion notice must be event-driven: a subscriber sitting in
    backoff against the dead leader's socket picks up the new address
    immediately instead of waiting the backoff out (ISSUE 13 satellite)."""
    async def body():
        dead = str(tmp_path / "dead.sock")
        live = str(tmp_path / "live.sock")
        leader, follower = Datastore(), Datastore()
        leader.endpoint_add_or_update(EndpointMetadata(
            name="e1", address="10.0.0.1", port=8000))
        pub = SnapshotPublisher(leader, live, interval_s=0.01)
        await pub.start()
        # retry_s far beyond the test budget: only an event-driven wake
        # can make this pass.
        sub = SnapshotSubscriber(follower, dead, retry_s=60.0)
        sub.start()
        try:
            await asyncio.sleep(0.1)  # let it fail once and enter backoff
            sub.retarget(live)
            for _ in range(300):
                if follower.endpoint_get("10.0.0.1:8000") is not None:
                    break
                await asyncio.sleep(0.01)
            assert follower.endpoint_get("10.0.0.1:8000") is not None
            assert sub.path == live
        finally:
            await sub.stop()
            await pub.stop()

    run(body())


# ---- leader re-election plumbing (ISSUE 13b) ----------------------------

def test_restart_budget_follows_leadership():
    """The restart-budget exemption must track the CURRENT leader, not
    the literal index 0: a promoted leader that crash-loops would
    otherwise be budget-killed and freeze the fleet (regression test for
    the ISSUE 13 satellite)."""
    from llm_d_inference_scheduler_tpu.router.fleet import (
        MAX_WORKER_RESTARTS,
        FleetSupervisor,
    )

    sup = FleetSupervisor(None, fleet=FleetConfig(workers=3))
    sup._restarts = [MAX_WORKER_RESTARTS] * 3
    # Boot layout: shard 0 leads and is exempt; followers are budgeted.
    assert sup._restart_allowed(0)
    assert not sup._restart_allowed(1) and not sup._restart_allowed(2)
    # After an election the promoted leader inherits the exemption and
    # the ex-leader becomes a budgeted follower.
    sup.leader_index = 2
    assert sup._restart_allowed(2)
    assert not sup._restart_allowed(0)


def test_lost_promote_ack_resolves_before_leader_respawn():
    """A promote whose ack was lost may still have LANDED: the supervisor
    must re-send the SAME (shard, path) promotion until acknowledged —
    never elect a different path or respawn the dead ex-leader as a
    leader meanwhile (split-brain with no reconciliation)."""
    from llm_d_inference_scheduler_tpu.router.fleet import FleetSupervisor

    sup = FleetSupervisor(None, fleet=FleetConfig(workers=3))
    sup._ipc_dir = "/tmp/fleet-test-ipc"
    sup.ipc_path = "/tmp/fleet-test-ipc/snapshot.sock"
    sup._procs = [None, object(), object()]  # leader 0 dead, 1+2 "alive"
    sup.worker_alive = lambda i: i != 0  # type: ignore[method-assign]

    calls: list[tuple[int, str, str]] = []
    fail = {"promote": True}

    async def fake_control(shard, action, path):
        calls.append((shard, action, path))
        if action == "promote" and fail["promote"]:
            raise RuntimeError("ack lost")

    sup._fleet_control = fake_control  # type: ignore[method-assign]
    run(sup._elect_leader())
    assert sup._pending_promote is not None
    assert sup.leader_index == 0 and sup.elections_total == 0
    pending = sup._pending_promote
    # The dead ex-leader must NOT be respawned while the promotion is
    # unresolved (the monitor-loop guard condition).
    assert pending is not None and sup.leader_index == 0
    # Retry re-sends the SAME shard + path; on ack the election completes.
    fail["promote"] = False
    run(sup._elect_leader())
    assert sup._pending_promote is None
    assert sup.leader_index == 1 and sup.elections_total == 1
    promotes = [(s, p) for s, a, p in calls if a == "promote"]
    assert promotes[0] == promotes[1] == (pending[0], pending[1])


def test_worker_spec_role_follows_leader():
    """A worker respawned after an election must rejoin as a follower of
    the promoted leader, aimed at the NEW snapshot socket (no
    thrash-back)."""
    from llm_d_inference_scheduler_tpu.router.fleet import FleetSupervisor

    sup = FleetSupervisor(None, fleet=FleetConfig(workers=3))
    sup.ipc_path = "/tmp/snap-0.sock"
    assert sup._worker_spec(0)["worker"]["role"] == "leader"
    assert sup._worker_spec(1)["worker"]["role"] == "follower"
    sup.leader_index = 1
    sup.ipc_path = "/tmp/snap-1.sock"
    spec0 = sup._worker_spec(0)["worker"]
    assert spec0["role"] == "follower"
    assert spec0["ipc_path"] == "/tmp/snap-1.sock"
    assert sup._worker_spec(1)["worker"]["role"] == "leader"
    assert spec0["replication"] is True


def test_merge_transfers_nweighted_per_pair():
    """The same (prefill, decode) pair observed by two shards merges into
    ONE row: EWMAs n-weighted by each shard's pull count (the merge_kv
    precedent), totals summed, last_unix freshest, shards annotated —
    no more duplicate rows per shard."""
    doc_a = {"pairs": [
        {"prefill": "p:1", "decode": "d:1", "pulls": 3, "bytes_total": 300,
         "last_unix": 100.0, "ewma_pull_ms": 10.0, "ewma_bytes": 100.0},
        {"prefill": "p:2", "decode": "d:1", "pulls": 1, "bytes_total": 10,
         "last_unix": 90.0, "ewma_pull_ms": 2.0},
    ]}
    doc_b = {"pairs": [
        {"prefill": "p:1", "decode": "d:1", "pulls": 1, "bytes_total": 100,
         "last_unix": 120.0, "ewma_pull_ms": 50.0, "ewma_bytes": 200.0},
        # Prefill-only row (streamed responses): pulls == 0 but the
        # prefill EWMA still contributes at weight 1.
        {"prefill": "p:3", "decode": "d:2", "pulls": 0, "bytes_total": 0,
         "last_unix": 80.0, "ewma_prefill_ms": 42.0},
    ]}
    out = merge_transfers([(0, doc_a), (1, doc_b)])
    pairs = {(p["prefill"], p["decode"]): p for p in out["pairs"]}
    assert len(pairs) == 3  # p:1/d:1 merged, not duplicated
    merged = pairs[("p:1", "d:1")]
    assert merged["pulls"] == 4 and merged["bytes_total"] == 400
    assert merged["last_unix"] == 120.0
    assert merged["shards"] == [0, 1]
    # n-weighted by pulls: (10*3 + 50*1) / 4.
    assert merged["ewma_pull_ms"] == pytest.approx(20.0)
    assert merged["ewma_bytes"] == pytest.approx((100 * 3 + 200 * 1) / 4)
    # Derived wire speed recomputed from the MERGED EWMAs.
    assert merged["ewma_mb_per_s"] == pytest.approx(
        merged["ewma_bytes"] / merged["ewma_pull_ms"] / 1e3, abs=1e-3)
    assert pairs[("p:2", "d:1")]["shards"] == [0]
    assert pairs[("p:3", "d:2")]["ewma_prefill_ms"] == 42.0


def test_merge_kv_leader_shard_param():
    """Divergence is measured against the CURRENT leader shard — after an
    election the promoted shard's confirmed index is the reference."""
    from llm_d_inference_scheduler_tpu.router.fleet import merge_kv
    from llm_d_inference_scheduler_tpu.router.metrics import (
        KV_INDEX_DIVERGENCE,
    )

    warm = {"enabled": True,
            "pods": {"p:1": {"confirmed_blocks": 100,
                             "speculative_blocks": 0}}}
    cold = {"enabled": True,
            "pods": {"p:1": {"confirmed_blocks": 0,
                             "speculative_blocks": 0}}}
    try:
        merged = merge_kv([(0, cold), (1, warm)], leader_shard=1)
        assert merged["leader_shard"] == 1
        assert merged["index_divergence"] == {"0": 1.0, "1": 0.0}
    finally:
        for shard in ("0", "1"):
            try:
                KV_INDEX_DIVERGENCE.remove(shard)
            except KeyError:
                pass


# ---- fan-in admin plane against stub workers ----------------------------

STUB_METRICS = """\
# HELP inference_extension_request_total Requests handled
# TYPE inference_extension_request_total counter
inference_extension_request_total{{model="tiny",target_model="tiny"}} {req}
# HELP router_snapshot_epoch Snapshot epoch
# TYPE router_snapshot_epoch gauge
router_snapshot_epoch {epoch}
# HELP inference_pool_ready_pods Pods
# TYPE inference_pool_ready_pods gauge
inference_pool_ready_pods 2.0
"""


def _stub_worker(port, *, req, epoch, decision_rid=None):
    app = web.Application()

    async def metrics(request):
        return web.Response(text=STUB_METRICS.format(req=req, epoch=epoch),
                            content_type="text/plain")

    async def decision(request):
        rid = request.match_info["request_id"]
        if rid != decision_rid:
            return web.json_response({"error": "not here"}, status=404)
        return web.json_response({"request_id": rid, "final": {"code": 200}})

    async def slo(request):
        return web.json_response(_slo_doc(req, req, req * 4))

    async def transfers(request):
        return web.json_response({"pairs": [{"prefill": "p:1", "decode": "d:1",
                                             "pulls": 2, "bytes_total": 200,
                                             "last_unix": 50.0,
                                             "ewma_pull_ms": 2.0}]})

    async def health(request):
        return web.json_response({"status": "ok"})

    app.add_routes([web.get("/metrics", metrics),
                    web.get("/debug/decisions/{request_id}", decision),
                    web.get("/debug/slo", slo),
                    web.get("/debug/transfers", transfers),
                    web.get("/health", health)])
    return app, port


def test_fleet_admin_fan_in_with_stub_workers():
    from prometheus_client.parser import text_string_to_metric_families

    from verify_metrics import lint_exposition

    async def body():
        runners = []
        for app, port in (_stub_worker(STUB_A, req=3, epoch=7,
                                       decision_rid=None),
                          _stub_worker(STUB_B, req=5, epoch=7,
                                       decision_rid="req-owned-by-b")):
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "127.0.0.1", port).start()
            runners.append(runner)
        admin = FleetAdmin([("127.0.0.1", STUB_A), ("127.0.0.1", STUB_B)],
                           host="127.0.0.1", port=STUB_ADMIN)
        await admin.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                base = f"http://127.0.0.1:{STUB_ADMIN}"
                # Merged /metrics: parses, no duplicate families, counters
                # summed, replicated pool gauge NOT summed, shard families
                # present.
                r = await c.get(base + "/metrics")
                assert r.status_code == 200
                assert lint_exposition(r.text) == []
                fams = {f.name: f
                        for f in text_string_to_metric_families(r.text)}
                req_total = [s.value
                             for s in fams["inference_extension_request"].samples
                             if s.name.endswith("_total")]
                assert req_total == [8.0]
                assert [s.value for s in
                        fams["inference_pool_ready_pods"].samples] == [2.0]
                up = {s.labels["shard"]: s.value
                      for s in fams["router_shard_up"].samples}
                assert up == {"0": 1.0, "1": 1.0}
                epochs = {s.labels["shard"]: s.value
                          for s in fams["router_shard_snapshot_epoch"].samples}
                assert epochs == {"0": 7.0, "1": 7.0}
                shard_req = {s.labels["shard"]: s.value
                             for s in fams["router_shard_requests"].samples
                             if s.name.endswith("_total")}
                assert shard_req["0"] >= 3.0 and shard_req["1"] >= 5.0
                # Record lookup routes to the owning shard (worker B).
                r = await c.get(base + "/debug/decisions/req-owned-by-b")
                assert r.status_code == 200
                assert r.json()["shard"] == 1
                r = await c.get(base + "/debug/decisions/req-nowhere")
                assert r.status_code == 404
                # /debug/slo equals the sum of the per-worker ledgers.
                r = await c.get(base + "/debug/slo")
                totals = r.json()["totals"]
                assert (totals["requests"], totals["slo_met"]) == (8, 8)
                assert totals["output_tokens"] == 32
                # /debug/transfers: the same pair observed by both shards
                # is ONE merged row (n-weighted), shard-list annotated.
                r = await c.get(base + "/debug/transfers")
                pairs = r.json()["pairs"]
                assert len(pairs) == 1
                assert pairs[0]["shards"] == [0, 1]
                assert pairs[0]["pulls"] == 4
                assert pairs[0]["ewma_pull_ms"] == 2.0
                # /health aggregates worker states.
                r = await c.get(base + "/health")
                assert r.status_code == 200
                assert r.json()["workers_ready"] == 2
                # Counter monotonicity across a worker outage: with shard B
                # down, the merge serves B's last-seen families instead of
                # letting fleet *_total counters dip (Prometheus would read
                # the dip + recovery as a counter reset and spike rate()).
                await runners[1].cleanup()
                r = await c.get(base + "/metrics")
                fams = {f.name: f
                        for f in text_string_to_metric_families(r.text)}
                req_total = [s.value
                             for s in fams["inference_extension_request"].samples
                             if s.name.endswith("_total")]
                assert req_total == [8.0]  # B's 5.0 still contributes
                up = {s.labels["shard"]: s.value
                      for s in fams["router_shard_up"].samples}
                assert up == {"0": 1.0, "1": 0.0}
        finally:
            await admin.stop()
            for runner in runners:
                await runner.cleanup()

    run(body())


def test_fleet_admin_kv_fan_in_and_divergence():
    """Merged /debug/kv against stub workers: shard-annotated snapshots,
    n-weighted MAE merge, and the leader-vs-follower index-divergence
    gauge (the follower's speculative-only view measured against the
    leader's engine-confirmed KvBlockIndex counts)."""
    from llm_d_inference_scheduler_tpu.router.fleet import (
        shard_index_divergence,
    )
    from llm_d_inference_scheduler_tpu.router.metrics import (
        KV_INDEX_DIVERGENCE,
    )

    leader_doc = {
        "enabled": True, "predicted_stamps": 10, "confirmed_joins": 8,
        "prediction": {"n": 8, "mae_blocks": 2.0,
                       "mean_signed_blocks": 1.0},
        "prediction_ratio": {"n": 8, "mae_ratio": 0.1,
                             "mean_signed_ratio": 0.05},
        "pods": {"p:1": {"confirmed_blocks": 100, "speculative_blocks": 0},
                 "p:2": {"confirmed_blocks": 60, "speculative_blocks": 0}},
        "index_divergence": 0.0,
    }
    follower_doc = {
        "enabled": True, "predicted_stamps": 4, "confirmed_joins": 4,
        "prediction": {"n": 4, "mae_blocks": 5.0,
                       "mean_signed_blocks": -2.0},
        "prediction_ratio": {"n": 4, "mae_ratio": 0.4,
                             "mean_signed_ratio": -0.2},
        # Speculative-only view covering 40 of the leader's 160 confirmed.
        "pods": {"p:1": {"confirmed_blocks": 0, "speculative_blocks": 30},
                 "p:2": {"confirmed_blocks": 0, "speculative_blocks": 10}},
        "index_divergence": 0.0,
    }
    # Unit: 40/160 covered → divergence 0.75; full coverage → 0.
    assert shard_index_divergence(leader_doc, follower_doc) == 0.75
    assert shard_index_divergence(leader_doc, leader_doc) == 0.0
    assert shard_index_divergence({"pods": {}}, follower_doc) == 0.0

    def _kv_stub(port, doc):
        app = web.Application()

        async def kv(request):
            return web.json_response(doc)

        async def health(request):
            return web.json_response({"status": "ok"})

        app.add_routes([web.get("/debug/kv", kv),
                        web.get("/health", health)])
        return app, port

    async def body():
        runners = []
        for app, port in (_kv_stub(STUB_A, leader_doc),
                          _kv_stub(STUB_B, follower_doc)):
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "127.0.0.1", port).start()
            runners.append(runner)
        admin = FleetAdmin([("127.0.0.1", STUB_A), ("127.0.0.1", STUB_B)],
                           host="127.0.0.1", port=STUB_ADMIN)
        await admin.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                r = await c.get(
                    f"http://127.0.0.1:{STUB_ADMIN}/debug/kv")
                assert r.status_code == 200
                doc = r.json()
                assert doc["workers"] == 2 and doc["enabled"]
                assert doc["predicted_stamps"] == 14
                assert doc["confirmed_joins"] == 12
                # n-weighted MAE merge: (8*2 + 4*5) / 12 = 3.0.
                assert doc["prediction"] == {
                    "n": 12, "mae_blocks": 3.0, "mean_signed_blocks": 0.0}
                assert doc["prediction_ratio"]["mae_ratio"] == 0.2
                # Shard annotation + per-shard divergence, and the gauge.
                assert [s["shard"] for s in doc["shards"]] == [0, 1]
                assert doc["index_divergence"] == {"0": 0.0, "1": 0.75}
                assert doc["shards"][1]["index_divergence"] == 0.75
                m = (await c.get(
                    f"http://127.0.0.1:{STUB_ADMIN}/metrics")).text
                assert ('router_kv_index_divergence{shard="1"} 0.75'
                        in m)
        finally:
            await admin.stop()
            for runner in runners:
                await runner.cleanup()
            for shard in ("0", "1"):
                try:
                    KV_INDEX_DIVERGENCE.remove(shard)
                except KeyError:
                    pass

    run(body())


# ---- real 2-worker fleet e2e --------------------------------------------

FLEET_CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {E1}}}
    - {{address: 127.0.0.1, port: {E2}}}
scheduling: {{pickSeed: 7}}
"""


CHAOS_GW, CHAOS_E1, CHAOS_E2, CHAOS_ADMIN = 19085, 19086, 19087, 19090

# Precise-prefix scoring in the profile: the leader's engine-confirmed
# KvBlockIndex is what replication must keep identical in every shard.
CHAOS_CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {CHAOS_E1}}}
    - {{address: 127.0.0.1, port: {CHAOS_E2}}}
scheduling: {{pickSeed: 7}}
timeline: {{tickS: 0.5, rules: {{divergenceMax: 0.2}}}}
plugins:
  - {{type: token-producer}}
  - {{type: precise-prefix-cache-scorer}}
  - {{type: queue-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: precise-prefix-cache-scorer, weight: 2}}
      - {{pluginRef: queue-scorer, weight: 1}}
"""


@pytest.mark.slow
def test_fleet_chaos_leader_kill_election_and_divergence_recovery():
    """Fixed-seed kill-the-leader chaos (ISSUE 13 satellite, rides `make
    test-chaos`): 3 workers with confirmed-index replication converged,
    SIGKILL the datalayer leader mid-traffic — the supervisor must promote
    the lowest-index live follower, /debug/fleet must reflect the new role
    table (ex-leader rejoined as follower), and the per-shard
    router_kv_index_divergence must return to ~0 after the promotion."""
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.fleet import FleetSupervisor

    async def body():
        engines = []
        for port in (CHAOS_E1, CHAOS_E2):
            s = EngineServer(EngineConfig(backend="sim", model="tiny",
                                          port=port, max_batch=8,
                                          sim_decode_ms_per_token=1.0))
            await s.start()
            engines.append(s)
        sup = FleetSupervisor(
            CHAOS_CFG, host="127.0.0.1", port=CHAOS_GW,
            fleet=FleetConfig(workers=3, balancer="hash",
                              admin_port=CHAOS_ADMIN, kv_checkpoint_s=1.0),
            poll_interval=0.02, drain_timeout_s=2.0)
        await sup.start()
        statuses: list[int] = []

        async def one_request(i: int) -> None:
            # One connection per request so the balancer routes each flow
            # independently; 503s are the documented balancer blip for
            # flows owned by a dead shard.
            try:
                async with httpx.AsyncClient(timeout=15) as c:
                    r = await c.post(
                        f"http://127.0.0.1:{CHAOS_GW}/v1/completions",
                        headers={"x-request-id": f"chaos-{i}",
                                 "x-gateway-inference-fairness-id":
                                     f"flow-{i % 6}"},
                        json={"model": "tiny",
                              "prompt": f"shared prefix {'x' * 96} "
                                        f"tail {i % 6}",
                              "max_tokens": 2})
                    statuses.append(r.status_code)
            except httpx.HTTPError:
                statuses.append(-1)

        stop_traffic = asyncio.Event()

        async def traffic() -> None:
            i = 0
            while not stop_traffic.is_set():
                await one_request(i)
                i += 1
                await asyncio.sleep(0.05)

        async def converged(c, *, bound: float) -> dict:
            doc = {}
            deadline = asyncio.get_running_loop().time() + bound
            while asyncio.get_running_loop().time() < deadline:
                r = await c.get(f"http://127.0.0.1:{CHAOS_ADMIN}/debug/kv")
                doc = r.json()
                div = doc.get("index_divergence") or {}
                leader_doc = next(
                    (s for s in doc.get("shards") or []
                     if s.get("shard") == doc.get("leader_shard")), {})
                confirmed = sum(
                    int((row or {}).get("confirmed_blocks") or 0)
                    for row in (leader_doc.get("pods") or {}).values())
                if (len(div) == 3 and confirmed > 0
                        and all(v <= 0.05 for v in div.values())):
                    return doc
                await asyncio.sleep(0.25)
            return doc

        traffic_task = asyncio.get_running_loop().create_task(traffic())
        try:
            async with httpx.AsyncClient(timeout=15) as c:
                # Phase 1: replication converges — every shard's view
                # covers the leader's confirmed index (divergence ~0) with
                # real confirmed blocks on the leader.
                doc = await converged(c, bound=30.0)
                assert doc.get("index_divergence"), doc
                assert all(v <= 0.05
                           for v in doc["index_divergence"].values()), doc

                # Phase 2: kill the leader mid-traffic.
                sup._procs[sup.leader_index].kill()

                # Phase 3: election — lowest-index live follower promoted.
                promoted = False
                for _ in range(120):
                    await asyncio.sleep(0.25)
                    r = await c.get(
                        f"http://127.0.0.1:{CHAOS_ADMIN}/debug/fleet")
                    if r.json().get("leader") == 1:
                        promoted = True
                        break
                assert promoted, "no promotion within 30s of the kill"

                # Phase 4: divergence recovery under the new leader — the
                # rejoined ex-leader resyncs from the periodic checkpoint.
                doc = await converged(c, bound=40.0)
                assert all(v <= 0.05
                           for v in doc["index_divergence"].values()), doc
                assert doc["leader_shard"] == 1

                # Phase 5: the role table reflects the new world — shard 1
                # leads, the restarted worker 0 rejoined as a follower.
                r = await c.get(
                    f"http://127.0.0.1:{CHAOS_ADMIN}/debug/fleet")
                fleet_doc = r.json()
                assert fleet_doc["leader"] == 1
                assert fleet_doc["elections_total"] == 1
                roles = {w["shard"]: (w["role"], w["alive"])
                         for w in fleet_doc["admin"]}
                assert roles[1] == ("leader", True)
                assert roles[0] == ("follower", True)
                assert roles[2] == ("follower", True)
        finally:
            stop_traffic.set()
            await traffic_task
            await sup.stop()
            for e in engines:
                await e.stop()
        # Client-visible errors: only the balancer's documented 503 blip
        # for flows owned by the dead shard (and transport errors while
        # its listener is gone) — never a 5xx minted by a live worker.
        bad = [s for s in statuses if s not in (200, 503, -1)]
        assert not bad, f"unexpected statuses {bad}"
        assert statuses.count(200) > 0

    run(body())


@pytest.mark.slow
def test_verify_fleet_clean():
    """Failover drill (scripts/verify_fleet.py — the make verify-fleet
    twin): kill the leader, a new leader must serve snapshots within the
    bound."""
    import verify_fleet

    assert verify_fleet.check() == []


def test_fleet_e2e_two_workers_hash_balancer():
    """The full shape: 2 spawned gateway workers behind the hash balancer,
    snapshot IPC from the worker-0 leader, sim engines, and the
    supervisor's fan-in plane — merged /metrics parses clean, the decision
    lookup resolves through the supervisor to whichever shard served, and
    the follower tracks the leader's snapshot epochs."""
    from prometheus_client.parser import text_string_to_metric_families

    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.fleet import FleetSupervisor
    from verify_metrics import lint_exposition

    async def body():
        engines = []
        for port in (E1, E2):
            s = EngineServer(EngineConfig(backend="sim", model="tiny",
                                          port=port, max_batch=4,
                                          sim_decode_ms_per_token=1.0))
            await s.start()
            engines.append(s)
        sup = FleetSupervisor(
            FLEET_CFG, host="127.0.0.1", port=GW,
            fleet=FleetConfig(workers=2, balancer="hash", admin_port=ADMIN),
            poll_interval=0.02, drain_timeout_s=2.0)
        await sup.start()
        try:
            served_shards = set()
            rids = []
            for i in range(4):
                rid = f"fleet-e2e-{i}"
                rids.append(rid)
                # One client per request = one connection per request, so
                # the balancer routes each flow independently (keep-alive
                # connections are flow-sticky by design).
                async with httpx.AsyncClient(timeout=30) as c:
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        headers={"x-request-id": rid,
                                 "x-gateway-inference-fairness-id":
                                     f"flow-{i}"},
                        json={"model": "tiny", "prompt": f"hello {i}",
                              "max_tokens": 4})
                assert r.status_code == 200
                assert r.headers["x-router-shard"] in ("0", "1")
                served_shards.add(r.headers["x-router-shard"])
            # flow-0..3 hash across both shards (fixed xxh64 assignment).
            assert served_shards == {"0", "1"}

            async with httpx.AsyncClient(timeout=30) as c:
                base = f"http://127.0.0.1:{ADMIN}"
                r = await c.get(base + "/metrics")
                assert r.status_code == 200
                assert lint_exposition(r.text) == []
                fams = {f.name: f
                        for f in text_string_to_metric_families(r.text)}
                served = sum(
                    s.value for s in fams["inference_extension_request"].samples
                    if s.name.endswith("_total"))
                assert served == 4.0
                # Snapshot IPC: the follower's applied epoch tracks the
                # leader's published one (both shards report a live epoch).
                epochs = {s.labels["shard"]: s.value
                          for s in fams["router_shard_snapshot_epoch"].samples}
                assert set(epochs) == {"0", "1"}
                assert all(v >= 1.0 for v in epochs.values())
                assert {s.labels["shard"]: s.value
                        for s in fams["router_shard_up"].samples} == {
                            "0": 1.0, "1": 1.0}
                # Hash balancer counted each flow's connection.
                bal = sum(s.value for s in
                          fams["router_fleet_balancer_connections"].samples
                          if s.name.endswith("_total"))
                assert bal >= 4.0
                # Every request's decision record resolves through the
                # supervisor to the shard that served it.
                for rid in rids:
                    r = await c.get(base + f"/debug/decisions/{rid}")
                    assert r.status_code == 200, rid
                    assert r.json()["shard"] in (0, 1)
                # The merged list view covers all shards' records,
                # shard-annotated, newest first.
                r = await c.get(base + "/debug/decisions")
                doc = r.json()
                assert doc["count"] == 4 and doc["enabled"]
                listed = {d["request_id"] for d in doc["decisions"]}
                assert set(rids) <= listed
                assert all("shard" in d for d in doc["decisions"])
                stamps = [d["start_unix"] for d in doc["decisions"]]
                assert stamps == sorted(stamps, reverse=True)
                # ?n bounds the MERGED page, not n-per-worker.
                r = await c.get(base + "/debug/decisions?n=1")
                assert len(r.json()["decisions"]) == 1
                # Fleet SLO rollup saw all four requests.
                r = await c.get(base + "/debug/slo")
                assert r.json()["totals"]["requests"] == 4
                # Fleet /debug/kv: live on the supervisor with the
                # per-shard divergence gauge present for every shard
                # (leader shard 0 reports 0 by definition).
                r = await c.get(base + "/debug/kv")
                kv = r.json()
                assert kv["workers"] == 2
                assert set(kv["index_divergence"]) == {"0", "1"}
                assert kv["index_divergence"]["0"] == 0.0
                r = await c.get(base + "/health")
                assert r.status_code == 200
                assert r.json()["workers_ready"] == 2
        finally:
            await sup.stop()
            for e in engines:
                await e.stop()

    run(body())
