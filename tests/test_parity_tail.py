"""Reference-parity tail (VERDICT r4 missing #2-#4): prefiller sampling,
the generic HTTP datalayer source, and the tokenizer UDS transport."""

import asyncio
import json

import pytest
from aiohttp import web
from aiohttp.test_utils import make_mocked_request
from multidict import CIMultiDict

from llm_d_inference_scheduler_tpu.router.sidecar.proxy import (
    Sidecar,
    SidecarConfig,
)


def _req(headers: list[tuple[str, str]]):
    return make_mocked_request("POST", "/v1/completions",
                               headers=CIMultiDict(headers))


def test_prefiller_sampling():
    """chat_completions.go:79-95: repeated header values and comma lists are
    both candidate sets; sampling picks uniformly, default picks first."""
    first = Sidecar(SidecarConfig())
    # Default: first candidate, comma-separated form.
    r = _req([("x-prefiller-host-port", "a:1, b:2 ,c:3")])
    assert first._pick_prefiller(r) == "a:1"
    # Repeated header values.
    r = _req([("x-prefiller-host-port", "a:1"),
              ("x-prefiller-host-port", "b:2")])
    assert first._pick_prefiller(r) == "a:1"
    # No header → no prefiller.
    assert first._pick_prefiller(_req([])) is None
    # Empty-ish values are dropped.
    r = _req([("x-prefiller-host-port", " , ,x:9")])
    assert first._pick_prefiller(r) == "x:9"

    sampling = Sidecar(SidecarConfig(enable_prefiller_sampling=True))
    picks = []
    sampling._prefill_sampler = lambda n: picks.append(n) or (n - 1)
    r = _req([("x-prefiller-host-port", "a:1,b:2,c:3")])
    assert sampling._pick_prefiller(r) == "c:3"
    assert picks == [3]  # sampler sees the full candidate count

    # Statistical spread with the real sampler: over many draws every
    # candidate appears (uniform over 3, 60 draws: miss odds ~3e-11).
    real = Sidecar(SidecarConfig(enable_prefiller_sampling=True))
    seen = {real._pick_prefiller(r) for _ in range(60)}
    assert seen == {"a:1", "b:2", "c:3"}


def test_http_data_source_polls_into_attribute():
    """framework/plugins/datalayer/source/http: generic poller stores the
    parsed body under a configurable attribute key."""
    from llm_d_inference_scheduler_tpu.router.datalayer.datastore import (
        Datastore,
    )
    from llm_d_inference_scheduler_tpu.router.datalayer.http_source import (
        HttpDataExtractor,
        HttpDataSource,
    )
    from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
        EndpointMetadata,
    )

    PORT = 18571

    async def body():
        calls = {"n": 0}

        async def server_info(request):
            calls["n"] += 1
            return web.json_response({"engine": "tpu", "n": calls["n"]})

        async def plain(request):
            return web.Response(text="not json at all")

        app = web.Application()
        app.add_routes([web.get("/server_info", server_info),
                        web.get("/plain", plain)])
        runner = web.AppRunner(app)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", PORT).start()

        ds = Datastore()
        ep = ds.endpoint_add_or_update(EndpointMetadata(
            name="e1", address="127.0.0.1", port=PORT))
        try:
            src = HttpDataSource("http-data-source")
            src.configure({"path": "server_info"}, None)  # leading / added
            # Default extractor pairing keys by path.
            exs = src.extractors()
            raw = await src.collect(ep)
            assert raw is not None
            for ex in exs:
                ex.extract(raw, ep)
            assert ep.attributes.get("/server_info") == {"engine": "tpu",
                                                         "n": 1}

            # Explicit extractor with custom key + text format.
            src2 = HttpDataSource("src2")
            src2.configure({"path": "/plain"}, None)
            ex2 = HttpDataExtractor("ex2")
            ex2.configure({"attributeKey": "info/plain", "format": "text"},
                          None)
            src2.add_extractor(ex2)
            raw2 = await src2.collect(ep)
            ex2.extract(raw2, ep)
            assert ep.attributes.get("info/plain") == "not json at all"

            # format=json on an unparseable body stores nothing.
            ex3 = HttpDataExtractor("ex3")
            ex3.configure({"attributeKey": "info/strict", "format": "json"},
                          None)
            ex3.extract(raw2, ep)
            assert ep.attributes.get("info/strict") is None

            # refreshSeconds throttles: a second collect inside the window
            # is a no-op (None), not another GET.
            src3 = HttpDataSource("src3")
            src3.configure({"path": "/server_info", "refreshSeconds": 30},
                           None)
            assert await src3.collect(ep) is not None
            n_after_first = calls["n"]
            assert await src3.collect(ep) is None
            assert calls["n"] == n_after_first

            await src.close()
            await src2.close()
            await src3.close()
        finally:
            await runner.cleanup()

    asyncio.run(body())

    # Scheme validation (datasource.go:46).
    from llm_d_inference_scheduler_tpu.router.datalayer.http_source import (
        HttpDataSource as S,
    )

    with pytest.raises(ValueError, match="unsupported scheme"):
        S("bad").configure({"scheme": "ftp"}, None)


def test_token_producer_uds_transport(tmp_path):
    """dataproducer/tokenizer/uds.go: with udsPath set, render calls ride a
    unix socket to a node-local tokenizer, not the scheduled endpoint."""
    from llm_d_inference_scheduler_tpu.router.datalayer.datastore import (
        Datastore,
    )
    from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
        EndpointMetadata,
    )
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        InferenceRequest,
        InferenceRequestBody,
    )
    from llm_d_inference_scheduler_tpu.router.requestcontrol.producers import (
        TokenProducer,
    )

    sock = str(tmp_path / "tokenizer.sock")

    async def body():
        async def render(request):
            doc = await request.json()
            toks = [len(w) for w in (doc.get("prompt") or "").split()]
            return web.json_response({"token_ids": toks})

        app = web.Application()
        app.add_routes([web.post("/v1/completions/render", render)])
        runner = web.AppRunner(app)
        await runner.setup()
        await web.UnixSite(runner, sock).start()

        ds = Datastore()
        # Deliberately unreachable endpoint: proves the socket carried the
        # render call, not the endpoint URL.
        ep = ds.endpoint_add_or_update(EndpointMetadata(
            name="e1", address="127.0.0.1", port=1))
        try:
            tp = TokenProducer("token-producer")
            tp.configure({"udsPath": sock}, None)
            req = InferenceRequest(
                request_id="r1", target_model="m",
                body=InferenceRequestBody(
                    completions={"prompt": "alpha bb cccc"}))
            await tp.produce(None, req, [ep])
            assert req.body.tokenized_prompt == [5, 2, 4]
            # Cached on repeat (no socket needed).
            req2 = InferenceRequest(
                request_id="r2", target_model="m",
                body=InferenceRequestBody(
                    completions={"prompt": "alpha bb cccc"}))
            await runner.cleanup()
            await tp.produce(None, req2, [ep])
            assert req2.body.tokenized_prompt == [5, 2, 4]
            if tp._client is not None:
                await tp._client.aclose()
        finally:
            try:
                await runner.cleanup()
            except Exception:
                pass

    asyncio.run(body())
