"""End-to-end slice: gateway → director/scheduler → live sim engines.

Mirrors the reference's hermetic integration tier (SURVEY §4): real HTTP all
the way through, engines simulated (llm-d-inference-sim analogue).
"""

import asyncio
import json

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


CFG = """
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18341}
    - {address: 127.0.0.1, port: 18342}
"""


def run(coro):
    return asyncio.run(coro)


async def _spawn_engines(*ports, **cfg_kw):
    servers = []
    for port in ports:
        kw = dict(backend="sim", model="tiny", port=port, max_batch=4,
                  sim_decode_ms_per_token=1.0)
        kw.update(cfg_kw)
        s = EngineServer(EngineConfig(**kw))
        await s.start()
        servers.append(s)
    return servers


def test_gateway_routes_and_rewrites():
    async def body():
        engines = await _spawn_engines(18341, 18342)
        gw = build_gateway(CFG, port=18340, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                # health & readiness
                r = await c.get("http://127.0.0.1:18340/health")
                assert r.status_code == 200

                r = await c.post("http://127.0.0.1:18340/v1/completions",
                                 json={"model": "tiny", "prompt": "hello world",
                                       "max_tokens": 4})
                assert r.status_code == 200
                assert r.headers["x-gateway-destination-endpoint-served"] in (
                    "127.0.0.1:18341", "127.0.0.1:18342")
                assert r.json()["usage"]["completion_tokens"] == 4

                # chat + streaming through the proxy
                async with c.stream(
                        "POST", "http://127.0.0.1:18340/v1/chat/completions",
                        json={"model": "tiny", "max_tokens": 3, "stream": True,
                              "messages": [{"role": "user", "content": "hi"}]}) as r:
                    lines = [l async for l in r.aiter_lines() if l.startswith("data: ")]
                    assert lines[-1] == "data: [DONE]"

                # router metrics exposed
                r = await c.get("http://127.0.0.1:18340/metrics")
                assert "inference_extension_request_total" in r.text
                assert "inference_extension_scheduler_e2e_duration_seconds" in r.text
        finally:
            await gw.stop()
            for s in engines:
                await s.stop()

    run(body())


def test_gateway_load_balances_by_queue_depth():
    """Saturate engine A; the queue scorer must steer traffic to engine B."""
    async def body():
        engines = await _spawn_engines(18341, 18342, max_batch=2,
                                       sim_decode_ms_per_token=30.0)
        gw = build_gateway(CFG, port=18340, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # Pin load onto engine A directly (bypassing the gateway).
                pinned = [
                    asyncio.create_task(c.post(
                        "http://127.0.0.1:18341/v1/completions",
                        json={"prompt": "x" * 40, "max_tokens": 40}))
                    for _ in range(6)
                ]
                await asyncio.sleep(0.3)  # let collectors observe the load
                served = []
                for _ in range(6):
                    r = await c.post("http://127.0.0.1:18340/v1/completions",
                                     json={"model": "tiny", "prompt": "y",
                                           "max_tokens": 1})
                    served.append(r.headers["x-gateway-destination-endpoint-served"])
                await asyncio.gather(*pinned)
                # The loaded engine must receive (almost) none of the traffic.
                assert served.count("127.0.0.1:18342") >= 5, served
        finally:
            await gw.stop()
            for s in engines:
                await s.stop()

    run(body())


def test_gateway_prefix_affinity_stickiness():
    """With the prefix producer configured, identical long prompts stick to
    one endpoint (cache locality) while different prompts can move."""
    cfg = CFG + """
plugins:
  - type: approx-prefix-cache-producer
  - type: prefix-cache-scorer
  - type: queue-scorer
schedulingProfiles:
  - name: default
    plugins:
      - pluginRef: prefix-cache-scorer
        weight: 3
      - pluginRef: queue-scorer
        weight: 1
"""

    async def body():
        engines = await _spawn_engines(18341, 18342)
        gw = build_gateway(cfg, port=18340, poll_interval=0.02)
        await gw.start()
        try:
            prompt = "the quick brown fox jumps over the lazy dog " * 10
            served = []
            async with httpx.AsyncClient(timeout=30) as c:
                for _ in range(5):
                    r = await c.post("http://127.0.0.1:18340/v1/completions",
                                     json={"model": "tiny", "prompt": prompt,
                                           "max_tokens": 1})
                    served.append(r.headers["x-gateway-destination-endpoint-served"])
            # first pick free, everything after must stick
            assert len(set(served)) == 1, served
        finally:
            await gw.stop()
            for s in engines:
                await s.stop()

    run(body())


def test_gateway_error_paths():
    async def body():
        gw = build_gateway(CFG, port=18340, poll_interval=0.02)
        # no engines running: endpoints exist but upstream connect fails
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post("http://127.0.0.1:18340/v1/completions",
                                 json={"model": "m", "prompt": "x"})
                assert r.status_code == 502

                r = await c.post("http://127.0.0.1:18340/v1/completions",
                                 content=b"{not json")
                assert r.status_code == 400
        finally:
            await gw.stop()

    run(body())


def test_gateway_sigterm_drain():
    """run_gateway's SIGTERM flow: readiness flips not-ready immediately,
    an in-flight proxied stream still completes, then the gateway exits."""
    import os
    import signal

    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import (
        build_gateway,
        run_gateway,
    )

    EPORT, GPORT = 18621, 18620

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=EPORT,
                                        sim_decode_ms_per_token=40.0))
        await eng.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EPORT}}}
""", port=GPORT, poll_interval=0.02)
        gw_task = asyncio.create_task(run_gateway(gw, drain_timeout_s=20.0))
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                for _ in range(100):
                    if gw_task.done():
                        gw_task.result()
                        raise AssertionError("gateway exited early")
                    try:
                        if (await c.get(
                                f"http://127.0.0.1:{GPORT}/health")
                                ).status_code == 200:
                            break
                    except Exception:
                        pass
                    await asyncio.sleep(0.05)
                else:
                    raise AssertionError("gateway never became ready")

                gen = asyncio.create_task(c.post(
                    f"http://127.0.0.1:{GPORT}/v1/completions",
                    json={"model": "tiny", "prompt": "hi",
                          "max_tokens": 25}))
                await asyncio.sleep(0.2)
                os.kill(os.getpid(), signal.SIGTERM)
                await asyncio.sleep(0.3)
                r = await c.get(f"http://127.0.0.1:{GPORT}/health")
                assert r.status_code == 503  # draining: not-ready

                resp = await gen
                assert resp.status_code == 200
                assert resp.json()["usage"]["completion_tokens"] == 25
            await asyncio.wait_for(gw_task, timeout=30)
        finally:
            if not gw_task.done():
                gw_task.cancel()
            await eng.stop()

    asyncio.run(body())
