"""Precise prefix cache: engine KV events over ZMQ → router exact-block index."""

import asyncio
import json

import httpx
import zmq


from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.plugins.precise_prefix import KvBlockIndex
from llm_d_inference_scheduler_tpu.utils.hashing import chain_block_hashes


def test_engine_publishes_stored_and_removed_events():
    async def body():
        # prefix caching off: this test asserts the plain block lifecycle
        # (stored at prefill, removed at free); with caching, blocks park and
        # 'removed' fires at LRU eviction instead.
        cfg = EngineConfig(model="tiny", backend="tpu", max_batch=2,
                           max_model_len=128, port=18510, kv_events_port=18520,
                           enable_prefix_caching=False)
        eng = TpuEngine(cfg)

        events = []

        def listen():
            sock = zmq.Context.instance().socket(zmq.SUB)
            sock.setsockopt(zmq.SUBSCRIBE, b"kv-events")
            sock.setsockopt(zmq.RCVTIMEO, 500)
            sock.connect("tcp://127.0.0.1:18520")
            import time
            deadline = time.monotonic() + 30
            try:
                while time.monotonic() < deadline:
                    try:
                        _, payload = sock.recv_multipart()
                    except zmq.Again:
                        continue
                    events.append(json.loads(payload))
                    if events[-1]["event"] == "removed":
                        return
            finally:
                sock.close(linger=0)

        import threading
        t = threading.Thread(target=listen, daemon=True)
        t.start()
        await asyncio.sleep(0.3)  # late-joiner settle

        await eng.start()
        try:
            prompt = [1] + list(range(10, 41))  # 32 tokens = 2 full blocks
            out = eng.submit(EngineRequest(request_id="r", prompt_token_ids=prompt,
                                           max_tokens=2, stop_token_ids=(-1,)))
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=60)
                if ev.finish_reason is not None:
                    break
            await asyncio.get_running_loop().run_in_executor(None, t.join, 30)
            expect = chain_block_hashes("tiny", prompt, "", 16)
            assert len(expect) == 2
            stored = [e for e in events if e["event"] == "stored"]
            removed = [e for e in events if e["event"] == "removed"]
            assert stored and removed, events
            assert expect == stored[0]["hashes"][:2] or set(expect) <= set(
                h for e in stored for h in e["hashes"])
            assert set(expect) <= set(removed[0]["hashes"])
        finally:
            await eng.stop()

    asyncio.run(body())


def test_kv_block_index_semantics():
    idx = KvBlockIndex()
    idx.add("a", [1, 2, 3])
    idx.add("b", [1])
    assert idx.holds("a", 2) and idx.holds("b", 1) and not idx.holds("b", 2)
    idx.remove("a", [2])
    assert not idx.holds("a", 2) and idx.holds("a", 3)
    idx.add_speculative("c", [9])
    assert idx.holds("c", 9)  # within TTL
    idx.drop_pod("a")
    assert not idx.holds("a", 1) and idx.holds("b", 1)


def test_precise_scorer_e2e_steers_to_warm_pod():
    cfg_yaml = """
plugins:
  - {type: token-producer}
  - {type: precise-prefix-cache-scorer}
  - {type: queue-scorer}
schedulingProfiles:
  - name: default
    plugins:
      - {pluginRef: precise-prefix-cache-scorer, weight: 5}
      - {pluginRef: queue-scorer}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18511}
    - {address: 127.0.0.1, port: 18512}
"""

    async def body():
        from llm_d_inference_scheduler_tpu.engine.server import EngineServer

        engines = [EngineServer(EngineConfig(
            model="tiny", backend="tpu", max_batch=2, max_model_len=512,
            port=p, kv_events_port=p + 1000)) for p in (18511, 18512)]
        for e in engines:
            await e.start()
        gw = build_gateway(cfg_yaml, port=18513, poll_interval=0.02)
        await gw.start()
        try:
            scorer = gw.cfg.plugins_by_name["precise-prefix-cache-scorer"]
            await asyncio.sleep(0.3)  # let SUB sockets connect
            prompt = "warm cache target prompt " * 8  # > 2 token blocks
            async with httpx.AsyncClient(timeout=60) as c:
                # Long-running request holds its blocks; events land while it
                # decodes (blocks free → 'removed' when it finishes, matching
                # this engine's no-retention cache lifecycle).
                long_req = asyncio.create_task(c.post(
                    "http://127.0.0.1:18513/v1/completions",
                    json={"model": "tiny", "prompt": prompt, "max_tokens": 80,
                          "ignore_eos": True}))
                first_pod = None
                for _ in range(900):  # generous: first jit compiles serialize here
                    await asyncio.sleep(0.05)
                    for pod in ("127.0.0.1:18511", "127.0.0.1:18512"):
                        if scorer.index.pod_block_count(pod) > 0:
                            first_pod = pod
                            break
                    if first_pod:
                        break
                if first_pod is None:
                    diags = {
                        "long_req_done": long_req.done(),
                        "hub_subs": [len(e.engine.kv_events.hub._subscribers)
                                     if e.engine.kv_events and e.engine.kv_events.hub
                                     else -1 for e in engines],
                        "hub_pushed": [e.engine.kv_events.hub.pushed
                                       if e.engine.kv_events and e.engine.kv_events.hub
                                       else -1 for e in engines],
                        "hub_delivered": [e.engine.kv_events.hub.delivered
                                          if e.engine.kv_events and e.engine.kv_events.hub
                                          else -1 for e in engines],
                        "pub_bound": [e.engine.kv_events is not None
                                      and e.engine.kv_events._sock is not None
                                      for e in engines],
                        "slots": [[s is not None for s in e.engine.slots]
                                  for e in engines],
                        "prompt_tokens": [
                            e.engine.telemetry.prompt_tokens._value.get()
                            for e in engines],
                    }
                    raise AssertionError(f"no kv events reached the index: {diags}")

                # While the index holds the pod's blocks, scoring the same
                # prompt must prefer that pod with a full prefix hit. (Routing
                # stickiness end-to-end is racy against request lifetime on
                # slow CI hosts; the approx-prefix e2e covers it. Here we
                # assert the exact-index scoring signal itself.)
                from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
                    InferenceRequest, InferenceRequestBody)

                tok_ids = engines[0].engine.tokenizer.encode(prompt)
                ireq = InferenceRequest(
                    request_id="probe", target_model="tiny",
                    body=InferenceRequestBody(
                        completions={"model": "tiny", "prompt": prompt},
                        tokenized_prompt=tok_ids))
                eps = gw.datastore.endpoint_list()
                scores = scorer.score(None, None, ireq, eps)
                other = [p for p in ("127.0.0.1:18511", "127.0.0.1:18512")
                         if p != first_pod][0]
                assert scores[first_pod] > 0.9, scores
                assert scores[other] == 0.0, scores
                r1 = await long_req
                assert r1.headers["x-gateway-destination-endpoint-served"] == first_pod
        finally:
            await gw.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())
