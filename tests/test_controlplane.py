"""Leader election + config reconciler (VERDICT r1 item 8): the standalone
analogues of the reference's lease election (runner.go:306-316) and CRD
reconcilers (pkg/epp/controller), including the disruption-test shape
(test/e2e/disruption_test.go:86-316): leader serves, follower not-ready,
leader death → takeover."""

import asyncio
import json

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.controlplane import (
    ConfigReconciler,
    LeaseConfig,
    LeaseElector,
)
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


def _lease(path, holder, dur=0.6, renew=0.1):
    return LeaseConfig(path=str(path), holder_id=holder,
                       lease_duration_s=dur, renew_interval_s=renew)


def test_lease_acquire_and_follower_blocked(tmp_path):
    async def body():
        a = LeaseElector(_lease(tmp_path / "lease", "a"))
        b = LeaseElector(_lease(tmp_path / "lease", "b"))
        await a.start()
        await asyncio.sleep(0.3)
        await b.start()
        await asyncio.sleep(0.3)
        assert a.is_leader and not b.is_leader
        await a.stop()
        await b.stop()

    asyncio.run(body())


def test_graceful_release_hands_over_fast(tmp_path):
    async def body():
        a = LeaseElector(_lease(tmp_path / "lease", "a"))
        b = LeaseElector(_lease(tmp_path / "lease", "b"))
        await a.start()
        await asyncio.sleep(0.25)
        await b.start()
        await asyncio.sleep(0.25)
        assert a.is_leader
        await a.stop(graceful=True)  # zeroes the expiry
        for _ in range(30):
            await asyncio.sleep(0.1)
            if b.is_leader:
                break
        assert b.is_leader
        await b.stop()

    asyncio.run(body())


def test_crash_takeover_after_expiry(tmp_path):
    async def body():
        a = LeaseElector(_lease(tmp_path / "lease", "a"))
        b = LeaseElector(_lease(tmp_path / "lease", "b"))
        await a.start()
        await asyncio.sleep(0.25)
        await b.start()
        assert not b.is_leader
        # Simulate a crash: the renew loop dies WITHOUT releasing the lease.
        await a.stop(graceful=False)
        took = None
        for i in range(40):
            await asyncio.sleep(0.1)
            if b.is_leader:
                took = i * 0.1
                break
        assert b.is_leader, "follower never took over"
        assert took >= 0.2  # not before the lease expired
        await b.stop()

    asyncio.run(body())


def test_config_reconciler_converges_datastore(tmp_path):
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text("""
pool:
  endpoints:
    - {address: 10.0.0.1, port: 8200}
objectives:
  - {name: premium, priority: 5}
modelRewrites:
  - {source: old-model, targets: [{model: new-model, weight: 1}]}
""")
    ds = Datastore()
    rec = ConfigReconciler(str(cfg_path), ds)
    assert rec.reconcile_once()
    assert [e.metadata.address_port for e in ds.endpoint_list()] == ["10.0.0.1:8200"]
    assert ds.objective_get("premium").priority == 5
    assert ds.rewrite_for("old-model") is not None

    # Declarative update: endpoint replaced, objective changed, rewrite gone.
    cfg_path.write_text("""
pool:
  endpoints:
    - {address: 10.0.0.2, port: 8200}
    - {address: 10.0.0.3, port: 8200}
objectives:
  - {name: batch, priority: -1}
""")
    assert rec.reconcile_once()
    assert sorted(e.metadata.address_port for e in ds.endpoint_list()) == [
        "10.0.0.2:8200", "10.0.0.3:8200"]
    assert ds.objective_get("premium") is None
    assert ds.objective_get("batch").priority == -1
    assert ds.rewrite_for("old-model") is None

    # Unchanged mtime → no-op; malformed content → keep last good state.
    assert not rec.reconcile_once()
    cfg_path.write_text("pool: [broken")
    assert not rec.reconcile_once()
    assert len(ds.endpoint_list()) == 2


def test_ha_gateway_failover_e2e(tmp_path):
    """Two gateway replicas sharing a lease: leader 200, follower 503 on
    /health; kill the leader → the follower takes over and serves."""
    ENG, GW_A, GW_B = 18741, 18742, 18743
    lease = str(tmp_path / "lease")

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {ENG}}}
"""
        gw_a = build_gateway(cfg, port=GW_A, poll_interval=0.02, lease_path=lease)
        gw_b = build_gateway(cfg, port=GW_B, poll_interval=0.02, lease_path=lease)
        # Fast elections for the test.
        for gw in (gw_a, gw_b):
            gw.elector.cfg.lease_duration_s = 0.6
            gw.elector.cfg.renew_interval_s = 0.1
        await gw_a.start()
        await asyncio.sleep(0.3)
        await gw_b.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                await asyncio.sleep(0.4)
                ra = await c.get(f"http://127.0.0.1:{GW_A}/health")
                rb = await c.get(f"http://127.0.0.1:{GW_B}/health")
                assert ra.status_code == 200
                assert rb.status_code == 503
                assert rb.json()["status"] == "follower"

                # Leader serves inference; the follower (not-ready) is what a
                # health-checking LB would skip.
                r = await c.post(f"http://127.0.0.1:{GW_A}/v1/completions",
                                 json={"model": "tiny", "prompt": "x",
                                       "max_tokens": 2})
                assert r.status_code == 200

                # Disruption: stop the leader (graceful release).
                await gw_a.stop()
                for _ in range(30):
                    await asyncio.sleep(0.1)
                    if gw_b.elector.is_leader:
                        break
                rb = await c.get(f"http://127.0.0.1:{GW_B}/health")
                assert rb.status_code == 200
                r = await c.post(f"http://127.0.0.1:{GW_B}/v1/completions",
                                 json={"model": "tiny", "prompt": "y",
                                       "max_tokens": 2})
                assert r.status_code == 200
        finally:
            await gw_b.stop()
            await eng.stop()

    asyncio.run(body())
