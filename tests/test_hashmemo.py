"""Scheduling hot-path: prefix-hash memo, batched KV-index matching, and the
verify-hotpath lint (ISSUE 4 — one cycle must cost O(blocks + endpoints),
not O(endpoints × blocks))."""

import asyncio
import pathlib
import sys

import pytest

from llm_d_inference_scheduler_tpu.router import hashmemo
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    ProfileRunResult,
    SchedulingResult,
)
from llm_d_inference_scheduler_tpu.router.hashmemo import request_prefix_hashes
from llm_d_inference_scheduler_tpu.router.plugins.precise_prefix import (
    KvBlockIndex,
    PrecisePrefixCacheScorer,
    drain_sse_frames,
)
from llm_d_inference_scheduler_tpu.router.requestcontrol.producers import (
    ApproxPrefixCacheProducer,
    TokenProducer,
)
from llm_d_inference_scheduler_tpu.utils import hashing
from llm_d_inference_scheduler_tpu.utils.hashing import chain_block_hashes


@pytest.fixture(autouse=True)
def _fresh_global_lru():
    hashmemo.global_lru_clear()
    yield
    hashmemo.global_lru_clear()


def _request(rid="r1", prompt="hello world " * 40, tokens=None):
    return InferenceRequest(
        request_id=rid, target_model="tiny",
        body=InferenceRequestBody(completions={"prompt": prompt},
                                  tokenized_prompt=tokens))


def _endpoints(n, block_size=16, num_blocks=4096):
    eps = []
    for i in range(n):
        ep = Endpoint(EndpointMetadata(name=f"ep{i}", address=f"10.8.0.{i}",
                                       port=9000))
        ep.metrics.cache_block_size = block_size
        ep.metrics.cache_num_blocks = num_blocks
        eps.append(ep)
    return eps


def _result_for(ep):
    return SchedulingResult(
        profile_results={"default": ProfileRunResult(target_endpoints=[ep])},
        primary_profile_name="default")


# ---- memo semantics -------------------------------------------------------


def test_memo_parity_with_direct_chain():
    # Char-based (no tokenized prompt) and token-based, several block sizes:
    # the memo is a pure cache — values must be bit-identical to the direct
    # computation.
    for tokens in (None, list(range(100, 180))):
        for bs in (4, 16, 64):
            req = _request(tokens=tokens)
            direct = chain_block_hashes("tiny", tokens,
                                        req.body.prompt_text(), bs)
            assert request_prefix_hashes(req, bs) == direct
            # Second read: same values, served from the memo.
            assert request_prefix_hashes(req, bs) == direct


def test_memo_empty_token_list_falls_back_to_char_hashing():
    # An engine render reply of [] must behave like the direct call sites
    # did (`if token_ids:` truthiness): char-based chains, never an empty
    # token chain that zeroes every prefix score.
    req = _request(tokens=[])
    assert request_prefix_hashes(req, 16) == chain_block_hashes(
        "tiny", None, req.body.prompt_text(), 16)


def test_memo_invalidated_by_tokenization_upgrade():
    # TokenProducer sets tokenized_prompt mid-cycle: a char-based chain
    # memoized before the upgrade must never be served afterwards.
    req = _request()
    char_chain = request_prefix_hashes(req, 16)
    assert char_chain == chain_block_hashes("tiny", None,
                                            req.body.prompt_text(), 16)
    req.body.tokenized_prompt = list(range(200, 264))
    tok_chain = request_prefix_hashes(req, 16)
    assert tok_chain == chain_block_hashes("tiny", req.body.tokenized_prompt,
                                           "", 16)
    assert tok_chain != char_chain


def test_memo_reuse_on_reschedule_no_recompute():
    # The retry/failover path re-runs producers' pre_request and the scorer
    # against the SAME request object: zero new chain computations.
    req = _request(tokens=list(range(0, 96)))
    eps = _endpoints(128)
    prod = ApproxPrefixCacheProducer("approx")
    scorer = PrecisePrefixCacheScorer("precise")

    before = hashing.CHAIN_COMPUTES
    asyncio.run(prod.produce(None, req, eps))
    scorer.score(None, None, req, eps)
    prod.pre_request(None, req, _result_for(eps[0]))
    scorer.pre_request(None, req, _result_for(eps[0]))
    first_cycle = hashing.CHAIN_COMPUTES - before
    # The O-claim: one full 128-endpoint cycle (produce + score + both
    # pre_request hooks) computes the chain at most twice — not O(endpoints).
    assert first_cycle <= 2

    before = hashing.CHAIN_COMPUTES
    scorer.score(None, None, req, [ep for ep in eps if ep is not eps[0]])
    prod.pre_request(None, req, _result_for(eps[1]))
    scorer.pre_request(None, req, _result_for(eps[1]))
    assert hashing.CHAIN_COMPUTES - before == 0  # reschedule: pure reuse


def test_global_lru_serves_repeat_prompts_across_requests():
    tokens = list(range(500, 564))
    r1 = _request(rid="a", tokens=list(tokens))
    r2 = _request(rid="b", tokens=list(tokens))  # fresh request object
    h1 = request_prefix_hashes(r1, 16)
    before = hashing.CHAIN_COMPUTES
    assert request_prefix_hashes(r2, 16) == h1
    assert hashing.CHAIN_COMPUTES - before == 0  # LRU hit, no xxhash at all


def test_global_lru_distinguishes_model_mode_and_block_size():
    tokens = list(range(64))
    req = _request(tokens=tokens)
    assert request_prefix_hashes(req, 16) != request_prefix_hashes(req, 32)
    other = InferenceRequest(
        request_id="m2", target_model="other-model",
        body=InferenceRequestBody(completions={"prompt": "x"},
                                  tokenized_prompt=list(tokens)))
    assert request_prefix_hashes(other, 16) != request_prefix_hashes(req, 16)


# ---- batched KV-index matching -------------------------------------------


def test_match_prefix_consecutive_walk():
    idx = KvBlockIndex()
    idx.add("pod", [1, 2, 3])
    idx.add_speculative("pod", [4])
    assert idx.match_prefix("pod", [1, 2, 3, 4, 99]) == 4
    assert idx.match_prefix("pod", [2, 3]) == 2
    assert idx.match_prefix("pod", [99, 1]) == 0  # must match from the start
    assert idx.match_prefix("other", [1]) == 0
    assert idx.match_prefix("pod", []) == 0


def test_match_prefix_batched_expiry_sweep():
    idx = KvBlockIndex()
    idx.add("pod", [1, 2])
    idx.add_speculative("pod", [3])
    # Force-expire entry 2 and the speculative 3; the next lookup must not
    # count either, and the due per-pod sweep must physically drop the
    # confirmed one (per-pod — a match never scans the whole index).
    idx._by_pod["pod"][2] = 0.0
    idx._speculative[("pod", 3)] = 0.0
    idx._next_pod_sweep["pod"] = 0.0
    assert idx.match_prefix("pod", [1, 2, 3]) == 1
    assert 2 not in idx._by_pod["pod"]
    # Speculative garbage is collected on the subscriber write path (add),
    # never on the scoring path.
    idx._next_spec_sweep = 0.0
    idx.add("other", [9])
    assert ("pod", 3) not in idx._speculative


def test_holds_still_honors_expiry():
    idx = KvBlockIndex()
    idx.add("pod", [7])
    assert idx.holds("pod", 7)
    idx._by_pod["pod"][7] = 0.0
    assert not idx.holds("pod", 7)


# ---- producer satellites --------------------------------------------------


def test_pod_lru_resizes_when_cache_geometry_appears():
    prod = ApproxPrefixCacheProducer("approx")
    ep = _endpoints(1, num_blocks=0)[0]  # first scrape not landed yet
    lru = prod._lru_for(ep)
    assert lru.capacity == prod.lru_capacity  # default fallback, not pinned
    for h in range(16):
        lru.add(h)
    ep.metrics.cache_num_blocks = 8  # real geometry lands
    lru2 = prod._lru_for(ep)
    assert lru2 is lru and lru2.capacity == 8
    assert len(lru2) == 8  # trimmed to the real capacity, LRU end dropped
    assert lru2.contains(15) and not lru2.contains(0)
    ep.metrics.cache_num_blocks = 32  # geometry can also grow
    assert prod._lru_for(ep).capacity == 32
    # A scrape flapping back to 0 (family missing one poll) keeps the last
    # known capacity instead of shrinking to the default and evicting.
    ep.metrics.cache_num_blocks = 0
    assert prod._lru_for(ep).capacity == 32


def test_scheduler_keys_track_reordering_filter():
    # The Filter protocol doesn't forbid same-length reordering: scores must
    # still land on the right endpoints.
    from llm_d_inference_scheduler_tpu.router.plugins.pickers import (
        MaxScorePicker,
    )
    from llm_d_inference_scheduler_tpu.router.plugins.profile_handlers import (
        SingleProfileHandler,
    )
    from llm_d_inference_scheduler_tpu.router.scheduling.scheduler import (
        Scheduler,
        SchedulerProfile,
        WeightedScorer,
    )

    class ReverseFilter:
        def typed_name(self):
            return "reverse-filter"

        def filter(self, ctx, state, request, endpoints):
            return list(reversed(endpoints))

    class LastWinsScorer:
        def typed_name(self):
            return "last-wins-scorer"

        def score(self, ctx, state, request, endpoints):
            return {eps[-1].metadata.address_port: 1.0
                    for eps in [endpoints]}

    eps = _endpoints(4)
    profile = SchedulerProfile(
        "default", [ReverseFilter()],
        [WeightedScorer(LastWinsScorer(), 1.0)],
        MaxScorePicker("max-score-picker"))
    sched = Scheduler({"default": profile}, SingleProfileHandler())
    result = sched.schedule(None, _request(), eps)
    # After reversal the last candidate is eps[0]; a stale key snapshot
    # would pair its 1.0 score with a different endpoint.
    picked = result.primary().target_endpoints[0]
    assert picked.metadata.address_port == eps[0].metadata.address_port


def test_token_producer_cache_keys_are_fingerprints():
    prod = TokenProducer("tok")
    prompt = "a very long prompt " * 200
    ids = [1, 2, 3]
    prod._cache[("tiny", hashing.text_fingerprint(prompt))] = ids
    req = _request(prompt=prompt)
    asyncio.run(prod.produce(None, req, _endpoints(1)))
    assert req.body.tokenized_prompt == ids  # hit without any HTTP call
    # No key may pin prompt text verbatim.
    assert all(isinstance(m, str) and isinstance(fp, int)
               for m, fp in prod._cache)


# ---- SSE find-offset parsing ---------------------------------------------


def test_drain_sse_frames_across_chunk_boundaries():
    buf = ""
    frames = []
    for chunk in ["data: {\"a\"", ": 1}\n\ndata: {\"b\": 2}\n", "\n",
                  "data: partial"]:
        buf += chunk
        got, buf = drain_sse_frames(buf)
        frames.extend(got)
    assert frames == ['data: {"a": 1}', 'data: {"b": 2}']
    assert buf == "data: partial"  # incomplete frame stays buffered
    got, buf = drain_sse_frames(buf + "\n\n")
    assert got == ["data: partial"] and buf == ""


# ---- hot-path lint hook ---------------------------------------------------


def test_verify_hotpath_lint_clean():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                           / "scripts"))
    import verify_hotpath

    assert verify_hotpath.check() == []
