"""TLS end-to-end (VERDICT r4 missing #1): secure serving on the gateway
HTTP + ext-proc gRPC surfaces (self-signed fallback, cert reload) and the
sidecar's SecureServing + per-leg TLS knobs.

Reference: runserver.go:136-171, internal/tls/tls.go:33, certs.go,
pkg/sidecar/proxy/proxy.go:153-166 + proxy_helpers.go:55-100.
"""

import asyncio
import ssl

import httpx
import pytest
from aiohttp import web

# The whole module mints/verifies real certificates: without the
# cryptography wheel every test here would ERROR at setup (longstanding
# tier-1 noise on slim images) — report 6 clean skips instead.
pytest.importorskip("cryptography")

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.tlsutil import (
    TlsServing,
    create_self_signed_cert,
)

ENG, GW, EXTPROC, HEALTH = 18681, 18680, 18682, 18687
SC, PRE, DEC = 18691, 18693, 18695

CFG = """
pool:
  endpoints:
    - {address: 127.0.0.1, port: %d}
plugins:
  - {type: queue-scorer}
schedulingProfiles:
  - name: default
    plugins:
      - {pluginRef: queue-scorer}
""" % ENG


def test_self_signed_certificate_shape():
    """tls.go:33-86 contract: serverAuth EKU, long validity, usable pair."""
    from cryptography import x509
    from cryptography.x509.oid import ExtendedKeyUsageOID

    cert_pem, key_pem = create_self_signed_cert()
    cert = x509.load_pem_x509_certificate(cert_pem)
    eku = cert.extensions.get_extension_for_class(x509.ExtendedKeyUsage)
    assert ExtendedKeyUsageOID.SERVER_AUTH in eku.value
    ku = cert.extensions.get_extension_for_class(x509.KeyUsage).value
    assert ku.digital_signature and ku.key_encipherment
    assert (cert.not_valid_after_utc - cert.not_valid_before_utc).days >= 3649
    san = cert.extensions.get_extension_for_class(
        x509.SubjectAlternativeName).value
    assert "localhost" in san.get_values_for_type(x509.DNSName)
    # The pair must load into a server context.
    ts = TlsServing()
    assert ts.ssl_context is not None
    ts.close()


def test_gateway_https_and_extproc_tls_e2e():
    """Gateway --secure-serving: HTTP over TLS (self-signed), the SAME
    identity on the ext-proc gRPC port, and a full inference roundtrip."""
    from tests.test_extproc_grpc import (
        _call,
        req_body_frame,
        req_headers_frame,
    )

    async def body():
        import json

        import grpc.aio

        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02,
                           grpc_ext_proc_port=EXTPROC,
                           grpc_health_port=HEALTH, secure_serving=True)
        await gw.start()
        try:
            # Plain HTTP must NOT work on a TLS listener.
            async with httpx.AsyncClient(timeout=10) as c:
                with pytest.raises(httpx.HTTPError):
                    await c.get(f"http://127.0.0.1:{GW}/health")

            # Self-signed: clients skip verification (reference deploys set
            # insecure-skip-verify against the fallback cert)...
            async with httpx.AsyncClient(timeout=30, verify=False) as c:
                r = await c.get(f"https://127.0.0.1:{GW}/health")
                assert r.status_code == 200
                r = await c.post(f"https://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "hello",
                                       "max_tokens": 4})
                assert r.status_code == 200
                assert r.json()["choices"][0]["text"]

            # ...but the minted cert carries loopback SANs, so pinning it as
            # a CA also verifies.
            ctx = ssl.create_default_context()
            ctx.load_verify_locations(cadata=gw.tls.cert_pem().decode())
            async with httpx.AsyncClient(timeout=30, verify=ctx) as c:
                r = await c.get(f"https://127.0.0.1:{GW}/health")
                assert r.status_code == 200

            # ext-proc gRPC over the same identity.
            creds = grpc.ssl_channel_credentials(
                root_certificates=gw.tls.cert_pem())
            async with grpc.aio.secure_channel(f"127.0.0.1:{EXTPROC}",
                                               creds) as ch:
                payload = json.dumps({"model": "tiny", "prompt": "hi",
                                      "max_tokens": 2}).encode()
                frames = [
                    req_headers_frame({":path": "/v1/completions",
                                       "content-type": "application/json"}),
                    req_body_frame(payload),
                ]
                responses = await _call(ch, frames)
            assert any(r["oneof"] == "request_body" for r in responses)
            dest = [r["set_headers"].get("x-gateway-destination-endpoint")
                    for r in responses if r["set_headers"]]
            assert f"127.0.0.1:{ENG}" in dest

            # grpc.health.v1 shares the identity too (the reference
            # registers health on the same TLS server as ext-proc).
            from llm_d_inference_scheduler_tpu.router.health_grpc import (
                SERVING,
                serialize_response,
            )

            async with grpc.aio.secure_channel(f"127.0.0.1:{HEALTH}",
                                               creds) as ch:
                check = ch.unary_unary(
                    "/grpc.health.v1.Health/Check",
                    request_serializer=lambda s: b"",
                    response_deserializer=lambda b: b)
                assert await check("") == serialize_response(SERVING)
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_cert_reload(tmp_path):
    """certs.go semantics: rotating tls.crt/tls.key re-arms the listener
    without a restart; new handshakes present the new certificate."""
    from cryptography import x509

    certdir = tmp_path / "certs"
    certdir.mkdir()
    c1, k1 = create_self_signed_cert(common_name="gen-one")
    (certdir / "tls.crt").write_bytes(c1)
    (certdir / "tls.key").write_bytes(k1)

    ts = TlsServing(str(certdir), enable_reload=True)

    async def body():
        async def ok(request):
            return web.Response(text="ok")

        app = web.Application()
        app.add_routes([web.get("/", ok)])
        runner = web.AppRunner(app)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", SC,
                          ssl_context=ts.ssl_context).start()

        def served_cn() -> str:
            raw = ssl.get_server_certificate(("127.0.0.1", SC))
            cert = x509.load_pem_x509_certificate(raw.encode())
            return cert.subject.get_attributes_for_oid(
                x509.NameOID.COMMON_NAME)[0].value

        loop = asyncio.get_running_loop()
        try:
            assert await loop.run_in_executor(None, served_cn) == "gen-one"
            c2, k2 = create_self_signed_cert(common_name="gen-two")
            (certdir / "tls.crt").write_bytes(c2)
            (certdir / "tls.key").write_bytes(k2)
            for _ in range(100):  # poll(1s) + debounce(1 tick)
                await asyncio.sleep(0.2)
                if await loop.run_in_executor(None, served_cn) == "gen-two":
                    break
            else:
                raise AssertionError("certificate never reloaded")
        finally:
            await runner.cleanup()

    try:
        asyncio.run(body())
    finally:
        ts.close()


def test_full_tls_pd_stack_token_parity():
    """Composed TLS P/D: client → sidecar (HTTPS) → decode engine (TLS)
    with the 2-phase protocol's prefill leg to a TLS prefill engine —
    every HTTP leg encrypted, the KV pull riding the (non-HTTP) device
    transfer wire, tokens equal to a monolithic engine."""
    from llm_d_inference_scheduler_tpu.router.sidecar.proxy import (
        Sidecar,
        SidecarConfig,
    )

    M, P2, D2, S2 = 18696, 18697, 18698, 18699
    PROMPT = [1] + [(i * 11) % 400 + 3 for i in range(40)]

    async def body():
        mono = EngineServer(EngineConfig(backend="tpu", model="tiny",
                                         port=M, max_batch=4,
                                         max_model_len=256,
                                         kv_events_port=0))
        await mono.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post(f"http://127.0.0.1:{M}/v1/completions",
                                 json={"prompt": PROMPT, "max_tokens": 6,
                                       "temperature": 0, "ignore_eos": True})
                mono_text = r.json()["choices"][0]["text"]
        finally:
            await mono.stop()

        pre = EngineServer(EngineConfig(backend="tpu", model="tiny",
                                        port=P2, role="prefill", max_batch=4,
                                        max_model_len=256, kv_events_port=0,
                                        secure_serving=True))
        dec = EngineServer(EngineConfig(backend="tpu", model="tiny",
                                        port=D2, role="decode", max_batch=4,
                                        max_model_len=256, kv_events_port=0,
                                        secure_serving=True))
        await pre.start()
        await dec.start()
        sc = Sidecar(SidecarConfig(
            port=S2, decoder_url=f"https://127.0.0.1:{D2}",
            secure_serving=True,
            use_tls_for_prefiller=True, insecure_skip_verify_prefiller=True,
            use_tls_for_decoder=True, insecure_skip_verify_decoder=True))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=60, verify=False) as c:
                r = await c.post(
                    f"https://127.0.0.1:{S2}/v1/completions",
                    json={"prompt": PROMPT, "max_tokens": 6,
                          "temperature": 0, "ignore_eos": True},
                    headers={"x-prefiller-host-port": f"127.0.0.1:{P2}"})
                assert r.status_code == 200, r.text
                assert r.json()["choices"][0]["text"] == mono_text
            # The KV moved over the device transfer wire, not plaintext HTTP.
            assert dec.engine.kv_import_device_count == 1
            assert dec.engine.kv_import_host_count == 0
        finally:
            await sc.stop()
            await dec.stop()
            await pre.stop()

    asyncio.run(body())


def test_gateway_routes_to_tls_engine():
    """Router side of engine TLS: a pool endpoint declared `scheme: https`
    is scraped (metrics) and proxied (completions) over TLS with
    skip-verify — the reference scrape client's insecureSkipVerify
    default against pod-local certs."""
    E2, G2 = 18694, 18692

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=E2,
                                        sim_decode_ms_per_token=1.0,
                                        secure_serving=True))
        await eng.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {E2}, scheme: https}}
plugins:
  - {{type: queue-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: queue-scorer}}
""", port=G2, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(f"http://127.0.0.1:{G2}/v1/completions",
                                 json={"model": "tiny", "prompt": "hello",
                                       "max_tokens": 4})
                assert r.status_code == 200, r.text
                assert r.json()["choices"][0]["text"]
            # The metrics collector scraped the https endpoint.
            ep = gw.datastore.endpoint_list()[0]
            for _ in range(100):
                if ep.metrics.fresh:
                    break
                await asyncio.sleep(0.05)
            assert ep.metrics.fresh
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_sidecar_secure_serving_and_tls_prefill_leg():
    """proxy.go:153-166: the sidecar serves HTTPS and drives the prefill
    leg over TLS (with per-leg skip-verify against the pod-local cert)."""

    async def body():
        calls = {"n": 0, "body": None}

        # Fake prefill worker serving HTTPS with its own pod-local cert.
        pre_tls = TlsServing()

        async def prefill(request):
            calls["n"] += 1
            calls["body"] = await request.json()
            return web.json_response(
                {"choices": [{"text": "x", "finish_reason": "length"}],
                 "kv_transfer_params": None})

        app = web.Application()
        app.add_routes([web.post("/v1/completions", prefill)])
        runner = web.AppRunner(app)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", PRE,
                          ssl_context=pre_tls.ssl_context).start()

        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=DEC,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()

        from llm_d_inference_scheduler_tpu.router.sidecar.proxy import (
            Sidecar,
            SidecarConfig,
        )

        sc = Sidecar(SidecarConfig(
            port=SC + 1, decoder_url=f"http://127.0.0.1:{DEC}",
            secure_serving=True,
            use_tls_for_prefiller=True,
            insecure_skip_verify_prefiller=True))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=30, verify=False) as c:
                r = await c.post(
                    f"https://127.0.0.1:{SC + 1}/v1/completions",
                    json={"model": "tiny", "prompt": "hello",
                          "max_tokens": 4},
                    headers={"x-prefiller-host-port": f"127.0.0.1:{PRE}"})
                assert r.status_code == 200
                assert r.json()["choices"][0]["text"]
            # The prefill leg really rode TLS to the prefiller (the server
            # only listens on HTTPS) and carried the 2-phase contract.
            assert calls["n"] == 1
            assert calls["body"]["kv_transfer_params"] == {
                "do_remote_decode": True}
            assert calls["body"]["max_tokens"] == 1
        finally:
            await sc.stop()
            await eng.stop()
            await runner.cleanup()
            pre_tls.close()

    asyncio.run(body())
