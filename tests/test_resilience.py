"""Resilient data plane: retry/failover, circuit breaking, deadlines, chaos.

Unit tier: the resilience primitives (token-bucket retry budget, breaker
state machine, deadline arithmetic, deterministic fault decisions). E2E
tier: hermetic gateway/sidecar/engine stacks with the engine-side chaos
shim injecting resets, 503s, and mid-stream stalls — every client-visible
guarantee (zero 502s under failover, bounded retry storms, breaker-open
visibility in /metrics, half-open recovery, drain-retry with zero errors)
is asserted over real HTTP. Chaos decisions are a stable hash of
(CHAOS_SEED, kind, request id), so `make test-chaos` reruns are
bit-identical.
"""

import asyncio
import os

import httpx
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerRegistry,
    CircuitBreaker,
    Deadline,
    FaultInjector,
    ResilienceConfig,
    RetryBudget,
)
from llm_d_inference_scheduler_tpu.router.sidecar import Sidecar, SidecarConfig

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


def run(coro):
    return asyncio.run(coro)


# ---- unit tier -----------------------------------------------------------


def test_retry_budget_token_bucket():
    clock = [0.0]
    b = RetryBudget(ratio=0.5, min_per_sec=1.0, burst=2.0,
                    clock=lambda: clock[0])
    # Starts full; retries drain it.
    assert b.try_spend()
    assert b.try_spend()
    assert not b.try_spend()
    # Deposits (one per admitted request) refill by ratio.
    b.deposit()
    b.deposit()
    assert b.try_spend()
    assert not b.try_spend()
    # Time trickle refills too, capped at burst.
    clock[0] += 10.0
    assert b.tokens == pytest.approx(2.0)
    assert b.try_spend() and b.try_spend() and not b.try_spend()


def test_circuit_breaker_state_machine():
    clock = [0.0]
    cb = CircuitBreaker(failure_threshold=2, open_s=5.0,
                        half_open_successes=2, clock=lambda: clock[0])
    assert cb.state == CLOSED and cb.allow()
    cb.record_failure()
    # A success resets the consecutive-failure count.
    cb.record_success()
    cb.record_failure()
    assert cb.state == CLOSED
    cb.record_failure()
    assert cb.state == OPEN and not cb.allow() and not cb.would_allow()
    # Open window elapses -> half-open admits exactly ONE in-flight probe.
    clock[0] += 5.0
    assert cb.allow()
    assert cb.state == HALF_OPEN
    assert not cb.allow()  # second concurrent probe rejected
    cb.record_success()
    assert cb.state == HALF_OPEN  # needs two successes to close
    assert cb.allow()
    cb.record_success()
    assert cb.state == CLOSED
    # Probe failure reopens immediately.
    cb.record_failure()
    cb.record_failure()
    clock[0] += 5.0
    assert cb.allow() and cb.state == HALF_OPEN
    cb.record_failure()
    assert cb.state == OPEN


def test_breaker_probe_slot_released_on_abandoned_attempt():
    """An allow()ed attempt that never reaches an outcome (retry-budget
    fast-fail, caller cancelled, non-retryable 5xx path) must release the
    half-open probe slot — otherwise the endpoint is unprobeable forever."""
    clock = [0.0]
    cb = CircuitBreaker(failure_threshold=1, open_s=1.0,
                        clock=lambda: clock[0])
    cb.record_failure()
    clock[0] += 1.0
    assert cb.allow()          # half-open: probe slot claimed
    assert not cb.allow()
    cb.release()               # attempt abandoned with no outcome
    assert cb.allow()          # slot free again
    cb.record_success()
    assert cb.state == CLOSED
    # release() outside half-open is a no-op.
    cb.release()
    assert cb.state == CLOSED and cb.allow()


def test_breaker_registry_gauge_and_removal():
    from prometheus_client import generate_latest

    from llm_d_inference_scheduler_tpu.router.metrics import REGISTRY

    clock = [0.0]
    reg = BreakerRegistry(failure_threshold=1, open_s=60.0,
                          clock=lambda: clock[0])
    key = "10.9.9.9:1234"  # unique: the router REGISTRY is process-global
    assert reg.allow(key)
    reg.record_failure(key)
    assert reg.state(key) == OPEN and not reg.allow(key)
    text = generate_latest(REGISTRY).decode()
    assert ('router_endpoint_circuit_breaker_state{endpoint="%s"} 2.0'
            % key) in text
    reg.remove(key)
    # The state gauge drops the departed endpoint's label (the transitions
    # counter keeps its history — counters are monotonic by contract).
    gauge_lines = [l for l in generate_latest(REGISTRY).decode().splitlines()
                   if l.startswith("router_endpoint_circuit_breaker_state{")]
    assert not any(key in l for l in gauge_lines)
    assert reg.state(key) == CLOSED  # unknown endpoints default closed


def test_deadline_parse_decrement_and_header():
    clock = [100.0]
    d = Deadline.from_headers({"x-request-timeout": "2.5"},
                              clock=lambda: clock[0])
    assert d is not None and not d.expired
    assert d.remaining_s == pytest.approx(2.5)
    clock[0] += 1.0
    assert d.header_value() == "1.500"
    clock[0] += 2.0
    assert d.expired and d.remaining_s == 0.0
    # Absent header + no default -> no deadline; default applies when set.
    assert Deadline.from_headers({}) is None
    d = Deadline.from_headers({}, default_s=3.0, clock=lambda: clock[0])
    assert d is not None and d.remaining_s == pytest.approx(3.0)
    # A forwarded zero budget is an already-expired deadline, not "none".
    d = Deadline.from_headers({"x-request-timeout": "0.000"},
                              clock=lambda: clock[0])
    assert d is not None and d.expired
    # Garbage header falls back to the default.
    assert Deadline.from_headers({"x-request-timeout": "soon"}) is None
    # Client asks are capped.
    d = Deadline.from_headers({"x-request-timeout": "9999"}, max_s=10.0,
                              clock=lambda: clock[0])
    assert d.remaining_s <= 10.0


def test_fault_injector_spec_and_determinism():
    inj = FaultInjector.from_spec("reset:50,delay:100:250", seed=CHAOS_SEED)
    assert [r.kind for r in inj.rules] == ["reset", "delay"]
    assert inj.rules[1].arg == 250.0
    # Same request id -> same decision, every time.
    decisions = {rid: (inj.decide(rid) or type("n", (), {"kind": None})).kind
                 for rid in (f"req-{i}" for i in range(64))}
    for rid, kind in decisions.items():
        got = inj.decide(rid)
        assert (got.kind if got else None) == kind
    # pct 50 + a 100% fallthrough rule: both kinds appear across 64 ids.
    assert set(decisions.values()) == {"reset", "delay"}
    # Gating: disabled injector never fires; empty spec means no injector.
    inj.enabled = False
    assert inj.decide("req-0") is None
    assert FaultInjector.from_spec("") is None
    assert FaultInjector.from_spec(None) is None
    with pytest.raises(ValueError):
        FaultInjector.from_spec("meteor:100")


# ---- e2e tier ------------------------------------------------------------


def _metric_value(text: str, needle: str) -> float:
    for line in text.splitlines():
        if line.startswith(needle + " ") or (
                line.startswith(needle) and line[len(needle)] in "{ "):
            return float(line.rsplit(" ", 1)[-1])
    return 0.0


async def _sim(port, **kw):
    kw.setdefault("backend", "sim")
    kw.setdefault("model", "tiny")
    kw.setdefault("max_batch", 8)
    kw.setdefault("sim_decode_ms_per_token", 1.0)
    s = EngineServer(EngineConfig(port=port, **kw))
    await s.start()
    return s


def test_gateway_retries_draining_sidecar_zero_client_errors():
    """Drain lifecycle end-to-end (PR 1's retryable 503s finally have a
    consumer): a draining sidecar's `x-removal-reason: sidecar-draining`
    503 is retried by the gateway onto the healthy endpoint with ZERO
    client-visible errors."""
    GW, SCA, SCB, EA, EB = 18740, 18741, 18742, 18743, 18744
    # Low breaker threshold: the draining sidecar's breaker OPENS mid-run,
    # which also regression-tests the reschedule exclusion set — an open
    # endpoint the scheduler re-picks must not strand the request while a
    # healthy endpoint exists.
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SCA}}}
    - {{address: 127.0.0.1, port: {SCB}}}
resilience:
  breakerFailureThreshold: 3
  breakerOpenS: 60
"""

    async def body():
        ea, eb = await _sim(EA), await _sim(EB)
        sca = Sidecar(SidecarConfig(port=SCA, decoder_url=f"http://127.0.0.1:{EA}"))
        scb = Sidecar(SidecarConfig(port=SCB, decoder_url=f"http://127.0.0.1:{EB}"))
        await sca.start()
        await scb.start()
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            await sca.begin_drain()  # A now 503s every generate request
            async with httpx.AsyncClient(timeout=30) as c:
                served = []
                for i in range(16):
                    r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                     json={"model": "tiny", "prompt": "hi",
                                           "max_tokens": 2})
                    assert r.status_code == 200, (i, r.status_code, r.text)
                    served.append(
                        r.headers["x-gateway-destination-endpoint-served"])
                # Every request landed on the healthy sidecar.
                assert set(served) == {f"127.0.0.1:{SCB}"}
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, 'router_retries_total{kind="status"}') > 0
        finally:
            await gw.stop()
            await sca.stop()
            await scb.stop()
            await ea.stop()
            await eb.stop()

    run(body())


def test_chaos_failover_breaker_opens_and_recovers():
    """The acceptance scenario: chaos kills one decode endpoint mid-run
    (connection reset on every request). All traffic still completes via
    failover (zero client-visible 502s), the ejected endpoint shows
    breaker-open state in /metrics, and after the open window a half-open
    probe recovers it."""
    GW, EA, EB = 18750, 18751, 18752
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA}}}
    - {{address: 127.0.0.1, port: {EB}}}
plugins:
  - {{type: circuit-breaker-filter}}
  - {{type: queue-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: circuit-breaker-filter}}
      - {{pluginRef: queue-scorer}}
resilience:
  maxAttempts: 3
  breakerFailureThreshold: 2
  breakerOpenS: 0.5
"""

    async def body():
        ea = await _sim(EA, chaos="reset:100", chaos_seed=CHAOS_SEED)
        eb = await _sim(EB)
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                statuses = []
                for i in range(20):
                    r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                     json={"model": "tiny",
                                           "prompt": f"p{i}", "max_tokens": 2},
                                     headers={"x-request-id": f"chaos-{i}"})
                    statuses.append(r.status_code)
                # >= 99% success; with failover available there are ZERO
                # client-visible 502s.
                assert statuses.count(200) == len(statuses), statuses
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, 'router_endpoint_circuit_breaker_state'
                       '{endpoint="127.0.0.1:%d"}' % EA) == 2.0  # open
                assert _metric_value(
                    m, 'router_retries_total{kind="connect"}') > 0

                # Heal the endpoint; after the open window a half-open probe
                # closes the breaker and traffic returns to A.
                ea.chaos.enabled = False
                await asyncio.sleep(0.6)
                served = set()
                for i in range(30):
                    r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                     json={"model": "tiny",
                                           "prompt": f"r{i}", "max_tokens": 1})
                    assert r.status_code == 200
                    served.add(
                        r.headers["x-gateway-destination-endpoint-served"])
                    if f"127.0.0.1:{EA}" in served:
                        break
                assert f"127.0.0.1:{EA}" in served
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, 'router_endpoint_circuit_breaker_state'
                       '{endpoint="127.0.0.1:%d"}' % EA) == 0.0  # closed
        finally:
            await gw.stop()
            await ea.stop()
            await eb.stop()

    run(body())


def test_chaos_retry_budget_bounds_storm():
    """With every endpoint failing and the budget drained, excess failures
    return immediately with x-removal-reason instead of amplifying load:
    total upstream attempts == requests + burst, exactly. (A failed
    endpoint joins the exclusion set, so retries are failovers — two
    chaotic endpoints give each request one retry opportunity.)"""
    GW, EA, EB = 18760, 18761, 18762
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA}}}
    - {{address: 127.0.0.1, port: {EB}}}
resilience:
  maxAttempts: 4
  retryBudgetRatio: 0
  retryBudgetMinPerSec: 0
  retryBudgetBurst: 2
  breakerFailureThreshold: 1000
"""

    async def body():
        ea = await _sim(EA, chaos="http503:100", chaos_seed=CHAOS_SEED)
        eb = await _sim(EB, chaos="http503:100", chaos_seed=CHAOS_SEED)
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                budget_marked = 0
                for i in range(6):
                    r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                     json={"model": "tiny", "prompt": "x",
                                           "max_tokens": 1})
                    assert r.status_code == 503
                    assert r.headers["x-removal-reason"] == "chaos-injected"
                    budget_marked += (r.json().get("retry")
                                      == "retry-budget-exhausted")
                # Once the bucket drains, fast-fails are marked as such.
                assert budget_marked >= 4
                # 6 first attempts + exactly `burst` (2) failover retries
                # hit the engines; the rest failed fast on the empty bucket.
                triggered = (ea.chaos.triggered["http503"]
                             + eb.chaos.triggered["http503"])
                assert triggered == 8, triggered
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, "router_retry_budget_exhausted_total") >= 4
        finally:
            await gw.stop()
            await ea.stop()
            await eb.stop()

    run(body())


def test_chaos_sustained_overload_sheds_at_admission_only():
    """Sustained-overload invariant (router/overload.py): engine delay chaos
    plus >1x offered load, overload control on — requests that were admitted
    and began streaming are NEVER killed by shedding. Sheds happen at
    admission or in-queue only: every non-200 is a 429 carrying a finite
    Retry-After (the overload contract), every 200 stream runs to [DONE].
    Deterministic under the fixed CHAOS_SEED `make test-chaos` pins."""
    GW, EA = 18830, 18831
    cfg = f"""
featureGates: {{flowControl: true}}
overload: {{enabled: true}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA}}}
plugins:
  - {{type: predicted-latency-producer}}
  - {{type: queue-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: queue-scorer}}
"""

    async def body():
        # Every request eats a 40ms injected delay on a 2-slot engine: the
        # pool saturates as soon as more than a handful arrive together.
        ea = await _sim(EA, chaos="delay:100:40", chaos_seed=CHAOS_SEED,
                        max_batch=2, sim_decode_ms_per_token=2.0)
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                url = f"http://127.0.0.1:{GW}/v1/completions"

                # Train the ridge with concurrency variation so the
                # in-flight feature carries signal into the burst.
                for wave in range(3):
                    rs = await asyncio.gather(*[
                        c.post(url, json={"model": "tiny",
                                          "prompt": f"w{wave}-{i}",
                                          "max_tokens": 2})
                        for i in range(4)])
                    assert all(r.status_code == 200 for r in rs)

                async def one(i: int) -> tuple[int, bool, bool]:
                    """(status, stream_completed, aborted_mid_stream)."""
                    try:
                        async with c.stream(
                                "POST", url,
                                json={"model": "tiny", "prompt": f"o{i}",
                                      "max_tokens": 16, "stream": True},
                                headers={"x-request-id": f"ovl-{i}",
                                         "x-slo-ttft-ms": "250"}) as r:
                            if r.status_code != 200:
                                # Shed path: 429 + finite Retry-After.
                                assert r.status_code == 429, r.status_code
                                ra = r.headers.get("retry-after")
                                assert ra is not None and int(ra) >= 1
                                return r.status_code, False, False
                            saw_done = False
                            async for line in r.aiter_lines():
                                if line.startswith("data: [DONE]"):
                                    saw_done = True
                            return 200, saw_done, not saw_done
                    except (httpx.HTTPError, ConnectionError):
                        return -1, False, True

                # >1x offered load: 48 concurrent streams against 2 slots.
                results = await asyncio.gather(*[one(i) for i in range(48)])
                served = [r for r in results if r[0] == 200]
                shed = [r for r in results if r[0] == 429]
                aborted = [r for r in results if r[2]]
                # THE invariant: nothing admitted-and-streaming was killed.
                assert not aborted, aborted
                assert all(done for _, done, _ in served)
                assert len(served) + len(shed) == len(results)
                # The overload ramp actually engaged both mechanisms' range:
                # some traffic served, some shed at admission/in-queue.
                assert served, results
                assert shed, results
                slo = (await c.get(f"http://127.0.0.1:{GW}/debug/slo")).json()
                assert slo["totals"]["shed"] == len(shed)
                # Every shed is explained: pick one and check the block.
                recs = (await c.get(f"http://127.0.0.1:{GW}/debug/decisions"
                                    "?n=100")).json()["decisions"]
                blocks = [r["shed"] for r in recs if r.get("shed")]
                assert blocks and all("slo_ttft_ms" in b for b in blocks)
        finally:
            await gw.stop()
            await ea.stop()

    run(body())


def test_chaos_pd_prefiller_failover():
    """Chaos kills one prefiller: the sidecar walks the router's ranked
    candidate list (multi-candidate x-prefiller-host-port) to the healthy
    prefiller; the client sees 200, and the failover is counted."""
    GW, SC, DEC, PA, PB = 18770, 18771, 18772, 18773, 18774
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PA}, labels: {{llm-d.ai/role: prefill}}}}
    - {{address: 127.0.0.1, port: {PB}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: max-score-picker
    parameters: {{maxNumOfEndpoints: 2}}
  - type: disagg-profile-handler
    parameters:
      pdDecider: always-disagg-pd-decider
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
      - {{pluginRef: max-score-picker}}
"""

    async def body():
        dec = await _sim(DEC)
        pa = await _sim(PA, chaos="reset:100", chaos_seed=CHAOS_SEED)
        pb = await _sim(PB)
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC}",
                                   prefill_timeout_s=5.0))
        await sc.start()
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                ok = 0
                for i in range(6):
                    r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                     json={"model": "tiny",
                                           "prompt": "failover " * 8,
                                           "max_tokens": 2})
                    ok += r.status_code == 200
                assert ok == 6
                # The healthy prefiller really prefilled (pb counters grew)
                # whenever chaos reset the first candidate.
                mb = (await c.get(f"http://127.0.0.1:{PB}/metrics")).text
                assert _metric_value(mb, "jetstream:prompt_tokens_total") > 0
                ms = (await c.get(f"http://127.0.0.1:{SC}/metrics")).text
                assert _metric_value(
                    ms, "sidecar_prefill_failovers_total") > 0
        finally:
            await gw.stop()
            await sc.stop()
            await pa.stop()
            await pb.stop()
            await dec.stop()

    run(body())


def test_chaos_midstream_stall_counted_not_500():
    """Satellite 1: a mid-stream upstream disconnect after headers are on
    the wire is closed cleanly toward the client (truncated SSE, no 500/
    traceback) and counted in router_upstream_stream_aborted_total."""
    GW, EA = 18780, 18781
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA}}}
"""

    async def body():
        ea = await _sim(EA, chaos="stall:100", chaos_seed=CHAOS_SEED)
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                chunks = []
                async with c.stream(
                        "POST", f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": "x", "stream": True,
                              "max_tokens": 5}) as r:
                    assert r.status_code == 200  # stream started
                    try:
                        async for chunk in r.aiter_bytes():
                            chunks.append(chunk)
                    except httpx.HTTPError:
                        pass  # truncated transfer is acceptable client-side
                assert b"chaos" in b"".join(chunks)
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, "router_upstream_stream_aborted_total") >= 1
        finally:
            await gw.stop()
            await ea.stop()

    run(body())


def test_chaos_sidecar_stream_abort_guard():
    """Satellite 2: the sidecar's decode relay survives a mid-stream engine
    stall — clean truncation plus sidecar_upstream_stream_aborted_total."""
    SC, EA = 18790, 18791

    async def body():
        ea = await _sim(EA, chaos="stall:100", chaos_seed=CHAOS_SEED)
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{EA}"))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                async with c.stream(
                        "POST", f"http://127.0.0.1:{SC}/v1/completions",
                        json={"prompt": "x", "stream": True,
                              "max_tokens": 5}) as r:
                    assert r.status_code == 200
                    try:
                        async for _ in r.aiter_bytes():
                            pass
                    except httpx.HTTPError:
                        pass
                m = (await c.get(f"http://127.0.0.1:{SC}/metrics")).text
                assert _metric_value(
                    m, "sidecar_upstream_stream_aborted_total") >= 1
        finally:
            await sc.stop()
            await ea.stop()

    run(body())


def test_deadline_end_to_end():
    """x-request-timeout bounds the whole pipeline: an expired budget 504s
    at the gateway without dispatching; a budget that expires mid-serve is
    enforced engine-side (504 relayed, wall-clock bounded)."""
    import time as _time

    GW, EA = 18800, 18801
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA}}}
"""

    async def body():
        # 200 ms/token * 100 tokens >> the 1 s budget.
        ea = await _sim(EA, sim_decode_ms_per_token=200.0)
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "x",
                                       "max_tokens": 1},
                                 headers={"x-request-timeout": "0"})
                assert r.status_code == 504
                assert r.headers["x-removal-reason"] == "deadline-exceeded"

                t0 = _time.monotonic()
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "x",
                                       "max_tokens": 100},
                                 headers={"x-request-timeout": "1.0"})
                assert r.status_code == 504
                assert _time.monotonic() - t0 < 5.0
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, "router_request_deadline_exceeded_total") >= 1
        finally:
            await gw.stop()
            await ea.stop()

    run(body())


def test_sidecar_deadline_inherited_by_prefill_leg():
    """The sidecar prefill leg inherits the REMAINING budget: with a dead
    prefiller and a short deadline, fallback-to-decode happens within the
    budget instead of sitting out the full prefill timeout."""
    import time as _time

    SC, DEC = 18810, 18811

    async def body():
        dec = await _sim(DEC)
        # Prefill timeout configured long (60 s); the deadline must win.
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC}",
                                   prefill_timeout_s=60.0))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                t0 = _time.monotonic()
                # 127.0.0.1:9 is closed -> fast refusal is typical, but the
                # per-leg timeout is also clamped to the 2 s budget.
                r = await c.post(
                    f"http://127.0.0.1:{SC}/v1/completions",
                    json={"prompt": "x", "max_tokens": 2},
                    headers={"x-prefiller-host-port": "127.0.0.1:9",
                             "x-request-timeout": "2.0"})
                assert r.status_code == 200  # fell back to local decode
                assert _time.monotonic() - t0 < 5.0
                # An exhausted budget is rejected outright.
                r = await c.post(
                    f"http://127.0.0.1:{SC}/v1/completions",
                    json={"prompt": "x", "max_tokens": 2},
                    headers={"x-request-timeout": "0"})
                assert r.status_code == 504
                m = (await c.get(f"http://127.0.0.1:{SC}/metrics")).text
                assert _metric_value(
                    m, "sidecar_deadline_exceeded_total") >= 1
        finally:
            await sc.stop()
            await dec.stop()

    run(body())


def test_prefiller_candidates_full_list_and_rotation():
    """Satellite 3: the sidecar resolves the FULL ordered candidate list;
    the sampling knob rotates the starting point instead of discarding the
    tail, so failover keeps every candidate reachable."""
    from multidict import CIMultiDict

    class _Req:
        def __init__(self, items):
            self.headers = CIMultiDict(items)

    plain = Sidecar(SidecarConfig())
    r = _Req([("x-prefiller-host-port", "a:1,b:2,c:3")])
    assert plain._prefiller_candidates(r) == ["a:1", "b:2", "c:3"]

    sampling = Sidecar(SidecarConfig(enable_prefiller_sampling=True))
    sampling._prefill_sampler = lambda n: 1
    assert sampling._prefiller_candidates(r) == ["b:2", "c:3", "a:1"]
    assert sampling._pick_prefiller(r) == "b:2"


def test_chaos_pipelined_prefill_503_serial_fallback_zero_errors():
    """Chaos drill (ISSUE 20): every prefill answers 503, the sidecar runs
    in pipelined mode. The pipelined handoff aborts BEFORE the decode leg
    dispatches (first-chunk ack never lands), falls back to the serial
    candidate walk — which also finds the prefiller dead and degrades to
    local decode. The client sees 200 every time; the fallback is counted
    on sidecar_pipeline_fallbacks_total and the request's DecisionRecord
    still carries the full attempt trail."""
    GW, SC, DEC, PRE = 18918, 18919, 18920, 18921
    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider: always-disagg-pd-decider
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

    async def body():
        dec = await _sim(DEC)
        pre = await _sim(PRE, chaos="http503:100", chaos_seed=CHAOS_SEED)
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC}",
                                   prefill_timeout_s=5.0,
                                   pipeline_enabled=True))
        await sc.start()
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                for i in range(4):
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": "drill " * 8,
                              "max_tokens": 2},
                        headers={"x-request-id": f"chaos-pipe-{i}"})
                    assert r.status_code == 200, r.text
                m = (await c.get(f"http://127.0.0.1:{SC}/metrics")).text
                assert _metric_value(
                    m, "sidecar_pipeline_fallbacks_total") >= 4
                # The attempt trail survives: the router's DecisionRecord
                # for a drilled request shows the disagg round that picked
                # the (doomed) prefiller — the fallback is explainable.
                r = await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/chaos-pipe-0")
                assert r.status_code == 200
                rec = r.json()
                prof = rec["rounds"][0]["profiles"]
                assert prof["prefill"]["outcome"] == "picked"
                assert prof["decode"]["outcome"] == "picked"
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()

    run(body())


def test_chaos_prefiller_killed_mid_chunk_stream_zero_errors():
    """Chaos drill (ISSUE 20): the prefill engine DIES mid-chunk-stream,
    after the decode leg already dispatched against its partial export.
    The decode engine's chunk poll hits connection errors, abandons the
    import, and degrades to local prefill — the client still sees a 200
    with the full completion (zero client-visible errors)."""
    SC, DEC, PRE = 18922, 18923, 18924

    async def body():
        dec = await _sim(DEC)
        # Slow, chunked prefill: 64 tokens at 20 ms/token over 8-token
        # windows -> first chunk staged ~160 ms in, export complete only
        # at ~1.3 s. Killing the server at ~450 ms lands mid-stream.
        pre = await _sim(PRE, role="prefill", prefill_chunk=8,
                         sim_prefill_ms_per_token=20.0)
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC}",
                                   prefill_timeout_s=10.0,
                                   pipeline_enabled=True))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                req = asyncio.create_task(c.post(
                    f"http://127.0.0.1:{SC}/v1/completions",
                    json={"prompt": list(range(3, 67)), "max_tokens": 2},
                    headers={"x-prefiller-host-port":
                             f"127.0.0.1:{PRE}"}))
                await asyncio.sleep(0.45)
                await pre.stop()  # mid-stream kill
                r = await req
                assert r.status_code == 200, r.text
                out = r.json()
                assert out["usage"]["completion_tokens"] == 2
                assert out["usage"]["prompt_tokens"] == 64
        finally:
            await sc.stop()
            await dec.stop()

    run(body())
