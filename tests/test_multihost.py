"""Multi-host serving: 2 processes × 2 CPU devices = one global tp=2 mesh.

Real jax.distributed (gloo collectives), real instruction channel: the
leader process serves a request through the full continuous-batching engine
while the follower replays device ops in lockstep (engine/multihost.py).
Greedy tokens must match a single-process tp=2 engine exactly — the same
SPMD program, just split across controllers.
"""

from __future__ import annotations

import asyncio
import multiprocessing as mp
import os

import pytest

COORD = "127.0.0.1:19811"
INSTR_PORT = 19812
PROMPT = [1, 5, 9, 13, 27]
N_GEN = 6


def _engine_cfg(**kw):
    from llm_d_inference_scheduler_tpu.engine import EngineConfig

    base = dict(model="tiny", backend="tpu", max_batch=2, max_model_len=64,
                tp_size=2, decode_chunk=4, kv_events_port=0, seed=3,
                warmup=True)
    base.update(kw)
    return EngineConfig(**base)


async def _serve_one(eng):
    from llm_d_inference_scheduler_tpu.engine import EngineRequest

    await eng.start()
    try:
        req = EngineRequest(request_id="mh", prompt_token_ids=list(PROMPT),
                            max_tokens=N_GEN, temperature=0.0,
                            ignore_eos=True)
        out = eng.submit(req)
        got = []
        while True:
            ev = await out.get()
            if ev.token_id is not None:
                got.append(ev.token_id)
            if ev.finish_reason is not None:
                break
        return got
    finally:
        await eng.stop()


def _dist_worker(pid: int, q) -> None:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    try:
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        cfg = _engine_cfg(dist_coordinator=COORD, dist_num_processes=2,
                          dist_process_id=pid, dist_instr_port=INSTR_PORT)
        maybe_init_distributed(cfg)
        assert len(jax.devices()) == 4  # global view spans both processes
        eng = TpuEngine(cfg)
        if pid == 0:
            tokens = asyncio.run(_serve_one(eng))
            q.put(("leader", tokens))
        else:
            run_follower(eng)
            q.put(("follower", "released"))
    except Exception as e:  # surface child tracebacks in the parent
        import traceback

        q.put(("error", f"pid{pid}: {e}\n{traceback.format_exc()[-2000:]}"))


def test_multihost_serving_matches_single_process():
    # Reference: single-process tp=2 engine on the local virtual devices.
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    expected = asyncio.run(_serve_one(TpuEngine(_engine_cfg())))
    assert len(expected) == N_GEN

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_dist_worker, args=(pid, q), daemon=True)
             for pid in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            kind, payload = q.get(timeout=420)
            assert kind != "error", payload
            results[kind] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    assert results["follower"] == "released"
    assert results["leader"] == expected
