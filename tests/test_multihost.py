"""Multi-host serving: 2 processes × 2 CPU devices = one global tp=2 mesh.

Real jax.distributed (gloo collectives), real instruction channel: the
leader process serves a request through the full continuous-batching engine
while the follower replays device ops in lockstep (engine/multihost.py).
Greedy tokens must match a single-process tp=2 engine exactly — the same
SPMD program, just split across controllers.
"""

from __future__ import annotations

import asyncio
import multiprocessing as mp
import os

import pytest

COORD = "127.0.0.1:19811"
INSTR_PORT = 19812
PROMPT = [1, 5, 9, 13, 27]
N_GEN = 6


def _engine_cfg(**kw):
    from llm_d_inference_scheduler_tpu.engine import EngineConfig

    base = dict(model="tiny", backend="tpu", max_batch=2, max_model_len=64,
                tp_size=2, decode_chunk=4, kv_events_port=0, seed=3,
                warmup=True)
    base.update(kw)
    return EngineConfig(**base)


async def _serve_one(eng):
    from llm_d_inference_scheduler_tpu.engine import EngineRequest

    await eng.start()
    try:
        req = EngineRequest(request_id="mh", prompt_token_ids=list(PROMPT),
                            max_tokens=N_GEN, temperature=0.0,
                            ignore_eos=True)
        out = eng.submit(req)
        got = []
        while True:
            ev = await out.get()
            if ev.token_id is not None:
                got.append(ev.token_id)
            if ev.finish_reason is not None:
                break
        return got
    finally:
        await eng.stop()


def _dist_worker(pid: int, q) -> None:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    try:
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        cfg = _engine_cfg(dist_coordinator=COORD, dist_num_processes=2,
                          dist_process_id=pid, dist_instr_port=INSTR_PORT)
        maybe_init_distributed(cfg)
        assert len(jax.devices()) == 4  # global view spans both processes
        eng = TpuEngine(cfg)
        if pid == 0:
            async def lead():
                await eng.start()
                try:
                    from llm_d_inference_scheduler_tpu.engine import (
                        EngineRequest,
                    )

                    req = EngineRequest(request_id="mh",
                                        prompt_token_ids=list(PROMPT),
                                        max_tokens=N_GEN, temperature=0.0,
                                        ignore_eos=True)
                    out = eng.submit(req)
                    got = []
                    while True:
                        ev = await out.get()
                        if ev.token_id is not None:
                            got.append(ev.token_id)
                        if ev.finish_reason is not None:
                            break
                    # Embeddings ride the op broadcast (engine-thread queue):
                    # the follower replays the same jit (VERDICT r4 weak #5).
                    vec = await asyncio.get_running_loop().run_in_executor(
                        None, eng.embed, list(PROMPT))
                    return got, [float(x) for x in vec]
                finally:
                    await eng.stop()

            tokens, vec = asyncio.run(lead())
            q.put(("leader", (tokens, vec)))
        else:
            run_follower(eng)
            q.put(("follower", "released"))
    except Exception as e:  # surface child tracebacks in the parent
        import traceback

        q.put(("error", f"pid{pid}: {e}\n{traceback.format_exc()[-2000:]}"))


def test_multihost_serving_matches_single_process():
    # Reference: single-process tp=2 engine on the local virtual devices.
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    async def single():
        from llm_d_inference_scheduler_tpu.engine import EngineRequest

        eng = TpuEngine(_engine_cfg())
        await eng.start()
        try:
            req = EngineRequest(request_id="mh",
                                prompt_token_ids=list(PROMPT),
                                max_tokens=N_GEN, temperature=0.0,
                                ignore_eos=True)
            out = eng.submit(req)
            got = []
            while True:
                ev = await out.get()
                if ev.token_id is not None:
                    got.append(ev.token_id)
                if ev.finish_reason is not None:
                    break
            vec = eng.embed(list(PROMPT))
            return got, vec
        finally:
            await eng.stop()

    expected, expected_vec = asyncio.run(single())
    assert len(expected) == N_GEN

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_dist_worker, args=(pid, q), daemon=True)
             for pid in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            kind, payload = q.get(timeout=420)
            assert kind != "error", payload
            results[kind] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    assert results["follower"] == "released"
    got_tokens, got_vec = results["leader"]
    assert got_tokens == expected
    # Same pooled vector through the multi-controller mesh (psum layout may
    # reorder float adds; bf16 params → loose tolerance).
    import numpy as np

    np.testing.assert_allclose(np.asarray(got_vec),
                               np.asarray(expected_vec),
                               rtol=2e-2, atol=2e-2)


# ---- pipeline parallelism spanning hosts (VERDICT r4 next #4) ------------

COORD_PP = "127.0.0.1:19815"
INSTR_PP = 19816


def _dist_pp_worker(pid: int, q) -> None:
    """2 processes × 2 devices → a global (pp=2, tp=2) mesh: each host owns
    one full pipeline stage (tp inside the host), the stage-hop ppermute
    crosses processes — the BASELINE config-4 shape (70B pipeline over a
    multi-host slice) at test scale."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    try:
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        cfg = _engine_cfg(pp_size=2, tp_size=2,
                          dist_coordinator=COORD_PP, dist_num_processes=2,
                          dist_process_id=pid, dist_instr_port=INSTR_PP,
                          dist_recv_timeout_s=600.0)
        maybe_init_distributed(cfg)
        assert len(jax.devices()) == 4
        eng = TpuEngine(cfg)
        assert eng.pp_mesh is not None and eng.mesh is None
        # Stage placement: the pp axis must split across processes (the
        # ring hop is the cross-host edge).
        stage_procs = [sorted({d.process_index for d in row.flat})
                       for row in eng.pp_mesh.devices]
        assert stage_procs == [[0], [1]]
        if pid == 0:
            tokens = asyncio.run(_serve_one(eng))
            q.put(("leader", tokens))
        else:
            run_follower(eng)
            q.put(("follower", "released"))
    except Exception as e:
        import traceback

        q.put(("error", f"pid{pid}: {e}\n{traceback.format_exc()[-2000:]}"))


def test_multihost_pp_matches_single_process():
    """Greedy tokens through a host-spanning stage ring must equal the
    single-process pp=2×tp=2 engine's (same SPMD program, stages split
    across controllers)."""
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    expected = asyncio.run(_serve_one(TpuEngine(
        _engine_cfg(pp_size=2, tp_size=2))))
    assert len(expected) == N_GEN

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_dist_pp_worker, args=(pid, q), daemon=True)
             for pid in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            kind, payload = q.get(timeout=600)
            assert kind != "error", payload
            results[kind] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()

    assert results["follower"] == "released"
    assert results["leader"] == expected


# ---- failure semantics (NEXT: multi-host hardening) ----------------------


def test_channel_liveness_in_process():
    """Channel-level: pings flow leader→follower; a silent leader trips the
    follower's recv deadline (LeaderLost); a dead follower trips the
    leader's peer monitor and breaks broadcast (ChannelBroken)."""
    import threading
    import time

    from llm_d_inference_scheduler_tpu.engine.multihost import (
        ChannelBroken,
        InstructionChannel,
        LeaderLost,
    )

    port = 19821
    leader_box = {}

    def make_leader(ping):
        leader_box["ch"] = InstructionChannel(
            leader=True, host="127.0.0.1", port=port, n_followers=1,
            ping_interval=ping)

    # -- pings + silent-leader timeout
    t = threading.Thread(target=make_leader, args=(0.1,), daemon=True)
    t.start()
    follower = InstructionChannel(leader=False, host="127.0.0.1", port=port,
                                  recv_timeout=2.0)
    t.join(timeout=10)
    leader = leader_box["ch"]
    op, _ = follower.recv()
    assert op == ("ping",)
    leader.close()  # leader gone: EOF → LeaderLost
    try:
        while True:
            follower.recv()
    except LeaderLost:
        pass
    follower.close()

    # -- dead follower: peer monitor fires, broadcast raises
    port += 1
    lost = threading.Event()
    t = threading.Thread(target=make_leader, args=(0.0,), daemon=True)
    t.start()
    follower = InstructionChannel(leader=False, host="127.0.0.1", port=port,
                                  recv_timeout=2.0)
    t.join(timeout=10)
    leader = leader_box["ch"]
    leader.on_peer_lost = lambda idx, why: lost.set()
    follower.close()
    assert lost.wait(timeout=5.0), "peer monitor never fired"
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        try:
            leader.broadcast(("decode",), {})
        except ChannelBroken:
            break
        time.sleep(0.05)
    else:
        raise AssertionError("broadcast never raised ChannelBroken")
    leader.close()

    # -- follower recv deadline with a hung (never-pinging) leader
    port += 1
    t = threading.Thread(target=make_leader, args=(0.0,), daemon=True)
    t.start()
    follower = InstructionChannel(leader=False, host="127.0.0.1", port=port,
                                  recv_timeout=0.3)
    t.join(timeout=10)
    import pytest as _pytest

    with _pytest.raises(LeaderLost, match="presumed dead"):
        follower.recv()
    follower.close()
    leader_box["ch"].close()


def _degrade_worker(pid: int, q, ready, killed) -> None:
    """Leader engine degrades (abort + 503 semantics) when its follower is
    killed mid-flight; no collective is touched afterwards (no hang)."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    try:
        from llm_d_inference_scheduler_tpu.engine import EngineRequest
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            maybe_init_distributed,
            run_follower,
        )

        cfg = _engine_cfg(dist_coordinator="127.0.0.1:19831",
                          dist_num_processes=2, dist_process_id=pid,
                          dist_instr_port=19832, warmup=False)
        maybe_init_distributed(cfg)
        eng = TpuEngine(cfg)  # joint sharded init (collective) — both alive
        if pid == 1:
            ready.set()
            run_follower(eng)  # parent kills us here
            q.put(("follower", "unexpected clean exit"))
            return

        ready.set()
        assert killed.wait(timeout=120), "parent never killed the follower"

        async def drive():
            await eng.start()
            try:
                # Degrade latch flips via the peer monitor thread.
                import time as _t

                deadline = _t.monotonic() + 30
                while not eng.dist_degraded and _t.monotonic() < deadline:
                    await asyncio.sleep(0.1)
                assert eng.dist_degraded, "leader never noticed dead follower"
                # New work must be refused fast (ABORT), not hang in a
                # collective.
                out = eng.submit(EngineRequest(
                    request_id="x", prompt_token_ids=list(PROMPT),
                    max_tokens=4, temperature=0.0))
                ev = await asyncio.wait_for(out.get(), timeout=30)
                assert ev.finish_reason is not None, "no terminal event"
                return str(ev.finish_reason)
            finally:
                await eng.stop()

        q.put(("leader", asyncio.run(drive())))
    except Exception as e:
        import traceback

        q.put(("error", f"pid{pid}: {e}\n{traceback.format_exc()[-2000:]}"))


def test_leader_degrades_when_follower_dies():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    ready = [ctx.Event(), ctx.Event()]
    killed = ctx.Event()
    procs = [ctx.Process(target=_degrade_worker,
                         args=(pid, q, ready[pid], killed), daemon=True)
             for pid in range(2)]
    for p in procs:
        p.start()
    try:
        for ev in ready:
            assert ev.wait(timeout=300), "worker never became ready"
        # SIGKILL: jax.distributed installs a SIGTERM preemption handler,
        # so terminate() would leave the follower alive.
        procs[1].kill()
        procs[1].join(timeout=30)
        killed.set()
        kind, payload = q.get(timeout=300)
        assert kind == "leader", payload
        assert "abort" in payload.lower(), payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()


def _leaderloss_worker(pid: int, q, ready) -> None:
    """Follower exits with LeaderLost when the leader crashes (no stop)."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    try:
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine.multihost import (
            LeaderLost,
            maybe_init_distributed,
            run_follower,
        )

        cfg = _engine_cfg(dist_coordinator="127.0.0.1:19841",
                          dist_num_processes=2, dist_process_id=pid,
                          dist_instr_port=19842, warmup=False)
        maybe_init_distributed(cfg)
        eng = TpuEngine(cfg)
        ready.set()
        if pid == 0:
            import time as _t

            _t.sleep(2.0)   # let the follower settle into recv()
            os._exit(1)     # crash without the ("stop",) broadcast
        try:
            run_follower(eng)
            q.put(("follower", "clean (unexpected)"))
        except LeaderLost:
            q.put(("follower", "leader-lost"))
    except Exception as e:
        import traceback

        q.put(("error", f"pid{pid}: {e}\n{traceback.format_exc()[-2000:]}"))


def test_follower_exits_when_leader_crashes():
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    ready = [ctx.Event(), ctx.Event()]
    procs = [ctx.Process(target=_leaderloss_worker, args=(pid, q, ready[pid]),
                         daemon=True)
             for pid in range(2)]
    for p in procs:
        p.start()
    try:
        for ev in ready:
            assert ev.wait(timeout=300), "worker never became ready"
        # The follower must die promptly and NONZERO — either via our
        # LeaderLost (instruction channel EOF/ping deadline) or via the JAX
        # coordination service's own fatal leader-death detection,
        # whichever notices first. Both end in a pod restart in production.
        procs[1].join(timeout=120)
        assert not procs[1].is_alive(), "follower survived leader crash"
        assert procs[1].exitcode != 0, "follower exited 0 after leader crash"
        import queue as _queue

        try:
            kind, payload = q.get_nowait()
        except _queue.Empty:
            pass  # killed by the JAX runtime before reporting — acceptable
        else:
            assert (kind, payload) == ("follower", "leader-lost"), \
                (kind, payload)
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
