"""ext-proc state machine: protocol ordering, mutations, fallbacks, errors."""

import asyncio
import json

import pytest

from llm_d_inference_scheduler_tpu.router import plugins  # noqa: F401
from llm_d_inference_scheduler_tpu.router.config.loader import Handle, load_config
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.framework.datalayer import EndpointMetadata
from llm_d_inference_scheduler_tpu.router.handlers.extproc import (
    CommonResponse,
    ExtProcSession,
    ImmediateResponse,
    ProtocolError,
    RequestBody,
    RequestHeaders,
    ResponseBody,
    ResponseHeaders,
)
from llm_d_inference_scheduler_tpu.router.handlers.parsers import OpenAIParser
from llm_d_inference_scheduler_tpu.router.requestcontrol.admission import (
    AlwaysAdmitController,
)
from llm_d_inference_scheduler_tpu.router.requestcontrol.director import Director


def make_session(n_endpoints=2):
    ds = Datastore()
    for i in range(n_endpoints):
        ds.endpoint_add_or_update(EndpointMetadata(
            name=f"e{i}", address=f"10.0.0.{i+1}", port=8200))
    handle = Handle(datastore=ds)
    cfg = load_config(None, handle)
    director = Director(ds, cfg.scheduler, admission=AlwaysAdmitController(),
                        producers=cfg.producers,
                        pre_request_plugins=cfg.pre_request_plugins)
    return ExtProcSession(director, OpenAIParser("p")), ds


def run(coro):
    return asyncio.run(coro)


def test_full_stream_happy_path():
    async def body():
        sess, _ = make_session()
        # Headers + mid-body chunks are buffered silently; the response is
        # deferred until scheduling (reference server.go:314-318, 362-363).
        r = await sess.on_request_headers(RequestHeaders(headers={"X-Foo": "1"}))
        assert r is None

        payload = json.dumps({"model": "m", "prompt": "hello"}).encode()
        r = await sess.on_request_body(RequestBody(payload[:5]))
        assert r is None
        r = await sess.on_request_body(RequestBody(payload[5:], end_of_stream=True))
        assert isinstance(r, list) and len(r) == 2
        hdr, body_resp = r
        assert hdr.phase == "request_headers" and hdr.clear_route_cache
        dest = hdr.header_mutation.set_headers["x-gateway-destination-endpoint"]
        assert dest.startswith("10.0.0.")
        assert hdr.dynamic_metadata["envoy.lb"]["x-gateway-destination-endpoint"] == dest
        assert body_resp.phase == "request_body" and body_resp.body_eos
        assert body_resp.body == payload
        assert hdr.header_mutation.set_headers["content-length"] == str(len(payload))

        r = await sess.on_response_headers(ResponseHeaders(headers={}, status=200))
        assert r.header_mutation.set_headers[
            "x-gateway-destination-endpoint-served"] == dest

        resp = json.dumps({"model": "m", "usage": {"prompt_tokens": 3,
                                                   "completion_tokens": 5}}).encode()
        r = await sess.on_response_body(ResponseBody(resp, end_of_stream=True))
        assert r.dynamic_metadata["usage"]["completion_tokens"] == 5

    run(body())


def test_bodyless_request_falls_back_to_random():
    async def body():
        sess, _ = make_session()
        r = await sess.on_request_headers(
            RequestHeaders(headers={}, end_of_stream=True))
        assert isinstance(r, CommonResponse)
        assert "x-gateway-destination-endpoint" in r.header_mutation.set_headers

    run(body())


def test_ordering_violations_raise():
    async def body():
        sess, _ = make_session()
        with pytest.raises(ProtocolError):
            await sess.on_request_body(RequestBody(b"x", end_of_stream=True))
        sess2, _ = make_session()
        assert await sess2.on_request_headers(
            RequestHeaders(headers={})) is None
        with pytest.raises(ProtocolError):
            await sess2.on_response_headers(ResponseHeaders(headers={}))

    run(body())


def test_invalid_body_immediate_response():
    async def body():
        sess, _ = make_session()
        await sess.on_request_headers(RequestHeaders(headers={}))
        r = await sess.on_request_body(RequestBody(b"{nope", end_of_stream=True))
        assert isinstance(r, ImmediateResponse) and r.status == 400
        assert "x-removal-reason" in r.headers

    run(body())


def test_no_endpoints_immediate_503():
    async def body():
        sess, _ = make_session(n_endpoints=0)
        await sess.on_request_headers(RequestHeaders(headers={}))
        r = await sess.on_request_body(
            RequestBody(json.dumps({"model": "m", "prompt": "x"}).encode(),
                        end_of_stream=True))
        assert isinstance(r, ImmediateResponse) and r.status == 503

    run(body())


def test_client_injected_routing_header_stripped():
    async def body():
        sess, _ = make_session()
        await sess.on_request_headers(RequestHeaders(
            headers={"x-prefiller-host-port": "evil:1"}))
        r = await sess.on_request_body(
            RequestBody(json.dumps({"model": "m", "prompt": "x"}).encode(),
                        end_of_stream=True))
        assert "x-prefiller-host-port" not in r[0].header_mutation.set_headers

    run(body())
