"""KV-cache & prefix-reuse observability (router/kvobs.py + engine hit
accounting): the predicted-vs-confirmed hit ledger, /debug/kv surfaces,
decision-list filters, and the verify-debug lint hook."""

import asyncio

import httpx
import pytest

from llm_d_inference_scheduler_tpu.router.decisions import (
    DecisionRecord,
    record_matches,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    ProfileRunResult,
    SchedulingResult,
)
from llm_d_inference_scheduler_tpu.router.kvobs import (
    CacheLedger,
    KvHitTable,
    KvObsConfig,
)
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    PREFIX_ATTRIBUTE_KEY,
    PrefixCacheMatchInfo,
)


def _ep(port: int) -> Endpoint:
    return Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1",
                                     port=port))


def _request(rid: str = "r1") -> InferenceRequest:
    req = InferenceRequest(
        request_id=rid, target_model="tiny",
        body=InferenceRequestBody(completions={"prompt": "p"}))
    req.decision = DecisionRecord(rid, "tiny")
    return req


def _result(*eps: Endpoint) -> SchedulingResult:
    return SchedulingResult(
        profile_results={"default": ProfileRunResult(
            target_endpoints=list(eps))},
        primary_profile_name="default")


def _predicted(ep: Endpoint, blocks: int, total: int) -> None:
    ep.attributes.put(PREFIX_ATTRIBUTE_KEY,
                      PrefixCacheMatchInfo(blocks, total, 16))


# ---- CacheLedger unit behavior -------------------------------------------

def test_ledger_joins_headers_into_decision_cache_block():
    ledger = CacheLedger(KvObsConfig())
    ep = _ep(9001)
    _predicted(ep, 3, 4)
    req = _request()
    ledger.record_scheduled(req, _result(ep))
    assert req.cache is not None
    block = req.decision.cache
    assert block["chosen"] == "127.0.0.1:9001"
    assert block["predicted"]["127.0.0.1:9001"] == {
        "blocks": 3, "total": 4, "ratio": 0.75, "block_tokens": 16}
    ledger.observe_response(req, ep, {"x-kv-hit-tokens": "32",
                                      "x-kv-hit-blocks": "2"})
    actual = block["actual"]
    assert actual["blocks"] == 2 and actual["tokens"] == 32
    assert actual["source"] == "headers"
    assert actual["prediction_error_blocks"] == 1  # predicted 3, actual 2
    snap = ledger.snapshot()
    assert snap["predicted_stamps"] == 1 and snap["confirmed_joins"] == 1
    assert snap["prediction"]["mae_blocks"] == 1.0
    pod = snap["pods"]["127.0.0.1:9001"]
    assert pod["n"] == 1
    # header-only join with no usage: ratio derives from predicted total.
    assert pod["ewma_hit_ratio"] == 0.5
    # the x-debug-decision summary echo carries the cache verdict.
    assert "cache=pred:3/act:2" in req.decision.summary_line()


def test_ledger_usage_fallback_joins_streams():
    ledger = CacheLedger(KvObsConfig())
    ep = _ep(9002)
    _predicted(ep, 2, 2)
    req = _request()
    ledger.record_scheduled(req, _result(ep))
    # Streamed responses carry no hit headers; the terminal accounting
    # passes the parsed usage record instead.
    ledger.observe_response(req, ep, {}, None)
    assert "actual" not in req.decision.cache  # nothing to join yet
    ledger.observe_response(
        req, ep, {},
        {"prompt_tokens": 64, "prompt_tokens_details": {"cached_tokens": 32}})
    actual = req.decision.cache["actual"]
    assert actual["source"] == "usage"
    assert actual["tokens"] == 32 and actual["ratio"] == 0.5
    # first join wins: a later call cannot double-count.
    ledger.observe_response(req, ep, {"x-kv-hit-tokens": "64"},
                            {"prompt_tokens": 64})
    assert ledger.snapshot()["confirmed_joins"] == 1
    assert req.decision.cache["actual"]["tokens"] == 32


def test_ledger_killswitch_and_no_signal():
    ledger = CacheLedger(KvObsConfig(enabled=False))
    ep = _ep(9003)
    _predicted(ep, 1, 1)
    req = _request()
    ledger.record_scheduled(req, _result(ep))
    assert req.cache is None
    ledger.observe_response(req, ep, {"x-kv-hit-tokens": "16"})
    assert ledger.snapshot()["confirmed_joins"] == 0
    # Enabled, but no prefix plugin produced a signal: no stamp either.
    ledger2 = CacheLedger(KvObsConfig())
    req2 = _request("r2")
    ledger2.record_scheduled(req2, _result(_ep(9004)))
    assert req2.cache is None


def test_ledger_prefiller_attribution_and_reschedule_merge():
    """On a P/D split the hit belongs to the prefill pod the sidecar names
    (x-kv-prefiller), not the decode endpoint the gateway proxied to; a
    failover reschedule merges fresh candidates into the same block."""
    ledger = CacheLedger(KvObsConfig())
    decode, prefill = _ep(9005), _ep(9006)
    _predicted(decode, 0, 4)
    _predicted(prefill, 2, 4)
    req = _request()
    ledger.record_scheduled(req, _result(decode))
    assert "127.0.0.1:9006" not in req.cache.predicted
    ledger.record_scheduled(req, _result(decode, prefill))  # reschedule
    assert "127.0.0.1:9006" in req.cache.predicted
    assert ledger.snapshot()["predicted_stamps"] == 1  # merged, not re-stamped
    ledger.observe_response(
        req, decode,
        {"x-kv-hit-tokens": "32", "x-kv-hit-blocks": "2",
         "x-kv-prefiller": "127.0.0.1:9006"},
        {"prompt_tokens": 64})
    actual = req.decision.cache["actual"]
    assert actual["pod"] == "127.0.0.1:9006"
    assert actual["prediction_error_blocks"] == 0
    assert "127.0.0.1:9006" in ledger.snapshot()["pods"]
    assert "127.0.0.1:9005" not in ledger.snapshot()["pods"]


def test_kv_hit_table_lru_bound():
    table = KvHitTable(max_pods=2)
    for i in range(4):
        table.record(f"pod-{i}", hit_ratio=0.5, signed_error=None)
    assert len(table) == 2
    assert table.pod("pod-0") is None and table.pod("pod-3") is not None
    # EWMA blends toward the newest observation.
    table.record("pod-3", hit_ratio=1.0, signed_error=0.25)
    row = table.rows()["pod-3"]
    assert 0.5 < row["ewma_hit_ratio"] < 1.0
    assert row["ewma_signed_error"] == 0.25


# ---- /debug/decisions list filters ---------------------------------------

def test_record_matches_filters():
    met = {"outcome": {"verdict": "met", "slo_met": True},
           "final": {"destination": "a:1"}}
    missed = {"outcome": {"verdict": "missed", "slo_met": False},
              "final": {"destination": "b:2"}}
    err = {"outcome": {"verdict": "error", "slo_met": False,
                       "reason": "http-502"},
           "final": {"destination": "a:1"},
           "attempts": [{"endpoint": "c:3"}, {"endpoint": "a:1"}]}
    shed = {"outcome": {"verdict": "shed", "shed": True, "slo_met": False},
            "shed": {"action": "shed"}, "final": {}}
    assert record_matches(met, verdict="met")
    assert not record_matches(met, verdict="missed")
    assert record_matches(missed, outcome="miss")
    assert record_matches(err, outcome="miss")
    assert not record_matches(met, outcome="miss")
    assert record_matches(shed, outcome="shed")
    assert record_matches(shed, verdict="shed")
    assert not record_matches(missed, outcome="shed")
    assert record_matches(met, endpoint="a:1")
    assert not record_matches(missed, endpoint="a:1")
    assert record_matches(err, endpoint="c:3")  # attempt-trail match
    # AND semantics across filters.
    assert record_matches(err, verdict="error", endpoint="a:1")
    assert not record_matches(err, verdict="met", endpoint="a:1")
    # Legacy records without the verdict field: derived from slo_met/shed.
    legacy = {"outcome": {"slo_met": True}, "final": {}}
    assert record_matches(legacy, verdict="met")


# ---- engine + gateway surfaces (sim-backed e2e) --------------------------

def test_engine_hit_accounting_and_debug_kv():
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer

    async def body():
        srv = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=18790, max_batch=4))
        await srv.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                prompt = "the shared system preamble " * 8
                r1 = await c.post("http://127.0.0.1:18790/v1/completions",
                                  json={"prompt": prompt, "max_tokens": 2})
                assert r1.headers["x-kv-hit-tokens"] == "0"
                assert r1.json()["usage"]["prompt_tokens_details"] == {
                    "cached_tokens": 0}
                r2 = await c.post("http://127.0.0.1:18790/v1/completions",
                                  json={"prompt": prompt, "max_tokens": 2})
                warm = int(r2.headers["x-kv-hit-tokens"])
                assert warm > 0
                assert int(r2.headers["x-kv-hit-blocks"]) == warm // 16
                # Streamed: hit rides the terminal usage record instead.
                import json as _json

                usage = None
                async with c.stream(
                        "POST", "http://127.0.0.1:18790/v1/completions",
                        json={"prompt": prompt, "max_tokens": 2,
                              "stream": True}) as r3:
                    async for line in r3.aiter_lines():
                        if line.startswith("data: ") and '"usage"' in line:
                            usage = _json.loads(line[6:])["usage"]
                assert usage["prompt_tokens_details"]["cached_tokens"] > 0
                dbg = (await c.get(
                    "http://127.0.0.1:18790/debug/kv")).json()
                assert dbg["count"] == 3
                assert dbg["totals"]["prefix_hit_tokens"] > 0
                assert 0 < dbg["totals"]["actual_hit_ratio"] < 1
                newest = dbg["recent"][0]
                assert newest["hit_tokens"] > 0
                m = (await c.get("http://127.0.0.1:18790/metrics")).text
                assert "jetstream:prefill_tokens_total" in m
                assert "jetstream:prefix_hit_tokens_total" in m
        finally:
            await srv.stop()

    asyncio.run(body())


GW, E0 = 18791, 18792

GW_CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {E0}}}
plugins:
  - {{type: approx-prefix-cache-producer}}
  - {{type: prefix-cache-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: prefix-cache-scorer}}
"""


def test_gateway_kv_surface_headers_and_filters():
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=E0, max_batch=4))
        await eng.start()
        gw = build_gateway(GW_CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            await asyncio.sleep(0.15)
            async with httpx.AsyncClient(timeout=30) as c:
                prompt = "another shared preamble for the pool " * 6
                for rid in ("kvgw-1", "kvgw-2"):
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": prompt,
                              "max_tokens": 2},
                        headers={"x-request-id": rid,
                                 "x-debug-decision": "summary"})
                    assert r.status_code == 200
                # Warm request: hit headers relayed to the client and the
                # summary echo carries the cache verdict.
                assert int(r.headers["x-kv-hit-tokens"]) > 0
                assert "cache=pred:" in r.headers["x-decision-summary"]
                assert "/act:" in r.headers["x-decision-summary"]
                d = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/kvgw-2")).json()
                cache = d["cache"]
                assert cache["predicted"][f"127.0.0.1:{E0}"]["ratio"] == 1.0
                assert cache["actual"]["tokens"] > 0
                assert cache["actual"]["source"] == "headers"
                kv = (await c.get(f"http://127.0.0.1:{GW}/debug/kv")).json()
                assert kv["enabled"] and kv["predicted_stamps"] == 2
                assert kv["confirmed_joins"] == 2
                assert kv["index_divergence"] == 0.0
                pod = kv["pods"][f"127.0.0.1:{E0}"]
                assert pod["n"] == 2 and pod["approx_index_blocks"] > 0
                # The scraped engine counter pair lands per pod.
                for _ in range(40):
                    kv = (await c.get(
                        f"http://127.0.0.1:{GW}/debug/kv")).json()
                    if "scraped" in kv["pods"].get(f"127.0.0.1:{E0}", {}):
                        break
                    await asyncio.sleep(0.05)
                scraped = kv["pods"][f"127.0.0.1:{E0}"]["scraped"]
                assert scraped["prefill_tokens"] > 0
                # /debug/decisions list filters.
                r = await c.get(f"http://127.0.0.1:{GW}"
                                "/debug/decisions?verdict=met")
                assert {d["request_id"] for d in r.json()["decisions"]} >= {
                    "kvgw-1", "kvgw-2"}
                r = await c.get(f"http://127.0.0.1:{GW}"
                                "/debug/decisions?verdict=shed")
                assert r.json()["decisions"] == []
                r = await c.get(
                    f"http://127.0.0.1:{GW}"
                    f"/debug/decisions?endpoint=127.0.0.1:{E0}")
                assert len(r.json()["decisions"]) >= 2
                r = await c.get(f"http://127.0.0.1:{GW}"
                                "/debug/decisions?endpoint=10.0.0.9:1")
                assert r.json()["decisions"] == []
                # New metric families observed (counts are process-global
                # across tests, so assert non-zero rather than exact).
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                for fam in ("router_kv_predicted_hit_blocks",
                            "router_kv_hit_prediction_error",
                            "router_kv_actual_hit_ratio"):
                    line = next(ln for ln in m.splitlines()
                                if ln.startswith(f"{fam}_count"))
                    assert float(line.split()[-1]) > 0, fam
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_gateway_kv_killswitch():
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=E0, max_batch=4))
        await eng.start()
        gw = build_gateway("kvCache: {enabled: false}\n" + GW_CFG,
                           port=GW, poll_interval=0.02)
        await gw.start()
        try:
            await asyncio.sleep(0.1)
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "hi there",
                                       "max_tokens": 2},
                                 headers={"x-request-id": "kvoff-1"})
                assert r.status_code == 200
                kv = (await c.get(f"http://127.0.0.1:{GW}/debug/kv")).json()
                assert kv["enabled"] is False
                assert kv["predicted_stamps"] == 0
                d = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/kvoff-1")).json()
                assert "cache" not in d
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


# ---- verify-debug lint hook ----------------------------------------------

def test_verify_debug_surfaces_clean():
    """Every registered /debug route answers JSON and has a docs index row
    (scripts/verify_debug.py — the make verify-debug twin)."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "scripts"))
    import verify_debug

    assert verify_debug.check() == []
