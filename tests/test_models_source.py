"""models-data-source → /v1/models endpoint attribute → model-aware routing
and gateway model-union (reference framework/plugins/datalayer/source/models
README.md:8-13, extractor/models/extractor.go:15,106; VERDICT r2 missing #5
+ weak #8 heterogeneous-pool aggregation)."""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.datalayer.models_source import (
    MODELS_ATTRIBUTE_KEY,
)
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

GW, A, B = 18560, 18561, 18562

CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {A}}}
    - {{address: 127.0.0.1, port: {B}}}
plugins:
  - type: models-data-source
    parameters: {{refreshSeconds: 0.01}}
  - {{type: models-data-extractor}}
  - {{type: model-serving-filter}}
  - {{type: queue-scorer}}
dataLayer:
  sources:
    - pluginRef: models-data-source
      extractors:
        - {{pluginRef: models-data-extractor}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: model-serving-filter}}
      - {{pluginRef: queue-scorer}}
"""


async def _eventually(pred, timeout=10.0, what=""):
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred():
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"never held: {what}")
        await asyncio.sleep(0.05)


def test_models_source_union_and_model_aware_routing():
    async def body():
        # Heterogeneous pool: same weights, different served model names.
        ea = EngineServer(EngineConfig(backend="sim", model="tiny", port=A,
                                       served_model_name="alpha",
                                       sim_decode_ms_per_token=1.0))
        eb = EngineServer(EngineConfig(backend="sim", model="tiny", port=B,
                                       served_model_name="beta",
                                       sim_decode_ms_per_token=1.0))
        await ea.start()
        await eb.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            def polled():
                eps = gw.datastore.endpoint_list()
                return len(eps) == 2 and all(
                    MODELS_ATTRIBUTE_KEY in ep.attributes for ep in eps)

            await _eventually(polled, what="models attribute polled")

            async with httpx.AsyncClient(timeout=30) as c:
                # Union across the heterogeneous pool — reading only the
                # first endpoint would report a single model.
                r = await c.get(f"http://127.0.0.1:{GW}/v1/models")
                ids = sorted(m["id"] for m in r.json()["data"])
                assert ids == ["alpha", "beta"]

                # Model-aware candidates: every request lands on the one
                # endpoint actually serving the requested model.
                for model, port in (("alpha", A), ("beta", B)) * 3:
                    r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                     json={"model": model, "prompt": "hi",
                                           "max_tokens": 2})
                    assert r.status_code == 200
                    assert r.headers["x-gateway-destination-endpoint-served"] \
                        == f"127.0.0.1:{port}"

                # Fail-open: unknown model keeps the full candidate set
                # instead of bricking scheduling.
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "ghost", "prompt": "hi",
                                       "max_tokens": 2})
                assert r.status_code == 200
        finally:
            await gw.stop()
            await eb.stop()
            await ea.stop()

    asyncio.run(body())
