"""/v1/embeddings surface: engine pooling, HTTP endpoint, gateway routing.

Reference parity: the EPP's body model carries EmbeddingsRequest
(types.go:74-75) and routes it like any OpenAI body; the serving half there
is a vLLM embedding pod. Here the engine itself serves mean-pooled
final-hidden-state vectors (TpuEngine.embed)."""

import asyncio

import httpx
import numpy as np

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


def run(coro):
    return asyncio.run(coro)


def test_engine_embed_deterministic_and_padding_invariant():
    async def body():
        eng = TpuEngine(EngineConfig(model="tiny", backend="tpu", max_batch=2,
                                     max_model_len=64, kv_events_port=0))
        await eng.start()
        try:
            ids = [1, 5, 9, 13]
            v1 = eng.embed(ids)
            v2 = eng.embed(ids)
            assert v1.shape == (eng.mcfg.d_model,)
            np.testing.assert_array_equal(v1, v2)  # jit determinism
            # Different input → different vector.
            v3 = eng.embed([1, 5, 9, 14])
            assert not np.allclose(v1, v3)
            # Bucket padding must not leak into the pooled mean: the same
            # prompt through two bucket sizes (16 vs 32) pools identically.
            long_ids = list(range(3, 3 + 17))   # bucket 32
            short = eng.embed(long_ids[:4])     # bucket 16
            ref = eng.embed(long_ids[:4] + long_ids[4:])  # bucket 32 path hot
            v4 = eng.embed(long_ids[:4])
            np.testing.assert_allclose(short, v4, rtol=0, atol=0)
            assert ref.shape == short.shape
        finally:
            await eng.stop()

    run(body())


def test_engine_embed_under_pp_and_tp_matches_single_device():
    """pp ring embeddings (make_pp_embed) and tp-sharded embeddings must
    pool to the same vector as the single-device engine (VERDICT r4 weak #5:
    embeddings were tp/single-only)."""
    import jax
    import jax.numpy as jnp

    from llm_d_inference_scheduler_tpu.models import llama
    from llm_d_inference_scheduler_tpu.models.configs import get_config

    params = llama.init_params(get_config("tiny"), jax.random.key(7),
                               dtype=jnp.float32)

    def cfg(**kw):
        return EngineConfig(model="tiny", backend="tpu", max_batch=2,
                            max_model_len=64, kv_events_port=0, **kw)

    async def one(c):
        eng = TpuEngine(c, params=params)
        await eng.start()
        try:
            return eng.embed([1, 5, 9, 13])
        finally:
            await eng.stop()

    ref = run(one(cfg()))
    for kw in ({"pp_size": 2}, {"pp_size": 2, "tp_size": 2}, {"tp_size": 2}):
        vec = run(one(cfg(**kw)))
        np.testing.assert_allclose(vec, ref, rtol=0, atol=2e-4,
                                   err_msg=f"embed diverges under {kw}")


def test_engine_http_embeddings_endpoint():
    async def body():
        srv = EngineServer(EngineConfig(model="tiny", backend="tpu",
                                        max_batch=2, max_model_len=64,
                                        kv_events_port=0, port=18471))
        await srv.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                r = await c.post("http://127.0.0.1:18471/v1/embeddings",
                                 json={"model": "tiny",
                                       "input": ["hello", "world"]})
                assert r.status_code == 200
                doc = r.json()
                assert doc["object"] == "list" and len(doc["data"]) == 2
                assert doc["data"][0]["index"] == 0
                assert len(doc["data"][0]["embedding"]) == 128  # tiny d_model
                assert doc["usage"]["prompt_tokens"] > 0

                # token-id input shape
                r = await c.post("http://127.0.0.1:18471/v1/embeddings",
                                 json={"input": [3, 4, 5]})
                assert r.status_code == 200
                assert len(r.json()["data"]) == 1

                # over-context input → 400
                r = await c.post("http://127.0.0.1:18471/v1/embeddings",
                                 json={"input": "x" * 100})
                assert r.status_code == 400
        finally:
            await srv.stop()

    run(body())


def test_gateway_routes_embeddings_to_sim_pool():
    CFG = """
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18473}
    - {address: 127.0.0.1, port: 18474}
"""

    async def body():
        engines = []
        for port in (18473, 18474):
            s = EngineServer(EngineConfig(backend="sim", model="tiny",
                                          port=port, max_batch=4))
            await s.start()
            engines.append(s)
        gw = build_gateway(CFG, port=18472, poll_interval=0.02)
        await gw.start()
        try:
            await asyncio.sleep(0.2)
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post("http://127.0.0.1:18472/v1/embeddings",
                                 json={"model": "tiny", "input": "hello"})
                assert r.status_code == 200
                assert r.headers["x-gateway-destination-endpoint-served"] in (
                    "127.0.0.1:18473", "127.0.0.1:18474")
                doc = r.json()
                assert len(doc["data"]) == 1
                assert len(doc["data"][0]["embedding"]) == 64  # sim vectors
        finally:
            await gw.stop()
            for s in engines:
                await s.stop()

    run(body())


def test_embeddings_empty_input_rejected():
    async def body():
        srv = EngineServer(EngineConfig(model="tiny", backend="tpu",
                                        max_batch=2, max_model_len=64,
                                        kv_events_port=0, port=18475))
        await srv.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                for bad in ({"input": []}, {"input": ""},
                            {"input": ["ok", ""]}, {}):
                    r = await c.post("http://127.0.0.1:18475/v1/embeddings",
                                     json=bad)
                    assert r.status_code == 400, bad
        finally:
            await srv.stop()

    run(body())


def test_embeddings_body_scheduling_surface():
    """The router sees the real input: prompt_text feeds size estimates and
    prefix hashing (review finding: embeddings scheduled on an empty
    prompt), and payload() makes model rewrites repackage the body."""
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        InferenceRequestBody,
    )

    b = InferenceRequestBody(embeddings={"model": "m", "input": "hello world"})
    assert b.prompt_text() == "hello world"
    assert b.payload is not None and b.payload["model"] == "m"

    b2 = InferenceRequestBody(embeddings={"input": ["a", "b"]})
    assert b2.prompt_text() == "a b"

    b3 = InferenceRequestBody(embeddings={"input": [3, 4, 5]})
    assert "3" in b3.prompt_text()


def test_gateway_rewrites_embeddings_model():
    """Weighted model rewrite must reach the upstream body for /v1/embeddings
    too (payload() now includes embeddings)."""
    CFG = """
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18477}
modelRewrites:
  - sourceModel: alias-model
    targets:
      - {model: tiny, weight: 1}
"""

    async def body():
        s = EngineServer(EngineConfig(backend="sim", model="tiny",
                                      port=18477, max_batch=4))
        await s.start()
        gw = build_gateway(CFG, port=18476, poll_interval=0.02)
        await gw.start()
        try:
            await asyncio.sleep(0.2)
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post("http://127.0.0.1:18476/v1/embeddings",
                                 json={"model": "alias-model", "input": "hi"})
                assert r.status_code == 200
                # Response model name is rewritten back to the client alias.
                assert r.json()["model"] == "alias-model"
        finally:
            await gw.stop()
            await s.stop()

    run(body())
