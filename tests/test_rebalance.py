"""Self-balancing pool (router/rebalance.py): headroom math, the
drain-cycle role-flip state machine, transfer-aware victim selection,
scaling advice, the kill-switch, minDwellS anti-thrash, the loader's
default transfer-aware-pair-scorer injection (+ its shadow twin's
live_twin_active path), and the live e2e where a decode pod flips to
prefill under traffic with zero client-visible errors.
"""

import asyncio
import time

import httpx
import pytest

from llm_d_inference_scheduler_tpu.router.config.loader import (
    Handle,
    load_config,
)
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    DRAINING_LABEL,
    ROLE_LABEL,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.rebalance import (
    RebalanceConfig,
    RebalanceController,
    merge_rebalance,
)

import llm_d_inference_scheduler_tpu.router.plugins  # noqa: F401  (register)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _pool(ds: Datastore, spec: dict[str, str]) -> None:
    """spec: address_port -> role."""
    for addr, role in spec.items():
        host, _, port = addr.rpartition(":")
        ds.endpoint_add_or_update(EndpointMetadata(
            name=addr, address=host, port=int(port),
            labels={ROLE_LABEL: role}))


def _load(ds: Datastore, addr: str, *, waiting: int = 0, running: int = 0,
          scraped_at: float | None = None) -> None:
    ep = ds.endpoint_get(addr)
    ep.metrics.waiting_queue_size = waiting
    ep.metrics.running_requests_size = running
    if scraped_at is not None:
        ep.metrics.update_time = scraped_at


def _controller(ds: Datastore, clock: FakeClock, **over) -> RebalanceController:
    cfg = RebalanceConfig(enabled=True, tick_s=1.0, min_dwell_s=5.0,
                          headroom_target=0.25, donor_headroom=0.6,
                          sustain_ticks=2, drain_timeout_s=30.0)
    for k, v in over.items():
        setattr(cfg, k, v)
    return RebalanceController(cfg, datastore=ds, clock=clock,
                               wall=lambda: clock.t + 1e9)


class TestConfig:
    def test_defaults_off(self):
        cfg = RebalanceConfig.from_spec(None)
        assert cfg.enabled is False
        assert cfg.min_dwell_s == 30.0

    def test_spec_roundtrip(self):
        cfg = RebalanceConfig.from_spec({
            "enabled": True, "tickS": 0.5, "minDwellS": 10,
            "headroomTarget": 0.3, "maxConcurrentFlips": 2,
            "advice": False})
        assert (cfg.enabled, cfg.tick_s, cfg.min_dwell_s) == (True, 0.5, 10.0)
        assert cfg.max_concurrent_flips == 2
        assert cfg.advice is False

    def test_validation(self):
        with pytest.raises(ValueError):
            RebalanceConfig.from_spec({"tickS": 0})
        with pytest.raises(ValueError):
            RebalanceConfig.from_spec({"headroomTarget": 1.5})
        with pytest.raises(ValueError):
            RebalanceConfig.from_spec({"headroomTarget": 0.7,
                                       "donorHeadroom": 0.3})


class TestKillSwitch:
    def test_disabled_tick_is_inert(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        clock = FakeClock()
        c = RebalanceController(RebalanceConfig(enabled=False),
                                datastore=ds, clock=clock,
                                wall=lambda: clock.t)
        assert c.tick() is None
        assert c.flips_total == 0
        assert len(c.series) == 0
        doc = c.snapshot()
        assert doc["enabled"] is False
        assert doc["flips"] == []
        # Roles untouched.
        assert ds.endpoint_get("10.0.0.1:8000").metadata.labels[
            ROLE_LABEL] == "decode"


class TestHeadroom:
    def test_idle_pool_full_headroom(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        c = _controller(ds, FakeClock())
        s = c.tick()
        assert s["headroom"]["decode"]["headroom"] == 1.0
        assert s["headroom"]["prefill"]["headroom"] == 1.0

    def test_queue_pressure_collapses_headroom(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        _load(ds, "10.0.0.1:8000", waiting=36)  # util = 36/40 = 0.9
        c = _controller(ds, FakeClock())
        s = c.tick()
        assert s["headroom"]["decode"]["headroom"] == pytest.approx(0.1)
        assert s["headroom"]["decode"]["util_queue"] == pytest.approx(0.9)

    def test_low_volume_miss_is_confidence_scaled(self):
        """A single straggler completing late in a quiet tick must not
        read as role starvation: its workload class can miss through the
        OTHER role's congestion (a prefill request's e2e includes its
        decode leg's queue wait)."""
        from llm_d_inference_scheduler_tpu.router.slo import _Agg

        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})

        class Led:
            by_workload = {"prefill": _Agg()}

        led = Led()
        led.by_workload["prefill"].requests = 1    # one served, one miss
        clock = FakeClock()
        c = RebalanceController(
            RebalanceConfig(enabled=True), datastore=ds, slo_ledger=led,
            clock=clock, wall=lambda: clock.t)
        s = c.tick()
        # miss 1.0 scaled by served/MISS_CONF_SERVED = 1/3.
        assert s["headroom"]["prefill"]["miss_rate"] == pytest.approx(
            1 / 3, abs=1e-4)
        assert s["headroom"]["prefill"]["headroom"] == pytest.approx(
            2 / 3, abs=1e-4)

    def test_miss_without_queue_never_flips(self):
        """Queue corroboration: a flip adds service slots, which only
        helps QUEUED work — full-strength miss evidence with an empty
        queue (service over budget / cross-role contamination) must not
        start a flip, however long it sustains."""
        from llm_d_inference_scheduler_tpu.router.slo import _Agg

        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode",
                   "10.0.0.3:8000": "prefill"})

        class Led:
            by_workload = {"prefill": _Agg()}

        led = Led()
        clock = FakeClock()
        c = RebalanceController(
            RebalanceConfig(enabled=True, min_dwell_s=0.0, sustain_ticks=1),
            datastore=ds, slo_ledger=led, clock=clock,
            wall=lambda: clock.t)
        for _ in range(5):
            led.by_workload["prefill"].requests += 10   # 10 served/tick,
            s = c.tick()                                # all missed
        assert s["headroom"]["prefill"]["miss_rate"] == 1.0
        assert not c._active and c.flips_total == 0
        # The same starvation WITH queued work flips immediately.
        _load(ds, "10.0.0.3:8000", waiting=8)
        led.by_workload["prefill"].requests += 10
        c.tick()
        assert len(c._active) == 1

    def test_workload_miss_rate_collapses_headroom(self):
        from llm_d_inference_scheduler_tpu.router.slo import _Agg

        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})

        class Led:
            by_workload = {"prefill": _Agg()}

        led = Led()
        led.by_workload["prefill"].requests = 10
        led.by_workload["prefill"].slo_met = 2
        clock = FakeClock()
        c = RebalanceController(
            RebalanceConfig(enabled=True), datastore=ds, slo_ledger=led,
            clock=clock, wall=lambda: clock.t)
        s = c.tick()
        # 8 of 10 prefill-heavy requests missed → prefill headroom 0.2.
        assert s["headroom"]["prefill"]["miss_rate"] == pytest.approx(0.8)
        assert s["headroom"]["prefill"]["headroom"] == pytest.approx(0.2)
        assert s["workloads"]["prefill"]["requests"] == 10
        # Second tick: deltas, not cumulative counts.
        s2 = c.tick()
        assert s2["workloads"]["prefill"]["requests"] == 0
        assert s2["headroom"]["prefill"]["miss_rate"] == 0.0


class TestFlipLifecycle:
    def _starved_decode(self) -> tuple[Datastore, FakeClock,
                                       RebalanceController]:
        """3 prefill (idle) + 1 decode (drowning): the controller should
        flip prefill pods to decode (one per dwell window)."""
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.4:8000": "prefill", "10.0.0.3:8000": "decode"})
        _load(ds, "10.0.0.3:8000", waiting=50)
        clock = FakeClock()
        c = _controller(ds, clock)
        clock.advance(5.0)  # past the boot dwell
        return ds, clock, c

    def test_flip_runs_the_drain_cycle(self):
        ds, clock, c = self._starved_decode()
        c.tick()                      # sustain 1/2
        assert not c._active
        c.tick()                      # sustain 2/2 → flip starts
        assert len(c._active) == 1
        flip = c._active[0]
        assert (flip.from_role, flip.to_role) == ("prefill", "decode")
        victim = flip.pod
        # Draining mark republished into the metadata (role filters key
        # off it) and the flip carries its full explanation.
        assert ds.endpoint_get(victim).metadata.labels[
            DRAINING_LABEL] == "true"
        for key in ("reason", "headroom", "pair_ewmas", "sustained_ticks"):
            assert key in flip.inputs
        # Not drained yet: no post-drain scrape landed.
        clock.advance(1.0)
        c.tick()
        assert flip.state == "draining"
        # An idle scrape lands after the drain started → the flip
        # completes and the role republishes atomically.
        _load(ds, victim, waiting=0, running=0, scraped_at=clock.t)
        clock.advance(1.0)
        c.tick()
        assert flip.state == "completed"
        labels = ds.endpoint_get(victim).metadata.labels
        assert labels[ROLE_LABEL] == "decode"
        assert DRAINING_LABEL not in labels
        assert c.flips_total == 1
        assert c.snapshot()["flips"][0]["state"] == "completed"

    def test_draining_pod_excluded_from_role_filters(self):
        from llm_d_inference_scheduler_tpu.router.plugins.filters import (
            DecodeFilter,
            PrefillFilter,
        )

        ds, clock, c = self._starved_decode()
        c.tick()
        c.tick()
        victim = c._active[0].pod
        eps = ds.endpoint_list()
        kept_prefill = PrefillFilter().filter(None, None, None, eps)
        kept_decode = DecodeFilter().filter(None, None, None, eps)
        assert victim not in [e.metadata.address_port for e in kept_prefill]
        assert victim not in [e.metadata.address_port for e in kept_decode]

    def test_min_dwell_prevents_thrash(self):
        ds, clock, c = self._starved_decode()
        c.tick()
        c.tick()
        victim = c._active[0].pod
        _load(ds, victim, waiting=0, running=0, scraped_at=clock.t + 0.5)
        clock.advance(1.0)
        c.tick()
        assert c.flips_total == 1
        # The pool is STILL imbalanced (decode queue never moved in this
        # synthetic pool) — but the dwell must hold the next flip back.
        for _ in range(10):
            clock.advance(0.2)
            c.tick()
        assert c.flips_total == 1 and not c._active
        # Past the dwell the controller may act again.
        clock.advance(5.0)
        c.tick()
        c.tick()
        assert len(c._active) == 1

    def test_never_drains_the_last_donor_pod(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.3:8000": "decode"})
        _load(ds, "10.0.0.3:8000", waiting=50)
        clock = FakeClock()
        c = _controller(ds, clock)
        clock.advance(10.0)
        for _ in range(5):
            c.tick()
        assert not c._active and c.flips_total == 0

    def test_drain_timeout_completes_anyway(self):
        ds, clock, c = self._starved_decode()
        c.tick()
        c.tick()
        flip = c._active[0]
        _load(ds, flip.pod, waiting=0, running=3,
              scraped_at=clock.t + 0.5)  # never goes idle
        clock.advance(31.0)  # past drainTimeoutS
        c.tick()
        assert flip.state == "completed"
        assert flip.drain_timed_out is True
        assert ds.endpoint_get(flip.pod).metadata.labels[
            ROLE_LABEL] == "decode"

    def test_non_acting_follower_never_flips(self):
        ds, clock, _ = self._starved_decode()
        c = RebalanceController(
            RebalanceConfig(enabled=True, min_dwell_s=0.0, sustain_ticks=1),
            datastore=ds, acting=False, clock=clock,
            wall=lambda: clock.t)
        for _ in range(5):
            s = c.tick()
        assert s is not None and not c._active  # observes, never acts
        c.promote()
        assert c.acting is True
        c.tick()
        assert len(c._active) == 1


class TestVictimSelection:
    def test_decode_to_prefill_prefers_cheapest_future_pairs(self):
        """3 decode pods, prefill starving: the victim should be the pod
        whose measured (victim-as-prefill, remaining-decode) pulls are
        cheapest; the unmeasured candidate scores neutral."""
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode",
                   "10.0.0.3:8000": "decode", "10.0.0.9:8000": "prefill"})
        _load(ds, "10.0.0.9:8000", waiting=50)
        # d1 pairs expensive, d2 cheap; d3 unmeasured (neutral mean).
        for peer in ("10.0.0.2:8000", "10.0.0.3:8000"):
            ds.transfers.record("10.0.0.1:8000", peer, pull_ms=40.0)
        for peer in ("10.0.0.1:8000", "10.0.0.3:8000"):
            ds.transfers.record("10.0.0.2:8000", peer, pull_ms=1.0)
        clock = FakeClock()
        c = _controller(ds, clock, sustain_ticks=1, min_dwell_s=0.0)
        clock.advance(1.0)
        c.tick()
        assert len(c._active) == 1
        flip = c._active[0]
        assert flip.pod == "10.0.0.2:8000"
        rows = flip.inputs["pair_ewmas"]
        assert rows["10.0.0.2:8000"]["chosen"] is True
        assert rows["10.0.0.1:8000"]["mean_pair_pull_ms"] == 40.0
        assert rows["10.0.0.3:8000"]["mean_pair_pull_ms"] is None

    def test_prefill_to_decode_gives_up_most_expensive_pairs(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.3:8000": "decode"})
        _load(ds, "10.0.0.3:8000", waiting=50)
        ds.transfers.record("10.0.0.1:8000", "10.0.0.3:8000", pull_ms=1.0)
        ds.transfers.record("10.0.0.2:8000", "10.0.0.3:8000", pull_ms=40.0)
        clock = FakeClock()
        c = _controller(ds, clock, sustain_ticks=1, min_dwell_s=0.0)
        clock.advance(1.0)
        c.tick()
        assert c._active[0].pod == "10.0.0.2:8000"  # losing it costs least

    def test_load_breaks_ties(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.3:8000": "decode"})
        _load(ds, "10.0.0.3:8000", waiting=50)
        _load(ds, "10.0.0.1:8000", running=3)  # busier → drains slower
        clock = FakeClock()
        c = _controller(ds, clock, sustain_ticks=1, min_dwell_s=0.0)
        clock.advance(1.0)
        c.tick()
        assert c._active[0].pod == "10.0.0.2:8000"


class TestAdvice:
    def test_up_when_no_donor(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.3:8000": "decode"})
        _load(ds, "10.0.0.3:8000", waiting=50)
        _load(ds, "10.0.0.1:8000", waiting=50)
        c = _controller(ds, FakeClock())
        c.tick()
        advice = c.snapshot()["advice"]
        assert advice["decode"]["direction"] == "up"
        assert advice["prefill"]["direction"] == "up"

    def test_down_when_idle_against_healthy_peer(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.3:8000": "decode"})
        c = _controller(ds, FakeClock())
        c.tick()
        advice = c.snapshot()["advice"]
        assert advice["prefill"]["direction"] == "down"
        # Single decode pod (n < 2) never advises down.
        assert advice["decode"]["direction"] == "hold"

    def test_hop_skip_rate_feeds_prefill_down_evidence(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.3:8000": "decode"})
        skips = {"n": 0}
        clock = FakeClock()
        c = RebalanceController(
            RebalanceConfig(enabled=True), datastore=ds,
            hop_skips_fn=lambda: skips["n"], clock=clock,
            wall=lambda: clock.t)
        skips["n"] = 10
        s = c.tick()
        assert s["hop_skip_rate"] > 0
        advice = c.snapshot()["advice"]
        assert "hop-skip" in advice["prefill"]["why"]

    def test_advice_gauges_are_exported(self):
        from llm_d_inference_scheduler_tpu.router.metrics import REGISTRY

        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.3:8000": "decode"})
        c = _controller(ds, FakeClock())
        c.tick()
        assert REGISTRY.get_sample_value(
            "router_pool_advice",
            {"role": "prefill", "direction": "down"}) == 1.0
        assert REGISTRY.get_sample_value(
            "router_rebalance_headroom", {"role": "decode"}) == 1.0


class TestMergeRebalance:
    def test_merge_annotates_shards(self):
        leader = {"enabled": True, "acting": True, "flips_total": 2,
                  "advice": {"prefill": {"direction": "hold"}},
                  "flips": [{"pod": "a", "started_unix": 5.0},
                            {"pod": "b", "started_unix": 9.0}]}
        follower = {"enabled": True, "acting": False, "flips_total": 0,
                    "flips": []}
        doc = merge_rebalance([(0, leader), (1, follower)])
        assert doc["workers"] == 2
        assert doc["acting_shards"] == [0]
        assert doc["flips_total"] == 2
        assert doc["flips"][0] == {"pod": "b", "started_unix": 9.0,
                                   "shard": 0}
        assert doc["advice"] == leader["advice"]
        assert doc["shards"]["1"]["acting"] is False


class TestTimelineSeries:
    def test_sampler_records_rebalance_row(self):
        from llm_d_inference_scheduler_tpu.router.timeline import (
            TimelineConfig,
            TimelineSampler,
        )

        class Stub:
            enabled = True
            flips_total = 3
            active_count = 1
            last_headroom = {"prefill": 0.2, "decode": 0.9}

        s = TimelineSampler(TimelineConfig(), rebalance=Stub())
        sample = s.tick(wall=100.0)
        assert sample["rebalance"] == {
            "flips": 3, "draining": 1,
            "headroom": {"prefill": 0.2, "decode": 0.9}}
        Stub.flips_total = 4
        sample = s.tick(wall=101.0)
        assert sample["rebalance"]["flips"] == 1


# ---- loader default pair scorer + shadow live-twin (satellite 1) ----------

PAIR_CFG = """
shadow:
  policies: [{type: transfer-pair, parameters: {weight: 2.0}}]
plugins:
  - {type: decode-filter}
  - {type: prefill-filter}
  - {type: queue-scorer}
  - type: disagg-profile-handler
    parameters: {pdDecider: {type: always-disagg-pd-decider}}
schedulingProfiles:
  - name: decode
    plugins: [{pluginRef: decode-filter}, {pluginRef: queue-scorer}]
  - name: prefill
    plugins: [{pluginRef: prefill-filter}, {pluginRef: queue-scorer}]
"""


class TestDefaultPairScorer:
    def test_loader_injects_into_prefill_profile(self):
        ds = Datastore()
        cfg = load_config(PAIR_CFG, Handle(datastore=ds))
        names = [str(ws.scorer.typed_name())
                 for ws in cfg.scheduler.profiles["prefill"].scorers]
        assert "transfer-aware-pair-scorer/transfer-aware-pair-scorer" \
            in names
        ws = cfg.scheduler.profiles["prefill"].scorers[-1]
        assert ws.weight == 2.0
        # The decode profile stays pair-blind.
        assert not any("transfer-aware" in str(w.scorer.typed_name())
                       for w in cfg.scheduler.profiles["decode"].scorers)
        # The raw doc (and so /debug/config + the config hash) is served
        # verbatim — the injection must not leak into it.
        assert "transfer-aware" not in str(cfg.raw_doc)

    def test_opt_out_and_explicit_declaration(self):
        off = PAIR_CFG + "\ndisagg:\n  pairScorer: {enabled: false}\n"
        cfg = load_config(off, Handle(datastore=Datastore()))
        assert not any("transfer-aware" in str(w.scorer.typed_name())
                       for w in cfg.scheduler.profiles["prefill"].scorers)
        explicit = PAIR_CFG.replace(
            "  - {type: queue-scorer}",
            "  - {type: queue-scorer}\n  - {type: transfer-aware-pair-scorer}"
        ).replace(
            "plugins: [{pluginRef: prefill-filter}, {pluginRef: queue-scorer}]",
            "plugins: [{pluginRef: prefill-filter}, "
            "{pluginRef: transfer-aware-pair-scorer, weight: 7}]")
        cfg = load_config(explicit, Handle(datastore=Datastore()))
        pair = [ws for ws in cfg.scheduler.profiles["prefill"].scorers
                if "transfer-aware" in str(ws.scorer.typed_name())]
        assert len(pair) == 1 and pair[0].weight == 7.0

    def test_cold_table_scores_nothing(self):
        """Unmeasured-pair neutrality: on a cold TransferTable the injected
        scorer returns no scores, so profile totals are bit-identical to
        the pair-blind profile."""
        from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
            Endpoint,
        )

        ds = Datastore()
        cfg = load_config(PAIR_CFG, Handle(datastore=ds))
        scorer = [ws.scorer
                  for ws in cfg.scheduler.profiles["prefill"].scorers
                  if "transfer-aware" in str(ws.scorer.typed_name())][0]
        ep = Endpoint(EndpointMetadata(name="p", address="10.0.0.1",
                                       port=8200))
        req = type("R", (), {"decode_pick": "10.0.0.9:8000"})()
        assert scorer.score(None, None, req, [ep]) == {}

    def test_shadow_twin_takes_live_twin_active_path(self):
        """With the default injection live, the transfer-pair shadow
        policy must detect its live twin in the profile's raw scores and
        evaluate the totals as-is (activation monitoring — no double
        weighting, no false divergences)."""
        from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
            Endpoint,
        )
        from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
            InferenceRequest,
            InferenceRequestBody,
            ProfileRunResult,
            SchedulingResult,
        )
        from llm_d_inference_scheduler_tpu.router.shadow import (
            ShadowConfig,
            ShadowEvaluator,
        )

        ds = Datastore()
        cfg = load_config(PAIR_CFG, Handle(datastore=ds))
        pair_name = [str(ws.scorer.typed_name())
                     for ws in cfg.scheduler.profiles["prefill"].scorers
                     if "transfer-aware" in str(ws.scorer.typed_name())][0]
        ds.transfers.record("10.0.0.1:8200", "10.0.0.9:8000", pull_ms=1.0)
        ds.transfers.record("10.0.0.2:8200", "10.0.0.9:8000", pull_ms=40.0)

        def _ep(addr):
            host, _, port = addr.rpartition(":")
            return Endpoint(EndpointMetadata(name=addr, address=host,
                                             port=int(port)))

        result = SchedulingResult(
            profile_results={
                "decode": ProfileRunResult(
                    target_endpoints=[_ep("10.0.0.9:8000")]),
                "prefill": ProfileRunResult(
                    target_endpoints=[_ep("10.0.0.1:8200")],
                    totals={"10.0.0.1:8200": 3.0, "10.0.0.2:8200": 1.0},
                    raw_scores={pair_name: {"10.0.0.1:8200": 1.0,
                                            "10.0.0.2:8200": 0.0}}),
            },
            primary_profile_name="decode")
        ev = ShadowEvaluator(ShadowConfig.from_spec(cfg.shadow),
                             datastore=ds)
        req = InferenceRequest(request_id="lt-1", target_model="tiny",
                               body=InferenceRequestBody(
                                   completions={"prompt": "p"}))
        ev.submit(req, result)
        assert ev.flush()
        ev.stop()
        entry = req.shadow.entries["transfer-pair"]
        assert entry["live_twin_active"] is True
        assert entry["verdict"] == "agree"


class TestResyncPreservesOverrides:
    def test_external_resync_cannot_revert_flip_or_drain(self):
        """A kube pod event or config-file reconcile rebuilds metadata
        from the pre-flip source of truth; the rebalancer's role flip and
        draining mark must survive it (they'd otherwise silently revert
        while the controller still reports them at /debug/rebalance)."""
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode"})
        source = [EndpointMetadata(name=addr, address=addr.rpartition(":")[0],
                                   port=8000, labels={ROLE_LABEL: "decode"})
                  for addr in ("10.0.0.1:8000", "10.0.0.2:8000")]
        assert ds.set_endpoint_draining("10.0.0.1:8000", True)
        ds.resync(source)
        labels = ds.endpoint_get("10.0.0.1:8000").metadata.labels
        assert labels[DRAINING_LABEL] == "true"
        # Flip completes (role republish clears the draining mark) — then
        # another reconcile lands with the stale decode label.
        assert ds.set_endpoint_role("10.0.0.1:8000", "prefill")
        ds.resync(source)
        labels = ds.endpoint_get("10.0.0.1:8000").metadata.labels
        assert labels[ROLE_LABEL] == "prefill"
        assert DRAINING_LABEL not in labels
        # The untouched pod still follows the external source verbatim.
        assert ds.endpoint_get("10.0.0.2:8000").metadata.labels[
            ROLE_LABEL] == "decode"
        # A pod that leaves the pool drops its overlay: a fresh pod at
        # the same address reads the source of truth again.
        ds.endpoint_delete("10.0.0.1:8000")
        ds.resync(source)
        assert ds.endpoint_get("10.0.0.1:8000").metadata.labels[
            ROLE_LABEL] == "decode"


class TestSkipRateFloor:
    def test_stale_skip_residue_is_not_donor_evidence(self):
        """The hop-skip EWMA decays exponentially and never reaches 0.0:
        a single ancient burst must not keep lowering the prefill donor
        bar — only a rate above SKIP_RATE_MIN counts as evidence."""
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "prefill", "10.0.0.2:8000": "prefill",
                   "10.0.0.3:8000": "decode"})
        # Prefill merely healthy: headroom 0.5 — between headroomTarget
        # (0.25) and donorHeadroom (0.6), so it may donate ONLY with
        # skip evidence.
        _load(ds, "10.0.0.1:8000", waiting=4)
        _load(ds, "10.0.0.2:8000", waiting=4)
        skips = {"n": 0}
        clock = FakeClock()
        cfg = RebalanceConfig(enabled=True, tick_s=1.0, min_dwell_s=0.0,
                              headroom_target=0.25, donor_headroom=0.6,
                              sustain_ticks=2, drain_timeout_s=30.0)
        c = RebalanceController(cfg, datastore=ds, clock=clock,
                                hop_skips_fn=lambda: skips["n"],
                                wall=lambda: clock.t + 1e9)
        clock.advance(5.0)
        # An old burst, then silence: the EWMA decays below the floor
        # (3.0 * 0.7^15 ≈ 0.014) while the pool stays balanced.
        skips["n"] = 10
        c.tick()
        for _ in range(15):
            clock.advance(1.0)
            c.tick()
        assert 0.0 < c._skip_rate < 0.05
        # Decode starves NOW — the residue must not lower the donor bar.
        _load(ds, "10.0.0.3:8000", waiting=50)
        for _ in range(4):
            clock.advance(1.0)
            c.tick()
        assert not c._active and c.flips_total == 0
        # A FRESH sustained skip burst is real evidence: bar drops to the
        # headroom target and the flip starts.
        skips["n"] += 10
        clock.advance(1.0)
        c.tick()
        skips["n"] += 10
        clock.advance(1.0)
        c.tick()
        assert len(c._active) == 1
        assert c._active[0].inputs["skip_evidence"] is True

# ---- live e2e: a decode pod flips to prefill under traffic ----------------

GW, PRE, D1, D2, S1, S2 = 19540, 19541, 19542, 19543, 19544, 19545

E2E_CFG = f"""
rebalance:
  enabled: true
  tickS: 3600            # manual ticks drive the test deterministically
  minDwellS: 0
  sustainTicks: 2
  headroomTarget: 0.5
  donorHeadroom: 0.6
  drainTimeoutS: 30
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {S1}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {S2}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 64}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""


def test_decode_pod_flips_to_prefill_under_live_traffic():
    """The acceptance e2e: prefill starves under a cold-prompt burst while
    the decode side idles; the controller flips a decode pod through the
    drain cycle with ZERO client-visible errors, in-flight decode streams
    on the flipping pod run to ``[DONE]``, and the flip is explainable at
    /debug/rebalance."""
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
    from llm_d_inference_scheduler_tpu.router.sidecar import (
        Sidecar,
        SidecarConfig,
    )

    async def body():
        def sim(port, role, prefill_ms):
            return EngineConfig(
                backend="sim", model="tiny", port=port, role=role,
                max_batch=1 if role == "prefill" else 4,
                max_model_len=4096,
                sim_prefill_ms_per_token=prefill_ms,
                sim_decode_ms_per_token=10.0)

        engines = [EngineServer(sim(PRE, "prefill", 1.2)),
                   EngineServer(sim(D1, "decode", 0.05)),
                   EngineServer(sim(D2, "decode", 0.05))]
        for e in engines:
            await e.start()
        sidecars = [
            Sidecar(SidecarConfig(port=S1,
                                  decoder_url=f"http://127.0.0.1:{D1}")),
            Sidecar(SidecarConfig(port=S2,
                                  decoder_url=f"http://127.0.0.1:{D2}")),
        ]
        for s in sidecars:
            await s.start()
        gw = build_gateway(E2E_CFG, port=GW, poll_interval=0.05)
        await gw.start()
        statuses: list[int] = []
        stream_done: list[bool] = []
        try:
            async with httpx.AsyncClient(timeout=120) as c:

                async def stream_one(i: int) -> None:
                    saw_done = False
                    async with c.stream(
                            "POST", f"http://127.0.0.1:{GW}/v1/completions",
                            json={"model": "tiny", "prompt": f"s{i}",
                                  "max_tokens": 200, "stream": True}
                    ) as r:
                        statuses.append(r.status_code)
                        async for line in r.aiter_lines():
                            if line == "data: [DONE]":
                                saw_done = True
                    stream_done.append(saw_done)

                async def prefill_one(i: int) -> None:
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny",
                              "prompt": f"cold doc {i} " + "w " * 400,
                              "max_tokens": 1})
                    statuses.append(r.status_code)

                # Live decode streams on BOTH decode pods (~2 s each;
                # staggered so the queue scorer spreads them) + a
                # cold-prompt burst that drowns the single prefill pod.
                tasks = []
                for i in range(4):
                    tasks.append(asyncio.get_running_loop().create_task(
                        stream_one(i)))
                    await asyncio.sleep(0.12)
                tasks += [asyncio.get_running_loop().create_task(
                    prefill_one(i)) for i in range(8)]
                await asyncio.sleep(0.4)  # queues build + scrape lands

                # Manual grid ticks: sustain the imbalance → a decode pod
                # starts draining while its stream is still live.
                flip = None
                for _ in range(40):
                    gw.rebalancer.tick()
                    if gw.rebalancer._active:
                        flip = gw.rebalancer._active[0]
                        break
                    await asyncio.sleep(0.1)
                assert flip is not None, "no flip started"
                assert (flip.from_role, flip.to_role) == ("decode",
                                                          "prefill")
                victim = flip.pod
                assert gw.datastore.endpoint_get(victim).metadata.labels[
                    DRAINING_LABEL] == "true"

                # Tick until the drain cycle completes (streams finish,
                # an idle scrape lands, the role republishes).
                for _ in range(200):
                    gw.rebalancer.tick()
                    if flip.state == "completed":
                        break
                    await asyncio.sleep(0.1)
                assert flip.state == "completed"
                labels = gw.datastore.endpoint_get(victim).metadata.labels
                assert labels[ROLE_LABEL] == "prefill"
                assert DRAINING_LABEL not in labels

                # Every in-flight request (streams included) finished
                # clean: zero client-visible errors through the flip.
                await asyncio.gather(*tasks)
                assert statuses and all(s == 200 for s in statuses)
                assert stream_done and all(stream_done)

                # The flip is fully explainable at /debug/rebalance.
                doc = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/rebalance")).json()
                assert doc["flips_total"] == 1
                rec = doc["flips"][0]
                assert rec["pod"] == victim and rec["state"] == "completed"
                for key in ("reason", "headroom", "pair_ewmas",
                            "sustained_ticks"):
                    assert key in rec["inputs"]
                assert rec["inputs"]["headroom"]["prefill"][
                    "headroom"] < 0.5
                # And the headroom gauge family moved.
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert 'router_role_flips_total{from="decode",to="prefill"}' \
                    in m
        finally:
            await gw.stop()
            for s in sidecars:
                await s.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())
