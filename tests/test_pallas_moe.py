"""Grouped-matmul MoE kernel vs the dense-over-experts reference math.

Interpret-mode on CPU (same strategy as test_pallas_paged_attention.py);
compiled-on-TPU validation happens in the bench A/B.
"""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from llm_d_inference_scheduler_tpu.models.configs import ModelConfig
from llm_d_inference_scheduler_tpu.models.llama import _moe_ffn
from llm_d_inference_scheduler_tpu.ops.pallas_moe import moe_ffn_grouped


def _mk(E=4, D=128, F=256, k=2, seed=0):
    key = jax.random.key(seed)
    ks = jax.random.split(key, 4)
    lp = {
        "router": jax.random.normal(ks[0], (D, E), jnp.float32) * D ** -0.5,
        "w1": jax.random.normal(ks[1], (E, D, F), jnp.float32) * D ** -0.5,
        "w3": jax.random.normal(ks[2], (E, D, F), jnp.float32) * D ** -0.5,
        "w2": jax.random.normal(ks[3], (E, F, D), jnp.float32) * F ** -0.5,
    }
    cfg = ModelConfig(name="t", vocab_size=8, d_model=D, n_layers=1,
                      n_heads=2, n_kv_heads=1, d_ff=F, n_experts=E,
                      experts_per_token=k)
    return lp, cfg


@pytest.mark.parametrize("shape", [(1, 1), (2, 3), (4, 8)])
def test_grouped_matches_dense(shape):
    B, S = shape
    lp, cfg = _mk()
    x = jax.random.normal(jax.random.key(7), (B, S, cfg.d_model), jnp.float32)
    dense = _moe_ffn(cfg, lp, x)
    grouped = moe_ffn_grouped(lp, x, cfg.n_experts, cfg.experts_per_token,
                              tm=8, tf=128, interpret=True)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_grouped_skewed_routing():
    """All tokens on one expert (maximally ragged groups)."""
    lp, cfg = _mk(E=4, k=1)
    # Bias the router so expert 2 wins everywhere.
    lp["router"] = lp["router"].at[:, 2].add(100.0)
    x = jax.random.normal(jax.random.key(9), (2, 5, cfg.d_model), jnp.float32)
    dense = _moe_ffn(cfg, lp, x)
    grouped = moe_ffn_grouped(lp, x, cfg.n_experts, 1, tm=8, tf=128,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_engine_grouped_moe_matches_dense():
    """tiny-moe engine: grouped kernel produces the same greedy tokens as
    the dense-over-experts path (full prefill+paged-decode pipeline)."""
    import asyncio

    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
    from llm_d_inference_scheduler_tpu.models import llama
    from llm_d_inference_scheduler_tpu.models.configs import get_config

    # f32 params: keeps greedy argmax insensitive to the two impls' different
    # rounding points (bf16 numeric tolerance is covered by test_grouped_bf16).
    params = llama.init_params(get_config("tiny-moe"), jax.random.key(11),
                               dtype=jnp.float32)

    async def run(pallas_moe: bool):
        cfg = EngineConfig(model="tiny-moe", backend="tpu", max_batch=2,
                           max_model_len=64, seed=11, decode_chunk=4,
                           pallas_moe=pallas_moe, pallas_interpret=pallas_moe)
        eng = TpuEngine(cfg, params=params)
        await eng.start()
        try:
            req = EngineRequest(request_id="moe", prompt_token_ids=[1, 5, 9, 13],
                                max_tokens=6, temperature=0.0, ignore_eos=True)
            out = eng.submit(req)
            got = []
            while True:
                ev = await out.get()
                if ev.token_id is not None:
                    got.append(ev.token_id)
                if ev.finish_reason is not None:
                    break
            return got
        finally:
            await eng.stop()

    dense = asyncio.run(run(False))
    grouped = asyncio.run(run(True))
    assert len(dense) == 6
    assert grouped == dense


def test_grouped_rejects_unaligned_dff():
    """F with no 128-aligned divisor must raise, not silently drop columns."""
    lp, cfg = _mk(D=128, F=192)
    x = jax.random.normal(jax.random.key(1), (1, 2, cfg.d_model), jnp.float32)
    with pytest.raises(ValueError, match="tile divisor"):
        moe_ffn_grouped(lp, x, cfg.n_experts, cfg.experts_per_token,
                        interpret=True)


def test_grouped_nondefault_tile_divisor():
    """F=384 divides by 384 (not the default 512): tail must be computed."""
    lp, cfg = _mk(D=128, F=384)
    x = jax.random.normal(jax.random.key(2), (2, 3, cfg.d_model), jnp.float32)
    dense = _moe_ffn(cfg, lp, x)
    grouped = moe_ffn_grouped(lp, x, cfg.n_experts, cfg.experts_per_token,
                              tm=8, interpret=True)
    np.testing.assert_allclose(np.asarray(grouped), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_grouped_bf16():
    lp, cfg = _mk()
    lp = jax.tree.map(lambda a: a.astype(jnp.bfloat16), lp)
    x = jax.random.normal(jax.random.key(3), (2, 4, cfg.d_model), jnp.bfloat16)
    dense = _moe_ffn(cfg, lp, x)
    grouped = moe_ffn_grouped(lp, x, cfg.n_experts, cfg.experts_per_token,
                              tm=16, tf=128, interpret=True)
    np.testing.assert_allclose(
        np.asarray(grouped, np.float32), np.asarray(dense, np.float32),
        atol=3e-2, rtol=3e-2)
