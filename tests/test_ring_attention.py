"""Ring attention vs reference causal attention on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_inference_scheduler_tpu.ops import causal_attention
from llm_d_inference_scheduler_tpu.parallel import make_mesh, make_ring_attention_fn


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_reference(sp):
    devices = jax.devices()
    assert len(devices) >= 8, "conftest must provide 8 virtual CPU devices"
    mesh = make_mesh(devices[: 2 * sp], tp=1, sp=sp)

    B, S, H, Hkv, D = 2, 8 * sp, 4, 2, 16
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)

    ref = causal_attention(q, k, v)
    ring_fn = make_ring_attention_fn(mesh)
    with jax.set_mesh(mesh):
        out = jax.jit(lambda q, k, v: ring_fn(q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("sp", [2, 4])
def test_ulysses_matches_reference(sp):
    from llm_d_inference_scheduler_tpu.parallel.ulysses import make_ulysses_attention_fn

    devices = jax.devices()
    mesh = make_mesh(devices[: 2 * sp], tp=1, sp=sp)

    B, S, H, Hkv, D = 2, 8 * sp, 8, 4, 16
    key = jax.random.key(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.float32)
    k = jax.random.normal(kk, (B, S, Hkv, D), jnp.float32)
    v = jax.random.normal(kv, (B, S, Hkv, D), jnp.float32)

    ref = causal_attention(q, k, v)
    fn = make_ulysses_attention_fn(mesh)
    with jax.set_mesh(mesh):
        out = jax.jit(lambda q, k, v: fn(q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
