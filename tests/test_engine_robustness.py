"""Engine robustness: aborts, stop handling, rejection, P/D edge cases."""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
from llm_d_inference_scheduler_tpu.engine.request import FinishReason
from llm_d_inference_scheduler_tpu.engine.server import EngineServer


def run(coro):
    return asyncio.run(coro)


def _cfg(backend, port, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_model_len", 128)
    return EngineConfig(backend=backend, port=port, **kw)


def test_abort_mid_decode_frees_blocks():
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0))
        await eng.start()
        try:
            req = EngineRequest(request_id="long", prompt_token_ids=[1, 5, 6],
                                max_tokens=100, stop_token_ids=(99999,))
            out = eng.submit(req)
            ev = await asyncio.wait_for(out.get(), timeout=30)  # first token
            assert ev.token_id is not None
            eng.abort("long")
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            assert ev.finish_reason == FinishReason.ABORT
            for _ in range(50):  # engine thread frees asynchronously
                if eng.allocator.free_blocks == eng.n_blocks - 1:
                    break
                await asyncio.sleep(0.05)
            assert eng.allocator.free_blocks == eng.n_blocks - 1
        finally:
            await eng.stop()

    run(body())


def test_impossible_request_rejected_not_wedged():
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        cfg = _cfg("tpu", 0, max_model_len=128, hbm_kv_blocks=3)
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            # Needs 8 blocks of 16, only 2 usable exist -> immediate abort.
            big = EngineRequest(request_id="big", prompt_token_ids=[1] * 100,
                                max_tokens=28)
            out = eng.submit(big)
            ev = await asyncio.wait_for(out.get(), timeout=10)
            assert ev.finish_reason == FinishReason.ABORT
            # Engine still serves normal requests afterwards.
            ok = EngineRequest(request_id="ok", prompt_token_ids=[1, 2, 3], max_tokens=2)
            out2 = eng.submit(ok)
            while True:
                ev = await asyncio.wait_for(out2.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            assert ev.finish_reason in (FinishReason.LENGTH, FinishReason.STOP)
        finally:
            await eng.stop()

    run(body())


def test_pd_import_block_count_exceeds_decode_allocation():
    """Exporter retained more blocks (prompt+16 default) than the decode side
    would allocate for max_tokens=1; import must still work."""
    async def body():
        pre = EngineServer(_cfg("tpu", 18321, role="prefill"))
        dec = EngineServer(_cfg("tpu", 18322, role="decode"))
        await pre.start()
        await dec.start()
        try:
            prompt = [1] + list(range(10, 23))  # 14 tokens: 1 block of 16...
            async with httpx.AsyncClient(timeout=60) as c:
                r1 = await c.post("http://127.0.0.1:18321/v1/completions", json={
                    "prompt": prompt,  # server default max_tokens=16 -> 2 blocks
                    "kv_transfer_params": {"do_remote_decode": True}})
                ktp = r1.json()["kv_transfer_params"]
                assert ktp["remote_num_blocks"] == 2
                r2 = await c.post("http://127.0.0.1:18322/v1/completions", json={
                    "prompt": prompt, "max_tokens": 1,
                    "kv_transfer_params": ktp})
                assert r2.status_code == 200
                assert r2.json()["usage"]["completion_tokens"] >= 1
        finally:
            await pre.stop()
            await dec.stop()

    run(body())


def test_stop_strings_and_stop_token_ids():
    async def body():
        cfg = _cfg("sim", 18323)
        server = EngineServer(cfg)
        await server.start()
        try:
            async with httpx.AsyncClient(base_url="http://127.0.0.1:18323",
                                         timeout=30) as c:
                # sim emits "lorem ipsum dolor ..." -> stop at "ipsum"
                r = await c.post("/v1/completions", json={
                    "prompt": "x", "max_tokens": 30, "stop": ["ipsum"]})
                body_ = r.json()
                assert body_["choices"][0]["finish_reason"] == "stop"
                assert "ipsum" not in body_["choices"][0]["text"]
                assert body_["choices"][0]["text"].startswith("lorem")
        finally:
            await server.stop()

    run(body())


def test_kv_export_ttl_sweep():
    async def body():
        from llm_d_inference_scheduler_tpu.engine import core as core_mod
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0))
        old_ttl = core_mod.KV_EXPORT_TTL_S
        core_mod.KV_EXPORT_TTL_S = 0.2
        await eng.start()
        try:
            req = EngineRequest(request_id="exp", prompt_token_ids=[1, 2, 3],
                                max_tokens=1,
                                kv_transfer_params={"do_remote_decode": True})
            out = eng.submit(req)
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            assert ev.kv_transfer_params is not None
            assert "exp" in eng.kv_exports
            await asyncio.sleep(0.5)
            # Submit another request so the engine loop runs a sweep.
            out2 = eng.submit(EngineRequest(request_id="poke",
                                            prompt_token_ids=[1, 2], max_tokens=1))
            while True:
                ev = await asyncio.wait_for(out2.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            assert "exp" not in eng.kv_exports
        finally:
            core_mod.KV_EXPORT_TTL_S = old_ttl
            await eng.stop()

    run(body())


def test_stream_stop_string_across_token_boundary():
    """Sim emits one char per token; a multi-char stop string must not leak
    its prefix into the SSE stream."""
    async def body():
        cfg = _cfg("sim", 18324)
        server = EngineServer(cfg)
        await server.start()
        try:
            async with httpx.AsyncClient(base_url="http://127.0.0.1:18324",
                                         timeout=30) as c:
                text = ""
                finish = None
                async with c.stream("POST", "/v1/completions", json={
                        "prompt": "x", "max_tokens": 30, "stream": True,
                        "stop": ["m ips"]}) as r:
                    async for line in r.aiter_lines():
                        if not line.startswith("data: ") or line == "data: [DONE]":
                            continue
                        import json as _json
                        doc = _json.loads(line[6:])
                        ch = doc["choices"][0]
                        text += ch.get("text", "")
                        if ch.get("finish_reason"):
                            finish = ch["finish_reason"]
                            assert doc["usage"]["prompt_tokens"] > 0
                assert finish == "stop"
                assert text == "lore", repr(text)  # truncated before "m ips"
        finally:
            await server.stop()

    run(body())


def test_over_context_prompt_rejected_400():
    """OpenAI/vLLM contract: a prompt that cannot fit the model context with
    at least one generated token is a 400, not a silently truncated serve."""
    async def body():
        srv = EngineServer(_cfg("tpu", 18467))
        await srv.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post("http://127.0.0.1:18467/v1/completions",
                                 json={"prompt": list(range(3, 131)),
                                       "max_tokens": 4})
                assert r.status_code == 400
                assert "maximum context length" in r.text

                # At the boundary (prompt + 1 generated == max_model_len): ok.
                r = await c.post("http://127.0.0.1:18467/v1/completions",
                                 json={"prompt": list(range(3, 130)),
                                       "max_tokens": 4, "ignore_eos": True})
                assert r.status_code == 200
                assert r.json()["usage"]["completion_tokens"] == 1
        finally:
            await srv.stop()

    run(body())


def test_mixed_admission_fuzz_batched_and_chunked():
    """Randomized mix of short/long prompts, mid-flight aborts, and varied
    max_tokens against an engine running BOTH batched prefill (groups of 4)
    and incremental prefill (32-token windows) with prefix caching on:
    every request must terminate, and every block must come back."""
    import random

    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    rng = random.Random(11)

    async def body():
        eng = TpuEngine(_cfg("tpu", 0, max_batch=6, max_model_len=256,
                             decode_chunk=4, kv_events_port=0, seed=11,
                             prefill_batch=4, prefill_chunk=32))
        await eng.start()
        outcomes = {"finished": 0, "aborted": 0}
        try:
            async def one(i):
                n_prompt = rng.choice([8, 30, 30, 90, 150])
                base = rng.randrange(3)  # some identical prompts → dedupe
                prompt = [1] + [(base * 131 + j * 7) % 400 + 3
                                for j in range(n_prompt)]
                req = EngineRequest(
                    request_id=f"fz{i}", prompt_token_ids=prompt,
                    max_tokens=rng.choice([1, 4, 9]), temperature=0.0,
                    ignore_eos=True)
                out = eng.submit(req)
                kill_after = rng.random() < 0.2
                got = 0
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=180)
                    if ev.token_id is not None:
                        got += 1
                        if kill_after and got == 1:
                            eng.abort(req.request_id)
                    if ev.finish_reason is not None:
                        key = ("aborted"
                               if ev.finish_reason == FinishReason.ABORT
                               else "finished")
                        outcomes[key] += 1
                        return

            # Three overlapping waves so admission sees bursts AND trickles.
            for wave in range(3):
                await asyncio.gather(*[one(wave * 20 + i) for i in range(20)])
            assert sum(outcomes.values()) == 60
            # Allocator fully drained (trash block excluded).
            free = getattr(eng.allocator, "reusable_blocks",
                           eng.allocator.free_blocks)
            assert free == eng.n_blocks - 1, (free, eng.n_blocks)
        finally:
            await eng.stop()
        assert outcomes["finished"] > 0

    run(body())


def test_sigterm_graceful_drain():
    """run_server's SIGTERM flow: readiness flips 503 immediately, the
    in-flight request still completes, then the server exits cleanly."""
    import os
    import signal

    from llm_d_inference_scheduler_tpu.engine.server import run_server

    async def body():
        cfg = _cfg("sim", 18341, sim_decode_ms_per_token=30.0)
        srv_task = asyncio.create_task(run_server(cfg, drain_timeout_s=20.0))
        async with httpx.AsyncClient(timeout=60) as c:
            for _ in range(100):  # wait for the listener
                if srv_task.done():
                    srv_task.result()  # surface the server's own exception
                    raise AssertionError("server exited before serving")
                try:
                    r = await c.get("http://127.0.0.1:18341/health")
                    if r.status_code == 200:
                        break
                except Exception:
                    pass  # httpx/httpcore connect errors while binding
                await asyncio.sleep(0.05)
            else:
                raise AssertionError("server never became healthy")

            # Long-ish request in flight, then SIGTERM mid-generation.
            gen = asyncio.create_task(c.post(
                "http://127.0.0.1:18341/v1/completions",
                json={"prompt": "hello", "max_tokens": 30}))
            await asyncio.sleep(0.2)
            os.kill(os.getpid(), signal.SIGTERM)
            await asyncio.sleep(0.3)
            r = await c.get("http://127.0.0.1:18341/health")
            assert r.status_code == 503
            assert r.json()["status"] == "draining"

            resp = await gen
            assert resp.status_code == 200
            assert resp.json()["usage"]["completion_tokens"] == 30
        await asyncio.wait_for(srv_task, timeout=30)

    run(body())


def test_chaos_shim_on_engine_surface():
    """The env/config-gated fault-injection shim (router/resilience.py via
    the EngineServer middleware): injected 503s carry the retryable
    x-removal-reason contract, decisions are deterministic per request id,
    and non-generate surfaces (health/metrics) are never chaos'd."""
    async def body():
        cfg = _cfg("sim", 18343, chaos="http503:50", chaos_seed=7)
        srv = EngineServer(cfg)
        await srv.start()
        try:
            async with httpx.AsyncClient(base_url="http://127.0.0.1:18343",
                                         timeout=30) as c:
                outcomes = {}
                for i in range(32):
                    r = await c.post("/v1/completions",
                                     json={"prompt": "x", "max_tokens": 1},
                                     headers={"x-request-id": f"det-{i}"})
                    outcomes[f"det-{i}"] = r.status_code
                assert set(outcomes.values()) == {200, 503}  # pct 50 splits
                for rid, status in outcomes.items():
                    r = await c.post("/v1/completions",
                                     json={"prompt": "x", "max_tokens": 1},
                                     headers={"x-request-id": rid})
                    assert r.status_code == status  # same id, same fate
                    if status == 503:
                        assert r.headers["x-removal-reason"] == "chaos-injected"
                # Control surfaces stay clean.
                assert (await c.get("/health")).status_code == 200
                assert (await c.get("/metrics")).status_code == 200
                # Runtime gate: disabling the injector heals everything.
                srv.chaos.enabled = False
                for rid in list(outcomes)[:8]:
                    r = await c.post("/v1/completions",
                                     json={"prompt": "x", "max_tokens": 1},
                                     headers={"x-request-id": rid})
                    assert r.status_code == 200
        finally:
            await srv.stop()

    run(body())


def test_drain_timeout_aborts_stragglers():
    """A request that cannot finish inside the drain window is actively
    aborted (ABORT event, not a hang into the SIGKILL window), and the
    server exits promptly."""
    import os
    import signal
    import time as _time

    from llm_d_inference_scheduler_tpu.engine.server import run_server

    async def body():
        # 200ms/token x 200 tokens >> the 1s drain window.
        cfg = _cfg("sim", 18342, sim_decode_ms_per_token=200.0)
        srv_task = asyncio.create_task(run_server(cfg, drain_timeout_s=1.0))
        async with httpx.AsyncClient(timeout=60) as c:
            for _ in range(100):
                if srv_task.done():
                    srv_task.result()
                    raise AssertionError("server exited before serving")
                try:
                    if (await c.get("http://127.0.0.1:18342/health")
                            ).status_code == 200:
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.05)
            gen = asyncio.create_task(c.post(
                "http://127.0.0.1:18342/v1/completions",
                json={"prompt": "hello", "max_tokens": 200}))
            await asyncio.sleep(0.3)
            t0 = _time.monotonic()
            os.kill(os.getpid(), signal.SIGTERM)
            resp = await gen  # aborted partial completion, not a hang
            assert resp.status_code == 200
            assert resp.json()["usage"]["completion_tokens"] < 200
            await asyncio.wait_for(srv_task, timeout=15)
            assert _time.monotonic() - t0 < 12  # 1s drain + bounded teardown

    run(body())


def test_drain_gate_waits_for_staged_kv_export():
    """SIGTERM drain must not tear down a prefill pod while a staged KV
    export is waiting for (or mid-way through) a decode peer's pull:
    idle() counts kv_exports and queued release requests (ADVICE r5)."""
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0, role="prefill"))
        await eng.start()
        try:
            assert eng.idle()
            req = EngineRequest(request_id="drain-exp",
                                prompt_token_ids=[1, 2, 3], max_tokens=1,
                                kv_transfer_params={"do_remote_decode": True})
            out = eng.submit(req)
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            assert ev.kv_transfer_params is not None
            assert "drain-exp" in eng.kv_exports
            # The request finished, but the staged export pins the drain
            # gate: a decode peer may still be mid-pull.
            assert not eng.idle()
            # Release (decode peer finished its pull) -> drain may proceed.
            eng.release_kv_export("drain-exp")
            for _ in range(100):
                if eng.idle():
                    break
                await asyncio.sleep(0.05)
            assert eng.idle()
        finally:
            await eng.stop()

    run(body())


def test_chunk_streamed_export_record_shape_and_drain_gate():
    """Pipelined P/D: a ``stream_chunks`` prefill stages its KV incrementally
    into the export record (chunk_blocks/chunks_staged/blocks_staged/complete
    state machine, chunk data aligned with the counters), and the SIGTERM
    drain gate pins the chunk-staged export exactly like a legacy one — a
    decode peer may still be mid-chunk-stream."""
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0, role="prefill", prefill_chunk=16))
        await eng.start()
        try:
            assert eng.idle()
            req = EngineRequest(
                request_id="chunk-exp",
                prompt_token_ids=list(range(3, 52)),  # 49 tokens, 4 blocks
                max_tokens=1,
                kv_transfer_params={"do_remote_decode": True,
                                    "stream_chunks": True})
            out = eng.submit(req)
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            assert ev.kv_transfer_params is not None
            rec = eng.kv_exports["chunk-exp"]
            # Record shape: counters and staged data agree, and the record
            # reads complete exactly once finalized.
            assert rec["complete"] is True
            assert rec["chunks_staged"] >= 2  # 16-token windows really chunked
            assert len(rec["chunk_blocks"]) == rec["chunks_staged"]
            assert len(rec["chunk_data"]) == rec["chunks_staged"]
            assert sum(rec["chunk_blocks"]) == rec["blocks_staged"]
            assert rec["blocks_staged"] == rec["num_blocks"]
            for (k_np, v_np), cb in zip(rec["chunk_data"],
                                        rec["chunk_blocks"]):
                assert k_np.shape[1] == cb and v_np.shape[1] == cb
            # Reassembled chunk bytes == the legacy full-payload serve.
            import numpy as np
            k_all = np.concatenate([k for k, _ in rec["chunk_data"]], axis=1)
            assert k_all.shape[1] == rec["num_blocks"]
            assert np.array_equal(k_all, np.asarray(rec["k"]))
            # Drain gate: the chunk-staged export pins idle() until released.
            assert not eng.idle()
            eng.release_kv_export("chunk-exp")
            for _ in range(100):
                if eng.idle():
                    break
                await asyncio.sleep(0.05)
            assert eng.idle()
        finally:
            await eng.stop()

    run(body())


def test_partial_chunk_export_dropped_on_abort():
    """A chunk-streamed prefill aborted mid-stream must not leave a
    partially-staged (complete=False) export behind: the decode peer's next
    poll 404s (it degrades to local prefill) and the drain gate is not
    pinned forever by a record no peer will ever release."""
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(_cfg("tpu", 0, role="prefill", prefill_chunk=16))
        await eng.start()
        try:
            req = EngineRequest(
                request_id="chunk-abort",
                prompt_token_ids=list(range(3, 120)),
                max_tokens=1,
                kv_transfer_params={"do_remote_decode": True,
                                    "stream_chunks": True})
            out = eng.submit(req)
            # Abort while the prefill windows are still being written.
            eng.abort("chunk-abort")
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=30)
                if ev.finish_reason is not None:
                    break
            for _ in range(100):
                if eng.idle():
                    break
                await asyncio.sleep(0.05)
            # Whatever was staged before the abort is gone (incomplete
            # records are dropped; a COMPLETE export would be kept).
            rec = eng.kv_exports.get("chunk-abort")
            assert rec is None or rec.get("complete", True)
            assert eng.idle()
        finally:
            await eng.stop()

    run(body())
