"""Traffic forecaster & capacity observatory (ISSUE 16,
router/forecast.py).

Hermetic tiers: pure units (config, the damped-HW model's skill vs
persistence, gap discipline across sampler stalls and missing series,
restart resume via prime(), capacity projection, merge_forecast
n-weighting), the rebalancer's forecast-qualified advice + transition
counter, the /debug/timeline ?series/?step_s satellite, one real gateway
driving /debug/forecast + the kill-switch contract + the incident
forecast embed, and the FleetAdmin fan-in against stub workers."""

import asyncio
import math
import os
import random
import sys

import httpx
import pytest
from aiohttp import web

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from llm_d_inference_scheduler_tpu.router.forecast import (
    ForecastConfig,
    ForecastEngine,
    merge_forecast,
)
from llm_d_inference_scheduler_tpu.router.metrics import REGISTRY
from llm_d_inference_scheduler_tpu.router.timeline import (
    RULE_DRAIN_COLLAPSE,
    TimelineConfig,
    TimelineSampler,
    merge_timeline,
)

GW_A, GW_B = 19270, 19271
STUB_A, STUB_B, STUB_ADMIN = 19272, 19273, 19274


def run(coro):
    return asyncio.run(coro)


def _engine(spec=None, *, tick_s=1.0) -> ForecastEngine:
    return ForecastEngine(ForecastConfig.from_spec(spec), tick_s=tick_s)


def _sample(t, **series):
    return {"t_unix": t, **series}


# ---- config -------------------------------------------------------------

class TestConfig:
    def test_defaults(self):
        cfg = ForecastConfig.from_spec(None)
        assert cfg.enabled is True
        assert cfg.horizons_s == (30.0, 120.0, 600.0)
        assert cfg.seasonal_period_s == 3600.0
        assert cfg.intervals == 0.9
        assert 0 < cfg.damping <= 1.0

    def test_spec_roundtrip(self):
        cfg = ForecastConfig.from_spec({
            "enabled": True, "horizons": [60, 15], "seasonalPeriodS": 120,
            "intervals": 0.8, "alpha": 0.5, "damping": 0.95,
            "warmupTicks": 10, "errorWindow": 64})
        assert cfg.horizons_s == (15.0, 60.0)  # sorted
        assert cfg.seasonal_period_s == 120.0
        assert cfg.intervals == 0.8
        assert cfg.warmup_ticks == 10 and cfg.error_window == 64

    def test_validation(self):
        with pytest.raises(ValueError):
            ForecastConfig.from_spec({"horizons": []})
        with pytest.raises(ValueError):
            ForecastConfig.from_spec({"horizons": [0]})
        with pytest.raises(ValueError):
            ForecastConfig.from_spec({"intervals": 1.5})
        with pytest.raises(ValueError):
            ForecastConfig.from_spec({"alpha": 0})
        with pytest.raises(ValueError):
            ForecastConfig.from_spec({"damping": 1.5})
        with pytest.raises(ValueError):
            ForecastConfig.from_spec({"seasonalPeriodS": -1})


# ---- the model: judged skill vs persistence -----------------------------

class TestModel:
    def test_skill_beats_persistence_on_seasonal_traffic(self):
        """The acceptance shape: on a noisy seasonal signal the judged
        MAE beats the naive last-value baseline by >= 20% at the lead
        horizon, interval coverage lands in [0.75, 0.99], and every
        elapsed forecast is judged (join coverage 1.0)."""
        eng = _engine({"horizons": [5, 15], "seasonalPeriodS": 60},
                      tick_s=0.25)
        rng = random.Random(7)
        for i in range(2400):
            t = 1_000_000.0 + i * 0.25
            y = 40 + 25 * math.sin(2 * math.pi * t / 60) + rng.gauss(0, 3)
            eng.observe(_sample(t, requests=y * 0.25))
        snap = eng.snapshot()
        assert snap["join_coverage"] == 1.0
        errors = snap["series"]["arrival_rate"]["errors"]
        lead = errors["5"]
        assert lead["skill"] is not None and lead["skill"] >= 0.2
        for cell in errors.values():
            assert 0.75 <= cell["coverage"] <= 0.99
        # The naive baseline is genuinely present, not zeroed.
        assert lead["naive_mae"] > 0

    def test_forecast_rows_and_pending(self):
        eng = _engine({"horizons": [3], "seasonalPeriodS": 0,
                       "warmupTicks": 2})
        row = None
        for i in range(6):
            row = eng.observe(_sample(100.0 + i, requests=5.0))
        assert row is not None and "stamps" in row and "joins" in row
        snap = eng.snapshot()
        s = snap["series"]["arrival_rate"]
        assert s["n_obs"] == 6
        assert s["pending"] >= 1
        fc = s["forecast"]["3"]
        assert fc["lo"] <= fc["yhat"] <= fc["hi"]

    def test_warmup_gates_stamping(self):
        eng = _engine({"horizons": [2], "warmupTicks": 5})
        for i in range(4):
            eng.observe(_sample(100.0 + i, requests=1.0))
        assert eng.stamps_total == 0
        eng.observe(_sample(104.0, requests=1.0))
        # warmup reached: stamping may begin (on the decimated grid).
        for i in range(5, 10):
            eng.observe(_sample(100.0 + i, requests=1.0))
        assert eng.stamps_total > 0

    def test_killswitch_is_inert(self):
        eng = _engine({"enabled": False})
        assert eng.observe(_sample(100.0, requests=5.0)) is None
        assert eng.stamps_total == 0 and eng.ticks == 0
        snap = eng.snapshot()
        assert snap["enabled"] is False and snap["series"] == {}
        assert eng.role_projection("prefill") is None


# ---- gap discipline -----------------------------------------------------

class TestGaps:
    def test_sampler_stall_drops_pending_never_interpolates(self):
        """A forecast whose target bucket the sampler never produced is
        dropped and counted — it must NOT be judged against whatever
        sample comes next."""
        eng = _engine({"horizons": [3], "seasonalPeriodS": 0,
                       "warmupTicks": 2})
        for i in range(4):
            eng.observe(_sample(100.0 + i, requests=2.0))
        assert eng.stamps_total > 0 and eng.joins_total >= 0
        before_joins = eng.joins_total
        # Jump the wall clock far past every pending target bucket.
        eng.observe(_sample(200.0, requests=2.0))
        assert eng.gap_skips_total > 0
        assert eng.joins_total == before_joins
        snap = eng.snapshot()
        assert snap["join_coverage"] < 1.0
        # Every surviving pending row targets a post-jump bucket — the
        # pre-jump forecasts are gone, not waiting to mis-join.
        assert all(b > int(round(200.0 / 1.0)) for b in eng._pending)

    def test_missing_series_is_a_gap_at_the_join(self):
        """A series absent from the sample its forecast targeted is a
        gap for that series — the join is skipped, not filled from a
        neighbour."""
        eng = _engine({"horizons": [3], "seasonalPeriodS": 0,
                       "warmupTicks": 2})
        for i in range(5):
            eng.observe(_sample(100.0 + i, requests=2.0, inflight=4.0))
        pend = {b: list(rows) for b, rows in eng._pending.items()}
        assert pend, "expected pending forecasts"
        target = min(pend)
        # Walk to the target bucket, but drop `requests` from exactly
        # that sample (inflight stays, so the tick itself is not a gap).
        t = 100.0 + 5
        while int(round(t / 1.0)) < target:
            eng.observe(_sample(t, requests=2.0, inflight=4.0))
            t += 1.0
        joins_before = eng.joins_total
        gaps_before = eng.gap_skips_total
        eng.observe(_sample(t, inflight=4.0))
        assert eng.gap_skips_total > gaps_before
        # inflight's forecast (same bucket) still joined.
        assert eng.joins_total > joins_before
        assert eng._series["arrival_rate"].missing == 1

    def test_gap_row_lands_in_sample(self):
        eng = _engine({"horizons": [2], "seasonalPeriodS": 0,
                       "warmupTicks": 2})
        for i in range(4):
            eng.observe(_sample(100.0 + i, requests=1.0))
        row = eng.observe(_sample(150.0, requests=1.0))
        assert row["gap_skips"] > 0


# ---- restart resume -----------------------------------------------------

class TestRestartResume:
    def test_prime_resumes_from_ring_state(self):
        """A restarted worker rebuilds its engine and replays the live
        timeline ring: the model resumes from live state (level/trend
        learned) but nothing is stamped or judged for the dead process's
        forecasts."""
        history = [_sample(1000.0 + i, requests=10.0 + i * 0.5)
                   for i in range(60)]
        fresh = _engine({"horizons": [5], "seasonalPeriodS": 0})
        consumed = fresh.prime(history)
        assert consumed == 60
        assert fresh.stamps_total == 0 and fresh.joins_total == 0
        assert fresh.ticks == 0
        st = fresh._series["arrival_rate"]
        assert st.n_obs == 60
        # Level tracked the ramp — a cold engine would sit at 0.
        assert st.level > 30.0
        assert st.trend > 0.0
        # The next LIVE tick stamps immediately (warmup already served).
        for i in range(60, 70):
            fresh.observe(_sample(1000.0 + i, requests=10.0 + i * 0.5))
        assert fresh.stamps_total > 0

    def test_prime_disabled_engine_is_noop(self):
        eng = _engine({"enabled": False})
        assert eng.prime([_sample(1.0, requests=1.0)]) == 0


# ---- capacity observatory -----------------------------------------------

class TestCapacity:
    def _drive_headroom(self, eng, slope, n=30, start=0.9):
        for i in range(n):
            eng.observe(_sample(
                2000.0 + i, requests=1.0,
                rebalance={"headroom": {"prefill": start + slope * i,
                                        "decode": 0.8}}))

    def test_declining_headroom_projects_saturation(self):
        eng = _engine({"horizons": [5], "seasonalPeriodS": 0})
        self._drive_headroom(eng, slope=-0.01)
        proj = eng.role_projection("prefill")
        assert proj is not None
        tts = proj["time_to_saturation_s"]
        assert tts is not None and 10.0 < tts < 200.0
        assert proj["trend_per_s"] < 0
        # The healthy role projects no saturation.
        assert eng.role_projection("decode")["time_to_saturation_s"] is None
        snap = eng.snapshot()
        assert snap["capacity"]["prefill"]["time_to_saturation_s"] == tts
        # Gauge exported (snapshot refreshes the metric families).
        g = REGISTRY.get_sample_value("router_time_to_saturation_seconds",
                                      {"role": "prefill"})
        assert g is not None and g == pytest.approx(tts, rel=0.01)

    def test_exhausted_headroom_projects_zero(self):
        eng = _engine({"horizons": [5], "seasonalPeriodS": 0})
        self._drive_headroom(eng, slope=-0.05, n=25, start=0.9)
        proj = eng.role_projection("prefill")
        assert proj["time_to_saturation_s"] == 0.0


# ---- forecast-qualified advice + transition counter ---------------------

class _FakeForecast:
    def role_projection(self, role):
        return {"time_to_saturation_s": 42.0, "headroom_now": 0.2,
                "headroom_level": 0.21, "trend_per_s": -0.005,
                "basis": "headroom level+trend zero-crossing"}


class TestAdviceQualification:
    def _pool(self, ds, spec):
        from llm_d_inference_scheduler_tpu.router.framework.datalayer \
            import ROLE_LABEL, EndpointMetadata
        for addr, role in spec.items():
            host, _, port = addr.rpartition(":")
            ds.endpoint_add_or_update(EndpointMetadata(
                name=addr, address=host, port=int(port),
                labels={ROLE_LABEL: role}))

    def _controller(self, ds):
        from llm_d_inference_scheduler_tpu.router.rebalance import (
            RebalanceConfig,
            RebalanceController,
        )
        cfg = RebalanceConfig(enabled=True)
        return RebalanceController(cfg, datastore=ds, clock=lambda: 50.0,
                                   wall=lambda: 1e9)

    def test_advice_rows_gain_lead_and_forecast(self):
        from llm_d_inference_scheduler_tpu.router.datalayer.datastore \
            import Datastore

        ds = Datastore()
        self._pool(ds, {"10.0.0.1:8000": "prefill",
                        "10.0.0.2:8000": "decode"})
        c = self._controller(ds)
        c.forecast = _FakeForecast()
        c.tick()
        advice = c.snapshot()["advice"]
        for role in ("prefill", "decode"):
            assert advice[role]["lead_s"] == 42.0
            assert advice[role]["forecast"]["trend_per_s"] == -0.005

    def test_transition_counter_counts_changes_only(self):
        from llm_d_inference_scheduler_tpu.router.datalayer.datastore \
            import Datastore

        def changes(direction):
            return REGISTRY.get_sample_value(
                "router_pool_advice_changes_total",
                {"role": "prefill", "direction": direction}) or 0.0

        ds = Datastore()
        # Two prefill pods idling against a healthy decode pool → down.
        self._pool(ds, {"10.0.0.1:8000": "prefill",
                        "10.0.0.2:8000": "prefill",
                        "10.0.0.3:8000": "decode",
                        "10.0.0.4:8000": "decode"})
        c = self._controller(ds)
        base_down = changes("down")
        base_up = changes("up")
        c.tick()
        # First verdict is a state, not a change.
        assert changes("down") == base_down
        c.tick()
        c.tick()
        # Sustained identical advice never increments.
        assert changes("down") == base_down
        # Starve prefill: both pools loaded → up; the transition counts.
        ep = ds.endpoint_get("10.0.0.1:8000")
        ep.metrics.waiting_queue_size = 80
        ep2 = ds.endpoint_get("10.0.0.3:8000")
        ep2.metrics.waiting_queue_size = 80
        ep3 = ds.endpoint_get("10.0.0.4:8000")
        ep3.metrics.waiting_queue_size = 80
        c.tick()
        new_dir = c.snapshot()["advice"]["prefill"]["direction"]
        assert new_dir != "down"
        assert (changes(new_dir) - (base_up if new_dir == "up"
                                    else 0.0)) >= 1.0


# ---- /debug/timeline ?series + ?step_s ----------------------------------

class TestTimelineSelection:
    def _sampler(self, tick_s=1.0):
        return TimelineSampler(
            TimelineConfig.from_spec({"tickS": tick_s}),
            inflight_fn=lambda: 3)

    def test_series_selection_filters_samples(self):
        s = self._sampler()
        for i in range(5):
            s.tick(wall=100.0 + i)
        doc = s.snapshot(series=["inflight"])
        assert doc["series"] == ["inflight"]
        for row in doc["samples"]:
            assert set(row) <= {"t_unix", "inflight"}
        # Unselected series also vanish from the aggregates.
        assert set(doc["aggregates"]) <= {"inflight"}

    def test_step_downsampling_is_gap_aware(self):
        s = self._sampler()
        for i in range(10):
            s.tick(wall=100.0 + i)
        # A stall: nothing lands in [110, 120).
        for i in range(10):
            s.tick(wall=120.0 + i)
        doc = s.snapshot(step_s=5.0, series=["inflight"])
        assert doc["step_s"] == 5.0
        times = [r["t_unix"] for r in doc["samples"]]
        # Buckets 110 and 115 never appear — a gap is absent, not
        # interpolated.
        assert 110.0 not in times and 115.0 not in times
        for row in doc["samples"]:
            assert row["n"] == 5
            assert row["inflight"] == 3.0

    def test_step_not_finer_than_tick(self):
        s = self._sampler()
        for i in range(4):
            s.tick(wall=100.0 + i)
        doc = s.snapshot(step_s=0.5)
        assert "step_s" not in doc  # ignored: finer than the tick grid
        assert len(doc["samples"]) == 4

    def test_merge_honors_downsampled_step(self):
        d0 = {"enabled": True, "tick_s": 1.0, "step_s": 5.0,
              "samples": [{"t_unix": 100.0, "n": 5, "inflight": 1.0},
                          {"t_unix": 105.0, "n": 5, "inflight": 2.0}]}
        d1 = {"enabled": True, "tick_s": 1.0, "step_s": 5.0,
              "samples": [{"t_unix": 100.0, "n": 5, "inflight": 3.0}]}
        out = merge_timeline([(0, d0), (1, d1)], workers=2)
        assert out["step_s"] == 5.0
        by_t = {r["t_unix"]: r for r in out["buckets"]}
        # Step-aligned buckets: 100 and 105, NOT one bucket per tick.
        assert set(by_t) == {100.0, 105.0}
        assert by_t[105.0]["gaps"] == [1]


# ---- merge_forecast -----------------------------------------------------

class TestMergeForecast:
    def test_n_weighted_mae_and_recomputed_skill(self):
        d0 = {"enabled": True, "tick_s": 1.0, "horizons_s": [30.0],
              "ticks": 50, "stamps_total": 10, "joins_total": 4,
              "gap_skips_total": 0, "join_coverage": 1.0,
              "series": {"arrival_rate": {"errors": {"30": {
                  "n": 4, "mae": 2.0, "naive_mae": 4.0, "coverage": 1.0}}}},
              "capacity": {"prefill": {"time_to_saturation_s": 90.0}}}
        d1 = {"enabled": True, "tick_s": 1.0, "horizons_s": [30.0],
              "ticks": 50, "stamps_total": 20, "joins_total": 12,
              "gap_skips_total": 4, "join_coverage": 0.75,
              "series": {"arrival_rate": {"errors": {"30": {
                  "n": 12, "mae": 6.0, "naive_mae": 4.0,
                  "coverage": 0.5}}}}}
        out = merge_forecast([(0, d0), (1, d1)])
        cell = out["series"]["arrival_rate"]["30"]
        # 4 joins at MAE 2 + 12 joins at MAE 6 → (8+72)/16 = 5.0; the
        # heavy shard moves the fleet MAE 3x more than the light one.
        assert cell["n"] == 16
        assert cell["mae"] == pytest.approx(5.0)
        assert cell["skill"] == pytest.approx(1.0 - 5.0 / 4.0)
        assert cell["coverage"] == pytest.approx((4 * 1.0 + 12 * 0.5) / 16)
        # Fleet join coverage from the summed counts.
        assert out["join_coverage"] == pytest.approx(16 / 20)
        assert out["capacity_shard"] == 0
        assert out["shards"]["1"]["gap_skips_total"] == 4

    def test_disabled_shards_merge_empty(self):
        out = merge_forecast([(0, {"enabled": False}),
                              (1, {"enabled": False})])
        assert out["enabled"] is False and out["series"] == {}


# ---- incident embed -----------------------------------------------------

class TestIncidentEmbed:
    def test_incident_carries_forecast_state(self):
        class _Flow:
            queued_requests = 0

            def queued_by_band(self):
                return {"standard": self.queued_requests}

        flow = _Flow()
        eng = _engine({"horizons": [3], "seasonalPeriodS": 0,
                       "warmupTicks": 2})
        cfg = TimelineConfig.from_spec(
            {"rules": {"drainMinRps": 5.0}})
        s = TimelineSampler(cfg, flow=flow,
                            drain_rate_fn=lambda: 0.1,
                            forecast=eng)
        # Quiet warm-up ticks so stamped forecasts exist when it trips.
        for i in range(6):
            s.tick(wall=300.0 + i)
        flow.queued_requests = 7
        s.tick(wall=306.0)
        incidents = s.incidents.snapshot()["incidents"]
        assert incidents and incidents[0]["rule"] == RULE_DRAIN_COLLAPSE
        fc = incidents[0]["forecast"]
        assert fc["enabled"] is True
        assert "queued" in fc["series"]
        # The per-tick forecast row rides the trigger sample too.
        assert "forecast" in incidents[0]["trigger"]


# ---- gateway e2e --------------------------------------------------------

GW_CFG = """
pool:
  endpoints: []
rebalance:
  enabled: true
forecast:
  horizons: [5, 15]
  seasonalPeriodS: 60
  warmupTicks: 3
timeline:
  tickS: 1.0
"""

KILL_CFG = """
pool:
  endpoints: []
forecast:
  enabled: false
"""


class TestGatewayE2E:
    def test_debug_forecast_and_wiring(self):
        from llm_d_inference_scheduler_tpu.router.gateway import (
            build_gateway,
        )

        async def body():
            gw = build_gateway(GW_CFG, port=GW_A, poll_interval=60.0)
            await gw.start()
            try:
                assert gw.timeline.forecast is gw.forecaster
                assert gw.rebalancer.forecast is gw.forecaster
                for i in range(30):
                    gw.timeline.tick(wall=1_000_000.0 + i)
                async with httpx.AsyncClient(timeout=10) as c:
                    base = f"http://127.0.0.1:{GW_A}"
                    doc = (await c.get(base + "/debug/forecast")).json()
                    assert doc["enabled"] is True
                    assert doc["horizons_s"] == [5.0, 15.0]
                    assert doc["ticks"] == 30
                    assert doc["stamps_total"] > 0
                    assert "arrival_rate" in doc["series"]
                    # ?joins=N inlines recent judged rows per cell.
                    doc2 = (await c.get(
                        base + "/debug/forecast?joins=4")).json()
                    s = doc2["series"]["arrival_rate"]
                    assert "joins" in s
                    # Timeline rows carry the per-tick forecast row.
                    tl = (await c.get(
                        base + "/debug/timeline?series=forecast,inflight"
                               "&step_s=5")).json()
                    assert tl["step_s"] == 5.0
                    assert tl["samples"], "expected downsampled buckets"
            finally:
                await gw.stop()

        run(body())

    def test_killswitch_zero_stamps(self):
        from llm_d_inference_scheduler_tpu.router.gateway import (
            build_gateway,
        )

        async def body():
            gw = build_gateway(KILL_CFG, port=GW_B, poll_interval=60.0)
            await gw.start()
            try:
                assert gw.timeline.forecast is None
                sample = gw.timeline.tick(wall=1_000_000.0)
                assert "forecast" not in sample
                assert gw.forecaster.stamps_total == 0
                async with httpx.AsyncClient(timeout=10) as c:
                    doc = (await c.get(
                        f"http://127.0.0.1:{GW_B}/debug/forecast")).json()
                    assert doc["enabled"] is False
                    assert doc["stamps_total"] == 0
                    assert doc["series"] == {}
            finally:
                await gw.stop()

        run(body())


# ---- fleet fan-in e2e ---------------------------------------------------

def _stub(port, doc):
    app = web.Application()

    async def forecast(request):
        return web.json_response(doc)

    app.add_routes([web.get("/debug/forecast", forecast)])
    return app, port


def test_fleet_admin_forecast_fan_in():
    from llm_d_inference_scheduler_tpu.router.fleet import FleetAdmin

    async def body():
        docs = [
            {"enabled": True, "tick_s": 1.0, "horizons_s": [30.0],
             "ticks": 50, "stamps_total": 10, "joins_total": 8,
             "gap_skips_total": 0, "join_coverage": 1.0,
             "series": {"arrival_rate": {"errors": {"30": {
                 "n": 8, "mae": 1.0, "naive_mae": 2.0, "coverage": 0.9}}}},
             "capacity": {"decode": {"time_to_saturation_s": 55.0}}},
            {"enabled": True, "tick_s": 1.0, "horizons_s": [30.0],
             "ticks": 50, "stamps_total": 30, "joins_total": 24,
             "gap_skips_total": 6, "join_coverage": 0.8,
             "series": {"arrival_rate": {"errors": {"30": {
                 "n": 24, "mae": 3.0, "naive_mae": 2.0,
                 "coverage": 0.7}}}}},
        ]
        runners = []
        for (app, port), d in zip(
                (_stub(STUB_A, docs[0]), _stub(STUB_B, docs[1])), docs):
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "127.0.0.1", port).start()
            runners.append(runner)
        admin = FleetAdmin([("127.0.0.1", STUB_A), ("127.0.0.1", STUB_B)],
                           host="127.0.0.1", port=STUB_ADMIN)
        await admin.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                out = (await c.get(
                    f"http://127.0.0.1:{STUB_ADMIN}/debug/forecast")).json()
                assert out["workers"] == 2
                assert out["responding"] == [0, 1]
                cell = out["series"]["arrival_rate"]["30"]
                # n-weighted: (8*1 + 24*3) / 32 = 2.5.
                assert cell["n"] == 32
                assert cell["mae"] == pytest.approx(2.5)
                assert cell["skill"] == pytest.approx(1.0 - 2.5 / 2.0)
                assert out["capacity_shard"] == 0
                assert out["join_coverage"] == pytest.approx(32 / 38,
                                                             abs=1e-3)
        finally:
            await admin.stop()
            for runner in runners:
                await runner.cleanup()

    run(body())
