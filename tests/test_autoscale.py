"""Guarded elastic-fleet actuator (router/autoscale.py): config parsing,
the kill-switch, the preflight pipeline (sustain, lead, bounds, breaker,
budget, dwell — the advice-flap hysteresis), the spawn/retire state
machines with their watchdogs, rollback-on-incident + freeze, post-hoc
outcome judging, the worker dimension, the fleet fan-in + /fleet/scale
surface, the supervisor retiring state machine (scale-in is not an
outage), and the lifecycle chaos kinds feeding the drills.
"""

import asyncio

import httpx
import pytest
from aiohttp import web

from llm_d_inference_scheduler_tpu.router.autoscale import (
    ABORTED,
    COMPLETED,
    REFUSED,
    RETIRE_POD,
    RETIRE_WORKER,
    ROLLED_BACK,
    SPAWN_POD,
    ActuatorController,
    AutoscaleConfig,
    SpawnHandle,
    merge_autoscale,
)
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    DRAINING_LABEL,
    ROLE_LABEL,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.resilience import FaultInjector


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _pool(ds: Datastore, spec: dict[str, str]) -> None:
    for addr, role in spec.items():
        host, _, port = addr.rpartition(":")
        ds.endpoint_add_or_update(EndpointMetadata(
            name=addr, address=host, port=int(port),
            labels={ROLE_LABEL: role}))


def _scrape(ds: Datastore, addr: str, *, at: float, waiting: int = 0,
            running: int = 0) -> None:
    ep = ds.endpoint_get(addr)
    ep.metrics.update_time = at
    ep.metrics.waiting_queue_size = waiting
    ep.metrics.running_requests_size = running


class StubLauncher:
    """Registers the spawned pod DRAINING (the launcher contract) and
    deletes on retire; ``fail`` makes spawn return a failed handle."""

    def __init__(self, ds: Datastore, *, fail: bool = False):
        self.ds = ds
        self.fail = fail
        self.spawned: list[str] = []
        self.retired: list[str] = []
        self._next = 50

    def spawn(self, role: str) -> SpawnHandle:
        h = SpawnHandle()
        if self.fail:
            h.state = "failed"
            h.error = "chaos spawn_fail"
            return h
        addr = f"10.0.0.{self._next}:8000"
        self._next += 1
        self.ds.endpoint_add_or_update(EndpointMetadata(
            name=addr, address=addr.rsplit(":", 1)[0], port=8000,
            labels={ROLE_LABEL: role, DRAINING_LABEL: "true"}))
        h.state = "ok"
        h.address_port = addr
        self.spawned.append(addr)
        return h

    def retire(self, address_port: str) -> None:
        self.retired.append(address_port)
        self.ds.endpoint_delete(address_port)


class StubScaler:
    def __init__(self, active: int = 3, provisioned: int = 3, *,
                 refuse: bool = False):
        self.active = active
        self.provisioned = provisioned
        self.refuse = refuse
        self.calls: list[str] = []

    def counts(self):
        return self.active, self.provisioned

    def retire(self):
        self.calls.append("retire")
        if self.refuse or self.active <= 1:
            return None
        self.active -= 1
        return str(self.active)

    def restore(self):
        self.calls.append("restore")
        if self.refuse or self.active >= self.provisioned:
            return None
        self.active += 1
        return str(self.active - 1)


def _ctrl(ds, clock, *, launcher=None, scaler=None, burn=None, att=None,
          **over):
    cfg = AutoscaleConfig(
        enabled=True, tick_s=1.0, sustain_ticks=2, require_lead=True,
        max_actions_per_window=4, window_s=300.0, dwell_s=60.0,
        observation_window_s=30.0, spawn_timeout_s=10.0,
        drain_timeout_s=10.0, max_pods_per_role=8)
    for k, v in over.items():
        setattr(cfg, k, v)
    advice: dict = {}
    c = ActuatorController(
        cfg, datastore=ds, advice_fn=lambda: advice, launcher=launcher,
        worker_scaler=scaler, burn_fn=burn, attainment_fn=att,
        clock=clock, wall=lambda: clock.t)
    return c, advice


def _up(lead=60.0, headroom=-0.1):
    return {"direction": "up", "why": "headroom below target",
            "headroom": headroom, "lead_s": lead}


def _down(headroom=0.8):
    return {"direction": "down", "why": "surplus headroom",
            "headroom": headroom}


class TestConfig:
    def test_defaults_off(self):
        cfg = AutoscaleConfig.from_spec(None)
        assert cfg.enabled is False
        assert cfg.sustain_ticks == 3
        assert cfg.require_lead is True
        assert cfg.pods_per_worker == 0

    def test_spec_roundtrip(self):
        cfg = AutoscaleConfig.from_spec({
            "enabled": True, "tickS": 0.5, "sustainTicks": 5,
            "requireLead": False, "maxActionsPerWindow": 2,
            "windowS": 120, "dwellS": 30, "observationWindowS": 15,
            "rollbackAttainment": 0.7, "spawnTimeoutS": 12,
            "drainTimeoutS": 8, "minPodsPerRole": 2, "maxPodsPerRole": 6,
            "podsPerWorker": 4, "minWorkers": 2})
        assert (cfg.tick_s, cfg.sustain_ticks) == (0.5, 5)
        assert cfg.require_lead is False
        assert (cfg.max_actions_per_window, cfg.window_s) == (2, 120.0)
        assert (cfg.min_pods_per_role, cfg.max_pods_per_role) == (2, 6)
        assert (cfg.pods_per_worker, cfg.min_workers) == (4, 2)

    def test_validation(self):
        with pytest.raises(ValueError):
            AutoscaleConfig.from_spec({"tickS": 0})
        with pytest.raises(ValueError):
            AutoscaleConfig.from_spec({"windowS": -1})
        with pytest.raises(ValueError):
            AutoscaleConfig.from_spec({"minPodsPerRole": 4,
                                       "maxPodsPerRole": 2})
        with pytest.raises(ValueError):
            AutoscaleConfig.from_spec({"rollbackAttainment": 1.5})


class TestKillSwitch:
    def test_disabled_is_bit_identical(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        c = ActuatorController(AutoscaleConfig(enabled=False),
                               datastore=ds, clock=clock,
                               wall=lambda: clock.t)
        for _ in range(10):
            c.tick()
            clock.advance(1.0)
        assert c.ticks_total == 0
        assert c.actions_total == 0
        assert c.snapshot()["records"] == []

    def test_non_acting_follower_is_inert(self):
        ds = Datastore()
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds))
        c.acting = False
        advice["decode"] = _up()
        for _ in range(5):
            c.tick()
            clock.advance(1.0)
        assert c.ticks_total == 0 and c.actions_total == 0


class TestHysteresis:
    """Satellite: flapping advice produces ZERO actions; sustained advice
    with positive lead produces EXACTLY ONE."""

    def test_flapping_advice_zero_actions(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode",
                   "10.0.0.3:8000": "prefill"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds),
                          sustain_ticks=3)
        for i in range(30):     # oscillate every tick: up, down, up, ...
            advice["decode"] = _up() if i % 2 == 0 else _down()
            c.tick()
            clock.advance(1.0)
        assert c.actions_total == 0
        assert c.refusals_total > 0
        kinds = {r["state"] for r in c.snapshot()["records"]}
        assert kinds == {REFUSED}

    def test_sustained_advice_exactly_one_action(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds),
                          sustain_ticks=3)
        advice["decode"] = _up(lead=45.0)
        for _ in range(6):
            c.tick()
            clock.advance(1.0)
        # One spawn started (then the controller serializes on it).
        assert c.actions_total == 1
        pending = c.snapshot()["pending"]
        assert pending["kind"] == SPAWN_POD and pending["role"] == "decode"
        assert pending["inputs"]["lead_s"] == 45.0

    def test_refusal_dedup_bumps_count(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds),
                          sustain_ticks=100)
        advice["decode"] = _up()
        for _ in range(7):
            c.tick()
            clock.advance(1.0)
        recs = [r for r in c.snapshot()["records"]
                if r["state"] == REFUSED]
        assert len(recs) == 1           # deduped, not one per tick
        assert recs[0]["count"] == 7
        assert c.refusals_total == 7


class TestPreflight:
    def test_scale_up_requires_positive_lead(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds))
        advice["decode"] = {"direction": "up", "why": "w",
                            "headroom": -0.1, "lead_s": None}
        for _ in range(4):
            c.tick()
            clock.advance(1.0)
        assert c.actions_total == 0
        rec = c.snapshot()["records"][0]
        assert "lead" in rec["why"]
        # requireLead: false acts on sustain alone.
        c2, advice2 = _ctrl(ds, clock, launcher=StubLauncher(ds),
                            require_lead=False)
        advice2["decode"] = {"direction": "up", "why": "w",
                             "headroom": -0.1, "lead_s": None}
        for _ in range(3):
            c2.tick()
            clock.advance(1.0)
        assert c2.actions_total == 1

    def test_never_retire_last_pod(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds))
        advice["decode"] = _down()
        for _ in range(5):
            c.tick()
            clock.advance(1.0)
        assert c.actions_total == 0
        assert "last pod" in c.snapshot()["records"][0]["why"]

    def test_max_pods_bound(self):
        ds = Datastore()
        _pool(ds, {f"10.0.0.{i}:8000": "decode" for i in range(1, 4)})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds),
                          max_pods_per_role=3)
        advice["decode"] = _up()
        for _ in range(5):
            c.tick()
            clock.advance(1.0)
        assert c.actions_total == 0
        assert "maxPodsPerRole" in c.snapshot()["records"][0]["why"]

    def test_dry_run_without_launcher(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=None)
        advice["decode"] = _up()
        for _ in range(4):
            c.tick()
            clock.advance(1.0)
        assert c.actions_total == 0
        assert "dry-run" in c.snapshot()["records"][0]["why"]

    def test_budget_and_dwell(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode",
                   "10.0.0.3:8000": "decode", "10.0.0.4:8000": "prefill"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1,
                          require_lead=False, max_actions_per_window=1,
                          window_s=100.0, dwell_s=200.0)
        advice["decode"] = _down()
        c.tick()
        assert c.actions_total == 1          # retire started
        addr = c.snapshot()["pending"]["target"]
        clock.advance(1.0)
        _scrape(ds, addr, at=clock.t)        # drained -> completes
        advice["decode"] = {"direction": "hold", "why": "ok"}
        c.tick()
        assert launcher.retired == [addr]
        # Budget: a second action inside the window refuses.
        advice["decode"] = _up()
        clock.advance(1.0)
        c.tick()
        c.tick()
        assert c.actions_total == 1
        assert "budget exhausted" in c.snapshot()["records"][0]["why"]
        # Window expires but the OPPOSING action still sits out dwellS.
        clock.advance(150.0)
        c.tick()
        assert c.actions_total == 1
        assert "dwell" in c.snapshot()["records"][0]["why"]
        # Past the dwell it acts.
        clock.advance(60.0)
        c.tick()
        assert c.actions_total == 2
        assert c.snapshot()["pending"]["kind"] == SPAWN_POD


class TestSpawnStateMachine:
    def test_spawn_completes_after_first_scrape(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1)
        advice["decode"] = _up()
        c.tick()
        addr = launcher.spawned[0]
        ep = ds.endpoint_get(addr)
        assert ep.metadata.labels.get(DRAINING_LABEL) == "true"
        # No scrape yet: stays pending (not pick-eligible).
        clock.advance(1.0)
        c.tick()
        assert c.snapshot()["pending"]["kind"] == SPAWN_POD
        # First scrape lands: draining clears, action completes.
        _scrape(ds, addr, at=clock.t)
        advice["decode"] = {"direction": "hold", "why": "ok"}
        clock.advance(1.0)
        c.tick()
        doc = c.snapshot()
        assert "pending" not in doc
        ep = ds.endpoint_get(addr)
        assert DRAINING_LABEL not in (ep.metadata.labels or {})
        rec = doc["records"][0]
        assert (rec["state"], rec["target"]) == (COMPLETED, addr)

    def test_spawn_failure_aborts_and_opens_breaker(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        c, advice = _ctrl(ds, clock, launcher=StubLauncher(ds, fail=True),
                          sustain_ticks=1, breaker_failure_threshold=2)
        advice["decode"] = _up()
        c.tick()                              # spawn #1 starts
        clock.advance(1.0)
        c.tick()                              # abort #1, spawn #2 starts
        aborted = [r for r in c.snapshot()["records"]
                   if r["state"] == ABORTED]
        assert len(aborted) == 1
        assert "spawn failed" in aborted[0]["why"]
        clock.advance(1.0)
        c.tick()                              # abort #2 -> breaker opens
        doc = c.snapshot()
        assert doc["breakers"] == {"pod:decode": "open"}
        assert "circuit open" in doc["records"][0]["why"]
        assert len([r for r in doc["records"]
                    if r["state"] == ABORTED]) == 2

    def test_spawn_timeout_watchdog_cleans_up(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1,
                          spawn_timeout_s=5.0)
        advice["decode"] = _up()
        c.tick()
        addr = launcher.spawned[0]
        advice["decode"] = {"direction": "hold", "why": "ok"}
        clock.advance(6.0)                    # never scraped
        c.tick()
        rec = c.snapshot()["records"][0]
        assert rec["state"] == ABORTED and rec["watchdog"] is True
        assert launcher.retired == [addr]     # half-made pod torn down
        assert c.watchdog_total == 1


class TestRetireStateMachine:
    def test_retire_drains_then_tears_down(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode",
                   "10.0.0.3:8000": "prefill"})
        _scrape(ds, "10.0.0.1:8000", at=900.0, running=0)
        _scrape(ds, "10.0.0.2:8000", at=900.0, running=3)
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1)
        advice["decode"] = _down()
        c.tick()
        # Victim is the least-loaded decode pod, marked draining.
        ep = ds.endpoint_get("10.0.0.1:8000")
        assert ep.metadata.labels.get(DRAINING_LABEL) == "true"
        # Still has queued work at the next scrape: not yet torn down.
        _scrape(ds, "10.0.0.1:8000", at=clock.advance(1.0), running=1)
        c.tick()
        assert launcher.retired == []
        # Drains empty: teardown.
        _scrape(ds, "10.0.0.1:8000", at=clock.advance(1.0))
        advice["decode"] = {"direction": "hold", "why": "ok"}
        c.tick()
        assert launcher.retired == ["10.0.0.1:8000"]
        assert c.snapshot()["records"][0]["state"] == COMPLETED

    def test_completed_retire_refreshes_census_same_tick(self):
        # Regression: the census is taken AFTER _advance_pending. A
        # retire that completes at the top of a tick deletes its
        # endpoint; the preflight for any follow-up action that same
        # tick must see the post-teardown pool — a stale census once
        # let sustained down-advice retire the genuinely last pod.
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode"})
        _scrape(ds, "10.0.0.1:8000", at=900.0, running=0)
        _scrape(ds, "10.0.0.2:8000", at=900.0, running=3)
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1)
        advice["decode"] = _down()
        c.tick()
        assert c.snapshot()["pending"]["target"] == "10.0.0.1:8000"
        # Drained: the next tick completes the retire AND, with advice
        # still down, immediately considers another one.
        _scrape(ds, "10.0.0.1:8000", at=clock.advance(1.0), running=0)
        c.tick()
        assert launcher.retired == ["10.0.0.1:8000"]
        snap = c.snapshot()
        assert snap.get("pending") is None
        survivor = ds.endpoint_get("10.0.0.2:8000")
        assert survivor is not None
        assert survivor.metadata.labels.get(DRAINING_LABEL) != "true"
        assert any(r["state"] == REFUSED and "last pod" in r["why"]
                   for r in snap["records"])

    def test_stuck_drain_force_finalized(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "decode",
                   "10.0.0.3:8000": "prefill"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1,
                          drain_timeout_s=5.0,
                          breaker_failure_threshold=1)
        advice["decode"] = _down()
        c.tick()
        addr = c.snapshot()["pending"]["target"]
        # The chaos stall_drain shape: scrapes keep showing running work
        # (until the watchdog tears the pod down and it vanishes).
        for _ in range(7):
            clock.advance(1.0)
            if ds.endpoint_get(addr) is not None:
                _scrape(ds, addr, at=clock.t, running=2)
            c.tick()
        rec = [r for r in c.snapshot()["records"]
               if r["kind"] == RETIRE_POD and r["state"] == COMPLETED][0]
        assert rec["drain_timed_out"] is True and rec["watchdog"] is True
        assert launcher.retired == [addr]     # torn down anyway
        assert c.watchdog_total == 1
        assert c._breaker("pod:decode").state == "open"


class TestRollback:
    def test_burn_trip_reverses_and_freezes(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        tripped = {"burn": False}
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1,
                          burn=lambda: tripped["burn"])
        advice["decode"] = _up()
        c.tick()
        addr = launcher.spawned[0]
        _scrape(ds, addr, at=clock.advance(1.0))
        advice["decode"] = {"direction": "hold", "why": "ok"}
        c.tick()                              # spawn completed, observing
        tripped["burn"] = True
        clock.advance(1.0)
        c.tick()                              # rollback fires
        doc = c.snapshot()
        assert doc["frozen"] is True
        assert "burn-rate" in doc["frozen_reason"]
        assert c.rollbacks_total == 1
        rolled = [r for r in doc["records"]
                  if r["state"] == ROLLED_BACK]
        assert rolled and rolled[0]["kind"] == SPAWN_POD
        # The reverse action (retire of the spawned pod) is in flight...
        assert doc["pending"]["kind"] == RETIRE_POD
        assert doc["pending"]["target"] == addr
        assert doc["pending"]["rollback_of"] == rolled[0]["id"]
        # ...and completes once the pod drains.
        _scrape(ds, addr, at=clock.advance(1.0))
        c.tick()
        assert launcher.retired == [addr]
        # Frozen: new advice only refuses.
        advice["decode"] = _up()
        for _ in range(4):
            clock.advance(1.0)
            c.tick()
        assert "frozen" in c.snapshot()["records"][0]["why"]
        c.unfreeze()
        assert c.snapshot()["frozen"] is False

    def test_attainment_collapse_triggers_rollback(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        att = {"v": None}
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1,
                          att=lambda: att["v"], rollback_attainment=0.5)
        advice["decode"] = _up()
        c.tick()
        _scrape(ds, launcher.spawned[0], at=clock.advance(1.0))
        advice["decode"] = {"direction": "hold", "why": "ok"}
        c.tick()
        att["v"] = 0.2                        # collapse inside the window
        clock.advance(1.0)
        c.tick()
        assert c.snapshot()["frozen"] is True
        assert "attainment" in c.snapshot()["frozen_reason"]

    def test_quiet_observation_window_judges_outcome(self):
        ds = Datastore()
        _pool(ds, {"10.0.0.1:8000": "decode", "10.0.0.2:8000": "prefill"})
        clock = FakeClock()
        launcher = StubLauncher(ds)
        c, advice = _ctrl(ds, clock, launcher=launcher, sustain_ticks=1,
                          observation_window_s=10.0)
        advice["decode"] = _up(headroom=-0.2)
        c.tick()
        _scrape(ds, launcher.spawned[0], at=clock.advance(1.0))
        advice["decode"] = {"direction": "hold", "why": "ok"}
        c.tick()
        # Window passes quietly; realized headroom improved.
        advice["decode"] = {"direction": "hold", "why": "ok",
                            "headroom": 0.3}
        clock.advance(15.0)
        c.tick()
        rec = [r for r in c.snapshot()["records"]
               if r["kind"] == SPAWN_POD][0]
        assert rec["state"] == COMPLETED
        assert rec["outcome"] == "improved"
        assert rec["realized_headroom"] == 0.3
        assert c.snapshot()["frozen"] is False


class TestWorkerDimension:
    def test_worker_count_tracks_pods(self):
        ds = Datastore()
        _pool(ds, {f"10.0.0.{i}:8000": "decode" for i in range(1, 5)})
        clock = FakeClock()
        scaler = StubScaler(active=3, provisioned=3)
        c, _ = _ctrl(ds, clock, scaler=scaler, pods_per_worker=2,
                     sustain_ticks=1)
        # 4 pods / 2 podsPerWorker = 2 workers wanted, 3 active: retire.
        c.tick()
        assert scaler.calls == ["retire"]
        assert c.snapshot()["pending"]["kind"] == RETIRE_WORKER
        clock.advance(1.0)
        c.tick()                              # counts converged
        assert c.snapshot()["records"][0]["state"] == COMPLETED
        assert scaler.active == 2

    def test_scaler_refusal_is_leddered(self):
        ds = Datastore()
        _pool(ds, {f"10.0.0.{i}:8000": "decode" for i in range(1, 5)})
        clock = FakeClock()
        scaler = StubScaler(active=3, provisioned=3, refuse=True)
        c, _ = _ctrl(ds, clock, scaler=scaler, pods_per_worker=2,
                     sustain_ticks=1)
        c.tick()
        rec = c.snapshot()["records"][0]
        assert rec["state"] == REFUSED
        assert "scaler refused" in rec["why"]


class TestMergeAndSnapshot:
    def test_merge_autoscale(self):
        acting = {"enabled": True, "acting": True, "actions_total": 3,
                  "refusals_total": 2, "rollbacks_total": 1,
                  "frozen": True, "frozen_reason": "burn",
                  "fleet_size": {"prefill": 1, "decode": 2},
                  "records": [{"id": 1, "t_unix": 10.0, "kind": SPAWN_POD,
                               "state": COMPLETED}]}
        follower = {"enabled": True, "acting": False, "actions_total": 0,
                    "refusals_total": 0, "rollbacks_total": 0,
                    "frozen": False, "records": []}
        out = merge_autoscale([(0, acting), (1, follower)])
        assert out["workers"] == 2
        assert out["acting_shards"] == [0]
        assert out["frozen"] is True and out["frozen_reason"] == "burn"
        assert out["actions_total"] == 3
        assert out["fleet_size"] == {"prefill": 1, "decode": 2}
        assert out["records"][0]["shard"] == 0
        assert out["shards"]["1"]["acting"] is False

    def test_snapshot_caps_records(self):
        ds = Datastore()
        clock = FakeClock()
        c, advice = _ctrl(ds, clock)
        for i in range(100):
            advice["decode"] = (_up() if i % 2 else _down())
            c.tick()
            clock.advance(1.0)
        assert len(c.snapshot(records_n=5)["records"]) <= 5


class TestLifecycleChaos:
    def test_spec_parses_new_kinds(self):
        inj = FaultInjector.from_spec(
            "spawn_fail:100,slow_start:50:1500,stall_drain:100:2", seed=11)
        kinds = [r.kind for r in inj.rules]
        assert kinds == ["spawn_fail", "slow_start", "stall_drain"]
        assert inj.rules[1].arg == 1500.0

    def test_lifecycle_decides_per_pod_and_is_deterministic(self):
        inj = FaultInjector.from_spec("spawn_fail:50", seed=11)
        verdicts = {p: inj.decide_lifecycle("spawn_fail", p) is not None
                    for p in (f"10.0.0.{i}:8000" for i in range(20))}
        again = FaultInjector.from_spec("spawn_fail:50", seed=11)
        assert verdicts == {
            p: again.decide_lifecycle("spawn_fail", p) is not None
            for p in verdicts}
        assert any(verdicts.values()) and not all(verdicts.values())

    def test_request_plane_skips_lifecycle_rules(self):
        inj = FaultInjector.from_spec("spawn_fail:100,stall_drain:100",
                                      seed=11)
        assert inj.decide("req-1") is None
        assert inj.triggered["spawn_fail"] == 0

    def test_engine_spawn_fail_raises_on_start(self):
        from llm_d_inference_scheduler_tpu.engine import EngineConfig
        from llm_d_inference_scheduler_tpu.engine.server import EngineServer

        async def body():
            s = EngineServer(EngineConfig(
                backend="sim", model="tiny", port=18631,
                chaos="spawn_fail:100", chaos_seed=7))
            with pytest.raises(RuntimeError, match="spawn_fail"):
                await s.start()

        asyncio.run(body())

    def test_engine_stall_drain_pins_phantom_running(self):
        from llm_d_inference_scheduler_tpu.engine import EngineConfig
        from llm_d_inference_scheduler_tpu.engine.server import EngineServer

        async def body():
            s = EngineServer(EngineConfig(
                backend="sim", model="tiny", port=18632,
                chaos="stall_drain:100:3", chaos_seed=7))
            await s.start()
            try:
                async with httpx.AsyncClient(timeout=10) as cx:
                    r = await cx.get("http://127.0.0.1:18632/metrics")
                line = [ln for ln in r.text.splitlines()
                        if ln.startswith("jetstream:num_requests_running ")]
                assert float(line[0].rsplit(" ", 1)[1]) >= 3.0
            finally:
                await s.stop()

        asyncio.run(body())

    def test_engine_slow_start_holds_health_503(self):
        from llm_d_inference_scheduler_tpu.engine import EngineConfig
        from llm_d_inference_scheduler_tpu.engine.server import EngineServer

        async def body():
            s = EngineServer(EngineConfig(
                backend="sim", model="tiny", port=18633,
                chaos="slow_start:100:400", chaos_seed=7))
            await s.start()
            try:
                async with httpx.AsyncClient(timeout=10) as cx:
                    r = await cx.get("http://127.0.0.1:18633/health")
                    assert r.status_code == 503
                    assert r.json()["status"] == "warming"
                    await asyncio.sleep(0.5)
                    r = await cx.get("http://127.0.0.1:18633/health")
                    assert r.status_code == 200
            finally:
                await s.stop()

        asyncio.run(body())


# ---------------------------------------------------------------------------
# Fleet plane: supervisor retiring state machine + admin surfaces.
# ---------------------------------------------------------------------------

SCALE_A, SCALE_B = 18641, 18642
SCALE_ADMIN = 18643


class _FakeProc:
    def __init__(self, alive=True):
        self.alive = alive
        self.terminated = False
        self.pid = 4242
        self.exitcode = None

    def is_alive(self):
        return self.alive

    def terminate(self):
        self.terminated = True
        self.alive = False   # drain resolves instantly in the fake


def _fake_sup(workers=3, leader=0):
    from llm_d_inference_scheduler_tpu.router.fleet import (
        FleetConfig,
        FleetSupervisor,
    )

    sup = FleetSupervisor(None, fleet=FleetConfig(workers=workers))
    sup._procs = [_FakeProc() for _ in range(workers)]
    sup.leader_index = leader
    sup._spawn = lambda i: sup._procs.__setitem__(i, _FakeProc())
    return sup


class TestSupervisorScaleIn:
    def test_retire_refuses_leader_and_last_worker(self):
        sup = _fake_sup(workers=2)
        assert sup.retire_worker(0) is None          # leader
        assert sup.retire_worker(1) == 1             # ok
        assert sup.retire_worker(None) is None       # last active = leader

    def test_retire_picks_highest_non_leader_and_restores(self):
        sup = _fake_sup(workers=3)
        assert sup.retire_worker(None) == 2
        assert sup._procs[2].terminated is True
        assert sup.worker_state(2) in ("retiring", "retired")
        assert sup.worker_state(1) == "up"
        assert sup.active_workers() == 2
        # A crashed worker reads "down", not "retired".
        sup._procs[1].alive = False
        assert sup.worker_state(1) == "down"
        sup._procs[1].alive = True
        # Restore brings the retired shard back.
        assert sup.restore_worker(None) == 2
        assert sup.worker_state(2) == "up"
        assert sup.retire_worker(2) == 2             # and it can retire again

    def test_scale_request_dispatch(self):
        sup = _fake_sup(workers=3)
        assert sup._scale_request("retire", None) == 2
        assert sup._scale_request("restore", None) == 2
        assert sup._scale_request("retire", 0) is None

    def test_balancer_remaps_disabled_shard(self):
        from llm_d_inference_scheduler_tpu.router.fleet import HashBalancer

        bal = HashBalancer("127.0.0.1", 0,
                           [("127.0.0.1", p) for p in (1, 2, 3)])
        bal.disable(1)
        assert bal.disabled == {1}
        bal.enable(1)
        assert bal.disabled == set()


def _scale_stub_worker(port, *, doc):
    app = web.Application()

    async def autoscale(request):
        return web.json_response(doc)

    async def health(request):
        return web.json_response({"status": "ok"})

    app.add_routes([web.get("/debug/autoscale", autoscale),
                    web.get("/health", health)])
    return app, port


def test_fleet_admin_autoscale_fan_in_and_scale_route():
    """/debug/autoscale fan-in (acting shard's ledger + follower rows +
    supervisor worker states) and the token-guarded POST /fleet/scale."""
    from llm_d_inference_scheduler_tpu.router.fleet import FleetAdmin

    acting_doc = {"enabled": True, "acting": True, "actions_total": 2,
                  "refusals_total": 1, "rollbacks_total": 0,
                  "frozen": False,
                  "records": [{"id": 1, "t_unix": 5.0, "kind": SPAWN_POD,
                               "state": COMPLETED}]}
    follower_doc = {"enabled": True, "acting": False, "actions_total": 0,
                    "refusals_total": 0, "rollbacks_total": 0,
                    "frozen": False, "records": []}
    scale_calls = []

    def scale_fn(action, shard):
        scale_calls.append((action, shard))
        return 1 if action == "retire" else None

    async def body():
        runners = []
        for app, port in (_scale_stub_worker(SCALE_A, doc=acting_doc),
                          _scale_stub_worker(SCALE_B, doc=follower_doc)):
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "127.0.0.1", port).start()
            runners.append(runner)
        states = {0: "up", 1: "up"}
        admin = FleetAdmin([("127.0.0.1", SCALE_A), ("127.0.0.1", SCALE_B)],
                           host="127.0.0.1", port=SCALE_ADMIN,
                           worker_state=lambda i: states[i],
                           scale_fn=scale_fn, control_token="tok")
        await admin.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                base = f"http://127.0.0.1:{SCALE_ADMIN}"
                r = await c.get(base + "/debug/autoscale")
                doc = r.json()
                assert doc["acting_shards"] == [0]
                assert doc["actions_total"] == 2
                assert doc["records"][0]["shard"] == 0
                assert doc["worker_states"] == ["up", "up"]
                # Token guard.
                r = await c.post(base + "/fleet/scale",
                                 json={"action": "retire"})
                assert r.status_code == 403
                r = await c.post(base + "/fleet/scale",
                                 json={"action": "retire"},
                                 headers={"x-fleet-token": "tok"})
                assert r.status_code == 200 and r.json()["shard"] == 1
                # Refusal -> 409.
                r = await c.post(base + "/fleet/scale",
                                 json={"action": "restore"},
                                 headers={"x-fleet-token": "tok"})
                assert r.status_code == 409 and r.json()["refused"]
                r = await c.post(base + "/fleet/scale",
                                 json={"action": "nuke"},
                                 headers={"x-fleet-token": "tok"})
                assert r.status_code == 400
                assert scale_calls == [("retire", None), ("restore", None)]
                # Satellite: a RETIRED shard does not 503 fleet /health
                # the way a crashed one does.
                await runners[1].cleanup()
                states[1] = "retired"
                r = await c.get(base + "/health")
                assert r.status_code == 200
                w = r.json()["workers"][1]
                assert (w["alive"], w["state"]) == (False, "retired")
                states[1] = "down"
                r = await c.get(base + "/health")
                assert r.status_code == 503
        finally:
            await admin.stop()
            for runner in runners:
                await runner.cleanup()

    asyncio.run(body())


def test_fleet_admin_scale_without_hooks_is_501():
    from llm_d_inference_scheduler_tpu.router.fleet import FleetAdmin

    async def body():
        admin = FleetAdmin([], host="127.0.0.1", port=SCALE_ADMIN + 1)
        await admin.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                r = await c.post(
                    f"http://127.0.0.1:{SCALE_ADMIN + 1}/fleet/scale",
                    json={"action": "retire"})
                assert r.status_code == 501
        finally:
            await admin.stop()

    asyncio.run(body())


@pytest.mark.slow
def test_fleet_scale_in_drain_e2e_zero_client_errors():
    """Satellite: retiring a worker mid-traffic is invisible to clients —
    in-flight requests on the retiring shard complete, new flows re-hash
    to survivors, fleet /health never flips, and the shard lands in
    ``retired`` (router_shard_state 3), not ``down``."""
    from prometheus_client.parser import text_string_to_metric_families

    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.fleet import (
        FleetConfig,
        FleetSupervisor,
    )

    E, GW, ADMIN = 18651, 18652, 18653
    CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {E}}}
scheduling: {{pickSeed: 7}}
"""

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=E, max_batch=8,
                                        sim_decode_ms_per_token=5.0))
        await eng.start()
        sup = FleetSupervisor(
            CFG, host="127.0.0.1", port=GW,
            fleet=FleetConfig(workers=2, balancer="hash",
                              admin_port=ADMIN),
            poll_interval=0.02, drain_timeout_s=5.0)
        await sup.start()
        try:
            # Both workers must have scraped the engine before traffic.
            async with httpx.AsyncClient(timeout=5) as c:
                for _ in range(200):
                    try:
                        r = await c.get(
                            f"http://127.0.0.1:{ADMIN}/health")
                        if (r.status_code == 200
                                and r.json().get("workers_ready") ==
                                sup.fleet.workers):
                            break
                    except httpx.HTTPError:
                        pass
                    await asyncio.sleep(0.05)
                else:
                    pytest.fail("fleet never became ready")
            victim = 1 if sup.leader_index == 0 else 0

            async def one(i):
                # flow pinned to the victim shard via the fairness id
                # search below; slow decode keeps it in flight across
                # the retire.
                async with httpx.AsyncClient(timeout=30) as c:
                    return await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        headers={"x-gateway-inference-fairness-id":
                                     flows[i]},
                        json={"model": "tiny", "prompt": "hi",
                              "max_tokens": 40})

            from llm_d_inference_scheduler_tpu.router.fleet import (
                flow_shard,
            )

            # Flows that hash to the victim shard (in-flight during the
            # retire) and one that doesn't (post-retire traffic).
            flows = [f for f in (f"flow-{i}" for i in range(64))
                     if flow_shard(f, 2) == victim][:3]
            tasks = [asyncio.create_task(one(i))
                     for i in range(len(flows))]
            await asyncio.sleep(0.15)         # requests reach the engine
            assert sup.retire_worker(victim) == victim
            results = await asyncio.gather(*tasks)
            assert [r.status_code for r in results] == [200] * len(flows)
            # New flow re-hashes to the survivor.
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(
                    f"http://127.0.0.1:{GW}/v1/completions",
                    headers={"x-gateway-inference-fairness-id":
                                 "post-retire"},
                    json={"model": "tiny", "prompt": "hi",
                          "max_tokens": 4})
                assert r.status_code == 200
                assert r.headers["x-router-shard"] == str(sup.leader_index)
                # The retiring shard settles into "retired".
                for _ in range(100):
                    if sup.worker_state(victim) == "retired":
                        break
                    await asyncio.sleep(0.1)
                assert sup.worker_state(victim) == "retired"
                base = f"http://127.0.0.1:{ADMIN}"
                r = await c.get(base + "/health")
                assert r.status_code == 200   # scale-in is not an outage
                doc = r.json()
                assert doc["workers"][victim]["state"] == "retired"
                r = await c.get(base + "/debug/fleet")
                assert r.json()["admin"][victim]["state"] == "retired"
                r = await c.get(base + "/metrics")
                fams = {f.name: f
                        for f in text_string_to_metric_families(r.text)}
                st = {s.labels["shard"]: s.value
                      for s in fams["router_shard_state"].samples}
                assert st[str(victim)] == 3.0
                assert st[str(sup.leader_index)] == 1.0
                # Restore: the shard comes back and serves again.
                r = await c.post(base + "/fleet/scale",
                                 json={"action": "restore"},
                                 headers={"x-fleet-token":
                                              sup._control_token})
                assert r.status_code == 200
                assert r.json()["shard"] == victim
                for _ in range(100):
                    if sup.worker_state(victim) == "up":
                        break
                    await asyncio.sleep(0.1)
                assert sup.worker_state(victim) == "up"
        finally:
            await sup.stop()
            await eng.stop()

    asyncio.run(body())
