"""Parity sweep (VERDICT r1 item 9): vllmgrpc-parser, the SGLang-style
concurrent-bootstrap sidecar connector, and prefix-cache-affinity-filter."""

import asyncio
import json
import struct

import httpx
from aiohttp import web

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.handlers.vllmgrpc import (
    EMBED_PATH,
    GENERATE_PATH,
    VllmGrpcParser,
)
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    LATENCY_ATTRIBUTE_KEY,
    PREFIX_ATTRIBUTE_KEY,
    LatencyPredictionInfo,
    PrefixCacheMatchInfo,
)
from llm_d_inference_scheduler_tpu.router.plugins.filters import (
    PrefixCacheAffinityFilter,
)
from llm_d_inference_scheduler_tpu.router.sidecar import Sidecar, SidecarConfig


# ---- protobuf encoding helpers (independent of the parser under test) ---


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _varint(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        out += bytes([b | (0x80 if v else 0)])
        if not v:
            return out


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _frame(msg: bytes) -> bytes:
    return b"\x00" + struct.pack(">I", len(msg)) + msg


def _generate_request() -> bytes:
    tokenized = _ld(1, b"hello world") + _ld(2, b"".join(
        _varint(t) for t in (5, 6, 7, 8)))  # packed input_ids
    sampling = (
        _tag(1, 5) + struct.pack("<f", 0.5)   # temperature
        + _tag(3, 0) + _varint(40)            # top_k
        + _tag(8, 0) + _varint(32)            # max_tokens
        + _ld(10, b"END")                     # stop
        + _tag(14, 0) + _varint(1)            # ignore_eos
    )
    msg = (_ld(1, b"req-42") + _ld(2, tokenized) + _ld(4, sampling)
           + _tag(5, 0) + _varint(1))         # stream=true
    return _frame(msg)


def test_vllmgrpc_parses_generate_request():
    res = VllmGrpcParser().parse(_generate_request(),
                                 {":path": GENERATE_PATH})
    assert res.error is None and not res.skip
    doc = res.body.completions
    assert doc["request_id"] == "req-42"
    assert doc["prompt"] == "hello world"
    assert res.body.tokenized_prompt == [5, 6, 7, 8]
    assert doc["max_tokens"] == 32 and doc["top_k"] == 40
    assert abs(doc["temperature"] - 0.5) < 1e-6
    assert doc["stop"] == ["END"] and doc["ignore_eos"] is True
    assert doc["stream"] is True
    # serialize() must forward the original wire bytes untouched
    assert VllmGrpcParser().serialize(res.body) == _generate_request()


def test_vllmgrpc_parses_embed_request():
    tokenized = _ld(1, b"embed me") + _ld(2, b"".join(_varint(t) for t in (9, 10)))
    raw = _frame(_ld(1, b"e-1") + _ld(2, tokenized))
    res = VllmGrpcParser().parse(raw, {":path": EMBED_PATH})
    assert res.error is None
    assert res.body.embeddings["input"] == "embed me"
    assert res.body.tokenized_prompt == [9, 10]


def test_vllmgrpc_parses_generate_response_usage():
    p = VllmGrpcParser()
    # Complete (oneof field 2): prompt=3, completion=4, cached=5.
    complete = (_tag(3, 0) + _varint(11) + _tag(4, 0) + _varint(7)
                + _tag(5, 0) + _varint(4))
    usage = p.parse_response(_frame(_ld(2, complete)), {})
    assert usage == {"prompt_tokens": 11, "completion_tokens": 7,
                     "total_tokens": 18,
                     "prompt_tokens_details": {"cached_tokens": 4}}
    # Streaming chunk (field 1): prompt=2, completion=3, cached=4.
    chunk = _tag(2, 0) + _varint(5) + _tag(3, 0) + _varint(2)
    usage = p.parse_response(_frame(_ld(1, chunk)), {})
    assert usage["total_tokens"] == 7
    # Token-less mid-stream chunk → no usage (reference vllmgrpc.go:150-156).
    empty = _ld(1, b"".join(_varint(t) for t in (1, 2)))  # token_ids only
    assert p.parse_response(_frame(_ld(1, empty)), {}) is None
    # EmbedResponse fallback: prompt_tokens=2.
    usage = p.parse_response(_frame(_tag(2, 0) + _varint(9)), {})
    assert usage == {"prompt_tokens": 9, "completion_tokens": 0,
                     "total_tokens": 9}
    # Garbage → None, never raises.
    assert p.parse_response(b"\x01junk", {}) is None
    # Coalesced stream buffer: [token-only chunk][final chunk with counts]
    # — usage must come from the LAST frame.
    final = _tag(2, 0) + _varint(6) + _tag(3, 0) + _varint(4)
    coalesced = _frame(_ld(1, empty)) + _frame(_ld(1, final))
    usage = p.parse_response(coalesced, {})
    assert usage["prompt_tokens"] == 6 and usage["completion_tokens"] == 4


def test_vllmgrpc_skips_unknown_paths_and_rejects_garbage():
    res = VllmGrpcParser().parse(b"\x00\x00\x00\x00\x00",
                                 {":path": "/vllm.grpc.engine.VllmEngine/Abort"})
    assert res.skip
    res = VllmGrpcParser().parse(b"\x01garbage", {":path": GENERATE_PATH})
    assert res.error is not None


# ---- prefix-cache-affinity-filter --------------------------------------


def _ep(port, hit=0.0, ttft=None) -> Endpoint:
    ep = Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1", port=port))
    ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(
        match_blocks=int(hit * 10), total_blocks=10, block_size_tokens=16))
    if ttft is not None:
        ep.attributes.put(LATENCY_ATTRIBUTE_KEY, LatencyPredictionInfo(
            ttft_ms=ttft, ttft_valid=True, tpot_valid=True))
    return ep


def _filter(**params) -> PrefixCacheAffinityFilter:
    f = PrefixCacheAffinityFilter()
    f.configure(params, None)
    f._rng.random = lambda: 0.99  # exploration off unless overridden
    return f


def test_affinity_filter_narrows_to_sticky():
    warm, cold = _ep(1, hit=0.9), _ep(2, hit=0.1)
    assert _filter().filter(None, None, None, [warm, cold]) == [warm]


def test_affinity_filter_keeps_all_without_sticky():
    eps = [_ep(1, hit=0.3), _ep(2, hit=0.1)]
    assert _filter().filter(None, None, None, eps) == eps


def test_affinity_filter_exploration_skips_gate():
    f = _filter()
    f._rng.random = lambda: 0.0
    eps = [_ep(1, hit=0.9), _ep(2, hit=0.1)]
    assert f.filter(None, None, None, eps) == eps


def test_affinity_filter_ttft_load_gate_breaks_stickiness():
    overloaded_warm = _ep(1, hit=0.9, ttft=9000.0)
    idle_cold = _ep(2, hit=0.1, ttft=50.0)
    eps = [overloaded_warm, idle_cold]
    assert _filter().filter(None, None, None, eps) == eps  # gate broken
    # within the penalty budget, stickiness holds
    assert _filter(maxTTFTPenaltyMs=20000).filter(
        None, None, None, eps) == [overloaded_warm]


# ---- sglang connector ---------------------------------------------------


def test_sglang_connector_concurrent_bootstrap():
    """Prefill and decode both receive the injected bootstrap triple; decode
    is NOT blocked on prefill completing (concurrency is the point)."""
    SC, DEC, PRE = 18651, 18652, 18653
    seen = {"prefill": None, "prefill_at": None}
    prefill_started = asyncio.Event()

    async def body():
        release_prefill = asyncio.Event()

        async def prefill_handler(request: web.Request):
            seen["prefill"] = await request.json()
            prefill_started.set()
            await release_prefill.wait()  # hold prefill OPEN past decode
            return web.json_response({"ok": True})

        app = web.Application()
        app.add_routes([web.post("/v1/completions", prefill_handler)])
        runner = web.AppRunner(app)
        await runner.setup()
        await web.TCPSite(runner, "127.0.0.1", PRE).start()

        dec = EngineServer(EngineConfig(backend="sim", model="tiny", port=DEC,
                                        sim_decode_ms_per_token=1.0))
        await dec.start()
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   connector="sglang", bootstrap_port=9333))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(f"http://127.0.0.1:{SC}/v1/completions",
                                 json={"prompt": "x", "max_tokens": 2},
                                 headers={"x-prefiller-host-port":
                                          f"127.0.0.1:{PRE}"})
                # Decode completed while prefill is still in flight.
                assert r.status_code == 200
                assert r.json()["choices"][0]["text"]
                await asyncio.wait_for(prefill_started.wait(), timeout=5)
                release_prefill.set()
                await asyncio.sleep(0.05)  # let the leg drain

            boot = seen["prefill"]
            assert boot["bootstrap_host"] == "127.0.0.1"
            assert boot["bootstrap_port"] == 9333
            assert isinstance(boot["bootstrap_room"], int)
            assert boot["prompt"] == "x"
        finally:
            await sc.stop()
            await dec.stop()
            await runner.cleanup()

    asyncio.run(body())
