"""Tracing: span capture, parent linkage, sampling, /debug/traces."""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router import tracing
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


def test_span_nesting_and_sampling():
    t = tracing.Tracer(enabled=True, sample_ratio=1.0)
    with t.span("outer", a=1) as outer:
        with t.span("inner") as inner:
            inner.set_attribute("b", 2)
    spans = t.snapshot()
    assert [s["name"] for s in spans] == ["inner", "outer"]
    assert spans[0]["parent_id"] == spans[1]["span_id"]
    assert spans[0]["trace_id"] == spans[1]["trace_id"]
    assert spans[0]["attributes"]["b"] == 2

    off = tracing.Tracer(enabled=False)
    with off.span("nope") as s:
        s.set_attribute("x", 1)  # noop span tolerates attributes
    assert off.snapshot() == []

    sampled = tracing.Tracer(enabled=True, sample_ratio=0.0)
    with sampled.span("dropped"):
        pass
    assert sampled.snapshot() == []


def test_gateway_traces_endpoint():
    async def body():
        old = (tracing.tracer.enabled, tracing.tracer.sample_ratio)
        tracing.tracer.enabled, tracing.tracer.sample_ratio = True, 1.0
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=18631))
        await eng.start()
        gw = build_gateway("""
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18631}
""", port=18630, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post("http://127.0.0.1:18630/v1/completions",
                                 json={"model": "tiny", "prompt": "t",
                                       "max_tokens": 2})
                assert r.status_code == 200
                r = await c.get("http://127.0.0.1:18630/debug/traces")
                spans = r.json()["spans"]
                names = [s["name"] for s in spans]
                assert "gateway.request" in names
                assert "gateway.request_orchestration" in names
                orch = next(s for s in spans
                            if s["name"] == "gateway.request_orchestration")
                root = next(s for s in spans if s["name"] == "gateway.request")
                assert orch["trace_id"] == root["trace_id"]
                assert orch["parent_id"] == root["span_id"]
                assert orch["attributes"]["target"].startswith("127.0.0.1")
        finally:
            tracing.tracer.enabled, tracing.tracer.sample_ratio = old
            await gw.stop()
            await eng.stop()

    asyncio.run(body())
