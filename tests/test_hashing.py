"""Pin the prefix-block hash chain's exact byte layout.

The chain must stay byte-for-byte stable (reference scheme,
approximateprefix/hashing.go:35-101: h_i = xxh64(block_i || h_{i-1}) with
4-byte little-endian token encoding): router index, engine KV events, and any
reference-side indexer in a mixed fleet all share this hash space. These
golden vectors reconstruct the layout independently so a silent change to the
concatenation order or token encoding fails CI.
"""

import xxhash

from llm_d_inference_scheduler_tpu.utils.hashing import (
    AVG_CHARS_PER_TOKEN,
    chain_block_hashes,
)


def test_token_chain_matches_reference_layout():
    model = "llama3-8b"
    tokens = list(range(100, 140))  # 40 tokens → 2 complete blocks of 16
    got = chain_block_hashes(model, tokens, "", 16)

    h = xxhash.xxh64(model.encode()).intdigest()
    expected = []
    for start in (0, 16):
        content = b"".join(t.to_bytes(4, "little") for t in tokens[start:start + 16])
        h = xxhash.xxh64(content + h.to_bytes(8, "little")).intdigest()
        expected.append(h)
    assert got == expected
    # Trailing partial block (tokens 32..39) is intentionally dropped.
    assert len(got) == 2


def test_token_chain_golden_digest():
    # Hard-coded digest: any change to model-seed hashing, token byte width,
    # endianness, or concatenation order changes this value.
    got = chain_block_hashes("m", [1, 2, 3, 4], "", 4)
    assert got == [15331926273878053439]


def test_text_chain_matches_reference_layout():
    model = "m"
    text = "a" * (2 * 4 * AVG_CHARS_PER_TOKEN + 3)  # 2 complete chunks + tail
    got = chain_block_hashes(model, None, text, 4)

    h = xxhash.xxh64(model.encode()).intdigest()
    step = 4 * AVG_CHARS_PER_TOKEN
    raw = text.encode()
    expected = []
    for start in (0, step):
        h = xxhash.xxh64(raw[start:start + step]
                         + h.to_bytes(8, "little")).intdigest()
        expected.append(h)
    assert got == expected
