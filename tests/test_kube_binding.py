"""k8s watch binding (router/kube.py): a fake API server speaking the real
list+watch protocol (resourceVersions, streaming JSON events, bookmarks,
410 Gone) drives the four reconcilers into the datastore — the hermetic
analogue of the reference's envtest-based controller tests."""

from __future__ import annotations

import asyncio
import json

import pytest
from aiohttp import web

from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.kube import (
    KubeApiClient,
    KubeBinding,
)

NS = "llmd"
PODS = f"/api/v1/namespaces/{NS}/pods"
POOLS = f"/apis/llm-d.ai/v1alpha2/namespaces/{NS}/inferencepools"
OBJS = f"/apis/llm-d.ai/v1alpha2/namespaces/{NS}/inferenceobjectives"
REWRITES = f"/apis/llm-d.ai/v1alpha2/namespaces/{NS}/inferencemodelrewrites"


class FakeKube:
    """Tiny API server: per-collection object store + watch event history;
    watches replay events after the requested resourceVersion then stream
    live. ``force_gone`` makes the next watch on a path return 410."""

    def __init__(self):
        self.rv = 0
        self.store: dict[str, dict[str, dict]] = {}
        self.history: dict[str, list[tuple[int, str, dict]]] = {}
        self.subscribers: dict[str, list[asyncio.Queue]] = {}
        self.force_gone: set[str] = set()
        self.app = web.Application()
        self.app.router.add_get("/{tail:.*}", self.handle)
        self.app.router.add_post("/{tail:.*}", self.handle_create)
        self.app.router.add_put("/{tail:.*}", self.handle_replace)
        self.runner = None
        self.port = None
        # Optional failure injection for write verbs (lease tests).
        self.fail_writes = False

    def _bump(self) -> int:
        self.rv += 1
        return self.rv

    def upsert(self, path: str, obj: dict):
        rv = self._bump()
        obj = json.loads(json.dumps(obj))
        obj.setdefault("metadata", {})["resourceVersion"] = str(rv)
        obj["metadata"].setdefault("namespace", NS)
        name = obj["metadata"]["name"]
        etype = "MODIFIED" if name in self.store.get(path, {}) else "ADDED"
        self.store.setdefault(path, {})[name] = obj
        self._emit(path, rv, etype, obj)

    def delete(self, path: str, name: str):
        rv = self._bump()
        obj = self.store.get(path, {}).pop(name, None)
        if obj is None:
            return
        obj["metadata"]["resourceVersion"] = str(rv)
        self._emit(path, rv, "DELETED", obj)

    def _emit(self, path: str, rv: int, etype: str, obj: dict):
        self.history.setdefault(path, []).append((rv, etype, obj))
        for q in self.subscribers.get(path, []):
            q.put_nowait((rv, etype, obj))

    async def handle_create(self, request: web.Request) -> web.Response:
        """POST to a collection: 409 when the named object exists (k8s
        AlreadyExists), else store with a fresh resourceVersion."""
        if self.fail_writes:
            return web.Response(status=500)
        path = "/" + request.match_info["tail"]
        obj = await request.json()
        name = (obj.get("metadata") or {}).get("name")
        if name in self.store.get(path, {}):
            return web.json_response({"reason": "AlreadyExists"}, status=409)
        self.upsert(path, obj)
        return web.json_response(self.store[path][name], status=201)

    async def handle_replace(self, request: web.Request) -> web.Response:
        """PUT an object: resourceVersion must match the stored one (k8s
        optimistic concurrency), else 409 Conflict."""
        if self.fail_writes:
            return web.Response(status=500)
        tail = request.match_info["tail"]
        path, _, name = ("/" + tail).rpartition("/")
        obj = await request.json()
        current = self.store.get(path, {}).get(name)
        if current is None:
            return web.Response(status=404)
        sent_rv = (obj.get("metadata") or {}).get("resourceVersion")
        if sent_rv != current["metadata"]["resourceVersion"]:
            return web.json_response({"reason": "Conflict"}, status=409)
        self.upsert(path, obj)
        return web.json_response(self.store[path][name])

    # Paths whose LAST segment is one of these are collection list/watch
    # requests; anything deeper is a single-object GET.
    COLLECTIONS = ("pods", "inferencepools", "inferenceobjectives",
                   "inferencemodelrewrites", "leases")

    async def handle(self, request: web.Request) -> web.StreamResponse:
        path = "/" + request.match_info["tail"]
        if request.query.get("watch") != "true":
            if path.rsplit("/", 1)[-1] not in self.COLLECTIONS:
                # Single-object GET (e.g. …/leases/<name>).
                coll, _, name = path.rpartition("/")
                obj = self.store.get(coll, {}).get(name)
                if obj is None:
                    return web.Response(status=404)
                return web.json_response(obj)
            items = list(self.store.get(path, {}).values())
            return web.json_response({
                "items": items,
                "metadata": {"resourceVersion": str(self.rv)}})
        if path in self.force_gone:
            self.force_gone.discard(path)
            return web.Response(status=410)
        since = int(request.query.get("resourceVersion") or 0)
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        for rv, etype, obj in self.history.get(path, []):
            if rv > since:
                q.put_nowait((rv, etype, obj))
        self.subscribers.setdefault(path, []).append(q)
        try:
            while True:
                rv, etype, obj = await q.get()
                frame = json.dumps({"type": etype, "object": obj}) + "\n"
                await resp.write(frame.encode())
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            self.subscribers.get(path, []).remove(q)
        return resp

    async def start(self):
        # Watch handlers block in q.get(); don't let cleanup wait 60s for
        # them (aiohttp's default shutdown_timeout) — cancel quickly.
        self.runner = web.AppRunner(self.app, shutdown_timeout=0.25)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self):
        if self.runner:
            await self.runner.cleanup()


def pod(name: str, ip: str, labels: dict, phase: str = "Running",
        ready: bool = True) -> dict:
    return {"metadata": {"name": name, "labels": labels},
            "status": {"podIP": ip, "phase": phase,
                       "conditions": [{"type": "Ready",
                                       "status": "True" if ready
                                       else "False"}]}}


async def eventually(predicate, timeout=5.0, what=""):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"condition never held: {what}")
        await asyncio.sleep(0.02)


@pytest.fixture()
def fake():
    return FakeKube()


def test_kube_binding_converges_and_tracks_watches(fake):
    async def run():
        await fake.start()
        fake.upsert(POOLS, {
            "metadata": {"name": "pool"},
            "spec": {"selector": {"matchLabels": {"app": "llmd"}},
                     "targetPort": 8200, "metricsPort": 9090}})
        fake.upsert(PODS, pod("d0", "10.0.0.1", {"app": "llmd",
                                                 "llm-d.ai/role": "decode"}))
        fake.upsert(PODS, pod("d1", "10.0.0.2", {"app": "llmd"}))
        fake.upsert(PODS, pod("other", "10.9.9.9", {"app": "unrelated"}))
        fake.upsert(PODS, pod("pending", "", {"app": "llmd"},
                              phase="Pending"))
        # Running but NOT Ready (still loading weights / failing its
        # readiness probe) — must not receive traffic (pod_reconciler.go:92).
        fake.upsert(PODS, pod("warming", "10.0.0.7", {"app": "llmd"},
                              ready=False))
        fake.upsert(OBJS, {"metadata": {"name": "premium"},
                           "spec": {"priority": 10}})
        fake.upsert(REWRITES, {
            "metadata": {"name": "canary"},
            "spec": {"sourceModel": "base",
                     "targets": [{"model": "base-v2", "weight": 1}]}})

        ds = Datastore()
        client = KubeApiClient(f"http://127.0.0.1:{fake.port}")
        binding = KubeBinding(ds, client, NS, pool_name="pool")
        await binding.start()
        try:
            await binding.wait_synced()
            # Initial convergence: matching Running pods only, pool ports.
            await eventually(lambda: len(ds.endpoint_list()) == 2,
                             what="initial pod sync")
            eps = {e.metadata.address_port: e for e in ds.endpoint_list()}
            assert set(eps) == {"10.0.0.1:8200", "10.0.0.2:8200"}
            assert eps["10.0.0.1:8200"].metadata.labels["llm-d.ai/role"] == "decode"
            assert eps["10.0.0.1:8200"].metadata.metrics_port == 9090
            assert ds.objective_get("premium").priority == 10
            assert ds.rewrite_for("base") is not None

            # Watch: pod add / delete propagate; a pod turning Ready joins.
            fake.upsert(PODS, pod("warming", "10.0.0.7", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 3,
                             what="pod turning Ready via watch")
            fake.upsert(PODS, pod("warming", "10.0.0.7", {"app": "llmd"},
                                  ready=False))
            await eventually(lambda: len(ds.endpoint_list()) == 2,
                             what="pod turning unready via watch")
            fake.upsert(PODS, pod("d2", "10.0.0.3", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 3,
                             what="pod add via watch")
            fake.delete(PODS, "d1")
            await eventually(
                lambda: {e.metadata.address_port for e in ds.endpoint_list()}
                == {"10.0.0.1:8200", "10.0.0.3:8200"},
                what="pod delete via watch")

            # Objective delete propagates.
            fake.delete(OBJS, "premium")
            await eventually(lambda: ds.objective_get("premium") is None,
                             what="objective delete")

            # 410 Gone forces a relist; changes made meanwhile are found.
            # Kill the live pod stream so the informer reconnects and is
            # served the 410 (otherwise the healthy watch never ends).
            fake.force_gone.add(PODS)
            for q in list(fake.subscribers.get(PODS, [])):
                q.put_nowait(None)  # poison → handler errors → stream ends
            fake.upsert(PODS, pod("d3", "10.0.0.4", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 3,
                             what="recovery after 410 relist")
            assert not fake.force_gone, "410 was never served to a watch"

            # Pool retarget: selector + port change re-derives endpoints
            # from the cached pods without a watch restart.
            fake.upsert(POOLS, {
                "metadata": {"name": "pool"},
                "spec": {"selector": {"matchLabels": {"app": "llmd",
                                                      "llm-d.ai/role": "decode"}},
                         "targetPort": 9000}})
            await eventually(
                lambda: {e.metadata.address_port for e in ds.endpoint_list()}
                == {"10.0.0.1:9000"},
                what="pool selector/port change")
        finally:
            await binding.stop()
            await fake.stop()

    asyncio.run(run())


def test_kube_binding_watch_resumes_from_resource_version(fake):
    """A dropped connection resumes from the last seen version — no events
    lost, no duplicate full resync (history replay path)."""
    async def run():
        await fake.start()
        ds = Datastore()
        client = KubeApiClient(f"http://127.0.0.1:{fake.port}")
        binding = KubeBinding(ds, client, NS, pool_name=None)
        binding.pool.selector = {"app": "llmd"}
        binding.pool.target_port = 8000
        await binding.start()
        try:
            await binding.wait_synced()
            fake.upsert(PODS, pod("a", "10.1.0.1", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 1,
                             what="first pod")
            # Kill every live watch stream (simulates LB idle reset);
            # mutate while disconnected — the replay-from-rv path must
            # deliver the missed event.
            for qs in fake.subscribers.values():
                for q in list(qs):
                    q.put_nowait(None)  # poison → TypeError → stream ends
            fake.upsert(PODS, pod("b", "10.1.0.2", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 2,
                             what="missed event recovered on resume")
        finally:
            await binding.stop()
            await fake.stop()

    asyncio.run(run())


def test_gateway_routes_to_kube_discovered_endpoints(fake):
    """Full path: gateway + kube binding against the fake API server; pods
    appear as endpoints and serve a real completion via a sim engine."""
    async def run():
        from llm_d_inference_scheduler_tpu.engine.config import EngineConfig
        from llm_d_inference_scheduler_tpu.engine.server import EngineServer
        from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

        eng = EngineServer(EngineConfig(model="tiny", backend="sim",
                                        port=18861, kv_events_port=0))
        await eng.start()
        await fake.start()
        fake.upsert(POOLS, {
            "metadata": {"name": "pool"},
            "spec": {"selector": {"matchLabels": {"app": "llmd"}},
                     "targetPort": 18861}})
        fake.upsert(PODS, pod("sim0", "127.0.0.1", {"app": "llmd"}))

        gw = build_gateway(
            "plugins: [{type: queue-scorer}]\n"
            "schedulingProfiles: [{name: default, plugins: "
            "[{pluginRef: queue-scorer}]}]\n",
            port=18860,
            kube={"api_url": f"http://127.0.0.1:{fake.port}",
                  "namespace": NS, "pool_name": "pool"})
        await gw.start()
        try:
            await gw.kube_binding.wait_synced()
            await eventually(
                lambda: len(gw.datastore.endpoint_list()) == 1,
                what="kube-discovered endpoint")

            import json as _json
            import urllib.request

            def post():
                body = _json.dumps({"model": "tiny", "prompt": "hi there",
                                    "max_tokens": 3}).encode()
                r = urllib.request.urlopen(urllib.request.Request(
                    "http://127.0.0.1:18860/v1/completions", data=body,
                    headers={"Content-Type": "application/json"}), timeout=30)
                return r.headers.get("x-gateway-destination-endpoint-served")

            dest = await asyncio.get_running_loop().run_in_executor(None, post)
            assert dest == "127.0.0.1:18861"
        finally:
            await gw.stop()
            await fake.stop()
            await eng.stop()

    asyncio.run(run())


# ---- coordination.k8s.io/v1 Lease leader election -----------------------


def make_lease_elector(fake, holder, **kw):
    from llm_d_inference_scheduler_tpu.router.kube import KubeLeaseElector

    client = KubeApiClient(f"http://127.0.0.1:{fake.port}")
    return KubeLeaseElector(client, NS, "epp-llmd-pool.llm-d.ai",
                            holder_id=holder,
                            lease_duration_s=kw.pop("lease_duration_s", 0.6),
                            renew_interval_s=kw.pop("renew_interval_s", 0.1),
                            **kw)


LEASES = f"/apis/coordination.k8s.io/v1/namespaces/{NS}/leases"


def test_kube_lease_acquire_renew_and_follower(fake):
    """First claimant creates the Lease and leads; a second stays follower
    while the lease is live; renewTime advances on the wire."""
    async def run():
        await fake.start()
        a = make_lease_elector(fake, "epp-a")
        b = make_lease_elector(fake, "epp-b")
        try:
            await a.start()
            await eventually(lambda: a.is_leader, what="a acquires")
            lease = fake.store[LEASES]["epp-llmd-pool.llm-d.ai"]
            assert lease["spec"]["holderIdentity"] == "epp-a"
            assert lease["spec"]["leaseTransitions"] == 0
            first_renew = lease["spec"]["renewTime"]
            await b.start()
            await asyncio.sleep(0.4)
            assert not b.is_leader and a.is_leader
            lease = fake.store[LEASES]["epp-llmd-pool.llm-d.ai"]
            assert lease["spec"]["renewTime"] > first_renew  # renewing
        finally:
            await a.stop()
            await b.stop()
            await fake.stop()

    asyncio.run(run())


def test_kube_lease_expiry_takeover_and_transitions(fake):
    """Killing the leader non-gracefully lets the follower take over after
    leaseDurationSeconds, bumping leaseTransitions (client-go takeover)."""
    async def run():
        await fake.start()
        a = make_lease_elector(fake, "epp-a")
        b = make_lease_elector(fake, "epp-b")
        try:
            await a.start()
            await eventually(lambda: a.is_leader, what="a acquires")
            await b.start()
            await asyncio.sleep(0.25)
            assert not b.is_leader
            # Crash a: no graceful release — b must wait out the expiry.
            await a.stop(graceful=False)
            await eventually(lambda: b.is_leader, timeout=5.0,
                             what="takeover after expiry")
            lease = fake.store[LEASES]["epp-llmd-pool.llm-d.ai"]
            assert lease["spec"]["holderIdentity"] == "epp-b"
            assert lease["spec"]["leaseTransitions"] == 1
        finally:
            await a.stop()
            await b.stop()
            await fake.stop()

    asyncio.run(run())


def test_kube_lease_graceful_release_fast_handoff(fake):
    """Graceful stop shortens the lease so the follower takes over on its
    next tick instead of waiting a full leaseDuration."""
    async def run():
        await fake.start()
        a = make_lease_elector(fake, "epp-a", lease_duration_s=30.0)
        b = make_lease_elector(fake, "epp-b", lease_duration_s=30.0)
        try:
            await a.start()
            await eventually(lambda: a.is_leader, what="a acquires")
            await b.start()
            await asyncio.sleep(0.25)
            assert not b.is_leader
            await a.stop(graceful=True)  # release: 30 s lease would block b
            await eventually(lambda: b.is_leader, timeout=3.0,
                             what="fast handoff after release")
        finally:
            await a.stop()
            await b.stop()
            await fake.stop()

    asyncio.run(run())


def test_kube_lease_demotes_when_api_unreachable(fake):
    """A leader that cannot renew must drop leadership (its lease may have
    been taken over) — readiness flips, the pair cannot split-brain."""
    async def run():
        await fake.start()
        a = make_lease_elector(fake, "epp-a")
        try:
            await a.start()
            await eventually(lambda: a.is_leader, what="a acquires")
            fake.fail_writes = True
            await eventually(lambda: not a.is_leader, timeout=3.0,
                             what="demote on renew failure")
            fake.fail_writes = False
            await eventually(lambda: a.is_leader, timeout=3.0,
                             what="re-acquire after API recovers")
        finally:
            await a.stop()
            await fake.stop()

    asyncio.run(run())


def test_gateway_ha_pair_via_kube_lease(fake):
    """Two gateways with lease-only kube config (endpoints from static
    config): only the Lease holder reports ready; killing it promotes the
    follower — the reference's HA disruption semantics without any shared
    volume (controller_manager.go:84-91)."""
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
    from llm_d_inference_scheduler_tpu.router.kube import KubeLeaseElector

    async def run():
        await fake.start()
        cfg = """
pool:
  endpoints:
    - {address: 127.0.0.1, port: 19999}
"""
        gws = []
        for port in (18880, 18881):
            gw = build_gateway(
                cfg, port=port, poll_interval=0.05,
                kube={"api_url": f"http://127.0.0.1:{fake.port}",
                      "namespace": NS,
                      "lease_name": "epp-llmd-pool.llm-d.ai"})
            assert isinstance(gw.elector, KubeLeaseElector)
            assert gw.kube_binding is None  # lease-only: config owns pool
            gw.elector.lease_duration_s = 0.6
            gw.elector.renew_interval_s = 0.1
            await gw.start()
            gws.append(gw)
        try:
            import aiohttp

            async def ready(port):
                async with aiohttp.ClientSession() as s:
                    async with s.get(f"http://127.0.0.1:{port}/health") as r:
                        return r.status == 200

            await eventually(
                lambda: sum(gw.elector.is_leader for gw in gws) == 1,
                what="exactly one leader")
            leader = next(gw for gw in gws if gw.elector.is_leader)
            follower = next(gw for gw in gws if not gw.elector.is_leader)
            assert await ready(leader.port)
            assert not await ready(follower.port)
            # Disruption: leader dies without a graceful release.
            await leader.elector.stop(graceful=False)
            leader.elector = None  # detach so gw.stop() doesn't double-stop
            await eventually(lambda: follower.elector.is_leader, timeout=5.0,
                             what="follower promoted after leader loss")
            assert await ready(follower.port)
        finally:
            for gw in gws:
                await gw.stop()
            await fake.stop()

    asyncio.run(run())


def test_kube_lease_skewed_holder_clock_no_spurious_takeover(fake):
    """A live holder whose wall clock is far behind (renewTime 'expired' by
    local reckoning) must NOT be stolen from while its renews keep landing:
    expiry is timed from the local observation of lease changes (client-go
    observedTime), not from comparing remote timestamps to the local
    clock."""
    import time as _time

    from llm_d_inference_scheduler_tpu.router.kube import _micro_time

    async def run():
        await fake.start()
        name = "epp-llmd-pool.llm-d.ai"
        skew = -3600.0  # holder's clock is an hour behind

        def skewed_renew():
            lease = fake.store.get(LEASES, {}).get(name)
            spec = {"holderIdentity": "epp-skewed",
                    "leaseDurationSeconds": 1,
                    "renewTime": _micro_time(_time.time() + skew),
                    "leaseTransitions": 0}
            if lease is None:
                fake.upsert(LEASES, {"metadata": {"name": name},
                                     "spec": spec})
            else:
                lease["spec"].update(spec)
                fake.upsert(LEASES, lease)

        skewed_renew()
        b = make_lease_elector(fake, "epp-b", lease_duration_s=1.0,
                               renew_interval_s=0.1)
        try:
            await b.start()
            # Keep the skewed holder renewing faster than its 1 s lease.
            for _ in range(10):
                await asyncio.sleep(0.2)
                skewed_renew()
                assert not b.is_leader, "stole a live (skewed) lease"
            holder = fake.store[LEASES][name]["spec"]["holderIdentity"]
            assert holder == "epp-skewed"
            # Once the skewed holder really stops, b takes over on the
            # locally-observed expiry.
            await eventually(lambda: b.is_leader, timeout=5.0,
                             what="takeover after real death")
        finally:
            await b.stop()
            await fake.stop()

    asyncio.run(run())
