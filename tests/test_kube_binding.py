"""k8s watch binding (router/kube.py): a fake API server speaking the real
list+watch protocol (resourceVersions, streaming JSON events, bookmarks,
410 Gone) drives the four reconcilers into the datastore — the hermetic
analogue of the reference's envtest-based controller tests."""

from __future__ import annotations

import asyncio
import json

import pytest
from aiohttp import web

from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.kube import (
    KubeApiClient,
    KubeBinding,
)

NS = "llmd"
PODS = f"/api/v1/namespaces/{NS}/pods"
POOLS = f"/apis/llm-d.ai/v1alpha2/namespaces/{NS}/inferencepools"
OBJS = f"/apis/llm-d.ai/v1alpha2/namespaces/{NS}/inferenceobjectives"
REWRITES = f"/apis/llm-d.ai/v1alpha2/namespaces/{NS}/inferencemodelrewrites"


class FakeKube:
    """Tiny API server: per-collection object store + watch event history;
    watches replay events after the requested resourceVersion then stream
    live. ``force_gone`` makes the next watch on a path return 410."""

    def __init__(self):
        self.rv = 0
        self.store: dict[str, dict[str, dict]] = {}
        self.history: dict[str, list[tuple[int, str, dict]]] = {}
        self.subscribers: dict[str, list[asyncio.Queue]] = {}
        self.force_gone: set[str] = set()
        self.app = web.Application()
        self.app.router.add_get("/{tail:.*}", self.handle)
        self.runner = None
        self.port = None

    def _bump(self) -> int:
        self.rv += 1
        return self.rv

    def upsert(self, path: str, obj: dict):
        rv = self._bump()
        obj = json.loads(json.dumps(obj))
        obj.setdefault("metadata", {})["resourceVersion"] = str(rv)
        obj["metadata"].setdefault("namespace", NS)
        name = obj["metadata"]["name"]
        etype = "MODIFIED" if name in self.store.get(path, {}) else "ADDED"
        self.store.setdefault(path, {})[name] = obj
        self._emit(path, rv, etype, obj)

    def delete(self, path: str, name: str):
        rv = self._bump()
        obj = self.store.get(path, {}).pop(name, None)
        if obj is None:
            return
        obj["metadata"]["resourceVersion"] = str(rv)
        self._emit(path, rv, "DELETED", obj)

    def _emit(self, path: str, rv: int, etype: str, obj: dict):
        self.history.setdefault(path, []).append((rv, etype, obj))
        for q in self.subscribers.get(path, []):
            q.put_nowait((rv, etype, obj))

    async def handle(self, request: web.Request) -> web.StreamResponse:
        path = "/" + request.match_info["tail"]
        if request.query.get("watch") != "true":
            items = list(self.store.get(path, {}).values())
            return web.json_response({
                "items": items,
                "metadata": {"resourceVersion": str(self.rv)}})
        if path in self.force_gone:
            self.force_gone.discard(path)
            return web.Response(status=410)
        since = int(request.query.get("resourceVersion") or 0)
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        for rv, etype, obj in self.history.get(path, []):
            if rv > since:
                q.put_nowait((rv, etype, obj))
        self.subscribers.setdefault(path, []).append(q)
        try:
            while True:
                rv, etype, obj = await q.get()
                frame = json.dumps({"type": etype, "object": obj}) + "\n"
                await resp.write(frame.encode())
        except (asyncio.CancelledError, ConnectionResetError):
            pass
        finally:
            self.subscribers.get(path, []).remove(q)
        return resp

    async def start(self):
        # Watch handlers block in q.get(); don't let cleanup wait 60s for
        # them (aiohttp's default shutdown_timeout) — cancel quickly.
        self.runner = web.AppRunner(self.app, shutdown_timeout=0.25)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]

    async def stop(self):
        if self.runner:
            await self.runner.cleanup()


def pod(name: str, ip: str, labels: dict, phase: str = "Running",
        ready: bool = True) -> dict:
    return {"metadata": {"name": name, "labels": labels},
            "status": {"podIP": ip, "phase": phase,
                       "conditions": [{"type": "Ready",
                                       "status": "True" if ready
                                       else "False"}]}}


async def eventually(predicate, timeout=5.0, what=""):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        if predicate():
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"condition never held: {what}")
        await asyncio.sleep(0.02)


@pytest.fixture()
def fake():
    return FakeKube()


def test_kube_binding_converges_and_tracks_watches(fake):
    async def run():
        await fake.start()
        fake.upsert(POOLS, {
            "metadata": {"name": "pool"},
            "spec": {"selector": {"matchLabels": {"app": "llmd"}},
                     "targetPort": 8200, "metricsPort": 9090}})
        fake.upsert(PODS, pod("d0", "10.0.0.1", {"app": "llmd",
                                                 "llm-d.ai/role": "decode"}))
        fake.upsert(PODS, pod("d1", "10.0.0.2", {"app": "llmd"}))
        fake.upsert(PODS, pod("other", "10.9.9.9", {"app": "unrelated"}))
        fake.upsert(PODS, pod("pending", "", {"app": "llmd"},
                              phase="Pending"))
        # Running but NOT Ready (still loading weights / failing its
        # readiness probe) — must not receive traffic (pod_reconciler.go:92).
        fake.upsert(PODS, pod("warming", "10.0.0.7", {"app": "llmd"},
                              ready=False))
        fake.upsert(OBJS, {"metadata": {"name": "premium"},
                           "spec": {"priority": 10}})
        fake.upsert(REWRITES, {
            "metadata": {"name": "canary"},
            "spec": {"sourceModel": "base",
                     "targets": [{"model": "base-v2", "weight": 1}]}})

        ds = Datastore()
        client = KubeApiClient(f"http://127.0.0.1:{fake.port}")
        binding = KubeBinding(ds, client, NS, pool_name="pool")
        await binding.start()
        try:
            await binding.wait_synced()
            # Initial convergence: matching Running pods only, pool ports.
            await eventually(lambda: len(ds.endpoint_list()) == 2,
                             what="initial pod sync")
            eps = {e.metadata.address_port: e for e in ds.endpoint_list()}
            assert set(eps) == {"10.0.0.1:8200", "10.0.0.2:8200"}
            assert eps["10.0.0.1:8200"].metadata.labels["llm-d.ai/role"] == "decode"
            assert eps["10.0.0.1:8200"].metadata.metrics_port == 9090
            assert ds.objective_get("premium").priority == 10
            assert ds.rewrite_for("base") is not None

            # Watch: pod add / delete propagate; a pod turning Ready joins.
            fake.upsert(PODS, pod("warming", "10.0.0.7", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 3,
                             what="pod turning Ready via watch")
            fake.upsert(PODS, pod("warming", "10.0.0.7", {"app": "llmd"},
                                  ready=False))
            await eventually(lambda: len(ds.endpoint_list()) == 2,
                             what="pod turning unready via watch")
            fake.upsert(PODS, pod("d2", "10.0.0.3", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 3,
                             what="pod add via watch")
            fake.delete(PODS, "d1")
            await eventually(
                lambda: {e.metadata.address_port for e in ds.endpoint_list()}
                == {"10.0.0.1:8200", "10.0.0.3:8200"},
                what="pod delete via watch")

            # Objective delete propagates.
            fake.delete(OBJS, "premium")
            await eventually(lambda: ds.objective_get("premium") is None,
                             what="objective delete")

            # 410 Gone forces a relist; changes made meanwhile are found.
            # Kill the live pod stream so the informer reconnects and is
            # served the 410 (otherwise the healthy watch never ends).
            fake.force_gone.add(PODS)
            for q in list(fake.subscribers.get(PODS, [])):
                q.put_nowait(None)  # poison → handler errors → stream ends
            fake.upsert(PODS, pod("d3", "10.0.0.4", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 3,
                             what="recovery after 410 relist")
            assert not fake.force_gone, "410 was never served to a watch"

            # Pool retarget: selector + port change re-derives endpoints
            # from the cached pods without a watch restart.
            fake.upsert(POOLS, {
                "metadata": {"name": "pool"},
                "spec": {"selector": {"matchLabels": {"app": "llmd",
                                                      "llm-d.ai/role": "decode"}},
                         "targetPort": 9000}})
            await eventually(
                lambda: {e.metadata.address_port for e in ds.endpoint_list()}
                == {"10.0.0.1:9000"},
                what="pool selector/port change")
        finally:
            await binding.stop()
            await fake.stop()

    asyncio.run(run())


def test_kube_binding_watch_resumes_from_resource_version(fake):
    """A dropped connection resumes from the last seen version — no events
    lost, no duplicate full resync (history replay path)."""
    async def run():
        await fake.start()
        ds = Datastore()
        client = KubeApiClient(f"http://127.0.0.1:{fake.port}")
        binding = KubeBinding(ds, client, NS, pool_name=None)
        binding.pool.selector = {"app": "llmd"}
        binding.pool.target_port = 8000
        await binding.start()
        try:
            await binding.wait_synced()
            fake.upsert(PODS, pod("a", "10.1.0.1", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 1,
                             what="first pod")
            # Kill every live watch stream (simulates LB idle reset);
            # mutate while disconnected — the replay-from-rv path must
            # deliver the missed event.
            for qs in fake.subscribers.values():
                for q in list(qs):
                    q.put_nowait(None)  # poison → TypeError → stream ends
            fake.upsert(PODS, pod("b", "10.1.0.2", {"app": "llmd"}))
            await eventually(lambda: len(ds.endpoint_list()) == 2,
                             what="missed event recovered on resume")
        finally:
            await binding.stop()
            await fake.stop()

    asyncio.run(run())


def test_gateway_routes_to_kube_discovered_endpoints(fake):
    """Full path: gateway + kube binding against the fake API server; pods
    appear as endpoints and serve a real completion via a sim engine."""
    async def run():
        from llm_d_inference_scheduler_tpu.engine.config import EngineConfig
        from llm_d_inference_scheduler_tpu.engine.server import EngineServer
        from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

        eng = EngineServer(EngineConfig(model="tiny", backend="sim",
                                        port=18861, kv_events_port=0))
        await eng.start()
        await fake.start()
        fake.upsert(POOLS, {
            "metadata": {"name": "pool"},
            "spec": {"selector": {"matchLabels": {"app": "llmd"}},
                     "targetPort": 18861}})
        fake.upsert(PODS, pod("sim0", "127.0.0.1", {"app": "llmd"}))

        gw = build_gateway(
            "plugins: [{type: queue-scorer}]\n"
            "schedulingProfiles: [{name: default, plugins: "
            "[{pluginRef: queue-scorer}]}]\n",
            port=18860,
            kube={"api_url": f"http://127.0.0.1:{fake.port}",
                  "namespace": NS, "pool_name": "pool"})
        await gw.start()
        try:
            await gw.kube_binding.wait_synced()
            await eventually(
                lambda: len(gw.datastore.endpoint_list()) == 1,
                what="kube-discovered endpoint")

            import json as _json
            import urllib.request

            def post():
                body = _json.dumps({"model": "tiny", "prompt": "hi there",
                                    "max_tokens": 3}).encode()
                r = urllib.request.urlopen(urllib.request.Request(
                    "http://127.0.0.1:18860/v1/completions", data=body,
                    headers={"Content-Type": "application/json"}), timeout=30)
                return r.headers.get("x-gateway-destination-endpoint-served")

            dest = await asyncio.get_running_loop().run_in_executor(None, post)
            assert dest == "127.0.0.1:18861"
        finally:
            await gw.stop()
            await fake.stop()
            await eng.stop()

    asyncio.run(run())
