"""Objectives (priority tiers), model rewrites, and the token-producer."""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


def test_sheddable_objective_rejected_under_saturation():
    """InferenceObjective priority < 0 + saturated pool -> 429 shed
    (reference LegacyAdmissionController semantics)."""
    cfg = """
objectives:
  - {name: batch-tier, priority: -1}
  - {name: premium-tier, priority: 10}
saturationDetector:
  type: utilization-detector
  parameters: {queueDepthThreshold: 1}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18381}
"""

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=18381,
                                        max_batch=1, sim_decode_ms_per_token=50.0))
        await eng.start()
        gw = build_gateway(cfg, port=18380, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                hogs = [asyncio.create_task(c.post(
                    "http://127.0.0.1:18381/v1/completions",
                    json={"prompt": "x", "max_tokens": 30})) for _ in range(3)]
                await asyncio.sleep(0.3)
                r = await c.post(
                    "http://127.0.0.1:18380/v1/completions",
                    json={"model": "tiny", "prompt": "y", "max_tokens": 1},
                    headers={"x-gateway-inference-objective": "batch-tier"})
                assert r.status_code == 429
                assert "sheddable" in r.headers.get("x-removal-reason", "")
                # premium rides through (legacy admission never blocks it)
                r = await c.post(
                    "http://127.0.0.1:18380/v1/completions",
                    json={"model": "tiny", "prompt": "y", "max_tokens": 1},
                    headers={"x-gateway-inference-objective": "premium-tier"})
                assert r.status_code == 200
                await asyncio.gather(*hogs)
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_model_rewrite_applied_and_unrewritten_in_response():
    cfg = """
modelRewrites:
  - source: marketing-name
    targets:
      - {model: tiny, weight: 1}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18381}
"""

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=18381))
        await eng.start()
        gw = build_gateway(cfg, port=18380, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post("http://127.0.0.1:18380/v1/completions",
                                 json={"model": "marketing-name", "prompt": "q",
                                       "max_tokens": 2})
                assert r.status_code == 200
                # engine saw the rewritten target, response shows client name
                assert r.json()["model"] == "marketing-name"
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_token_producer_feeds_exact_prefix_hashing():
    """token-producer fetches token ids from the engine's render endpoint; the
    prefix producer then hashes token blocks instead of char heuristics."""
    cfg = """
plugins:
  - {type: token-producer}
  - {type: approx-prefix-cache-producer}
  - {type: prefix-cache-scorer}
  - {type: queue-scorer}
schedulingProfiles:
  - name: default
    plugins:
      - {pluginRef: prefix-cache-scorer, weight: 3}
      - {pluginRef: queue-scorer}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18381}
    - {address: 127.0.0.1, port: 18382}
"""

    async def body():
        engines = [EngineServer(EngineConfig(backend="sim", model="tiny", port=p))
                   for p in (18381, 18382)]
        for e in engines:
            await e.start()
        gw = build_gateway(cfg, port=18380, poll_interval=0.02)
        await gw.start()
        try:
            prompt = "shared prefix for exact token hashing " * 8
            served = []
            async with httpx.AsyncClient(timeout=30) as c:
                for _ in range(4):
                    r = await c.post("http://127.0.0.1:18380/v1/completions",
                                     json={"model": "tiny", "prompt": prompt,
                                           "max_tokens": 1})
                    served.append(r.headers["x-gateway-destination-endpoint-served"])
            assert len(set(served)) == 1  # exact-token prefix affinity sticks
            # the producer actually tokenized: its cache holds the prompt's
            # fingerprint (keys never pin prompt text verbatim)
            from llm_d_inference_scheduler_tpu.utils.hashing import (
                text_fingerprint,
            )

            producer = gw.cfg.plugins_by_name["token-producer"]
            assert ("tiny", text_fingerprint(prompt)) in producer._cache
        finally:
            await gw.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())
