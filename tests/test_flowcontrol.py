"""Flow control: fairness, ordering, capacity, TTL, saturation gating."""

import asyncio

import pytest

from llm_d_inference_scheduler_tpu.router.flowcontrol import (
    FlowControlConfig,
    FlowController,
    FlowControlRequest,
    FlowKey,
    QueueOutcome,
)


def run(coro):
    return asyncio.run(coro)


def _req(rid, flow="f", prio=0, size=1, deadline=None):
    return FlowControlRequest(request_id=rid, flow_key=FlowKey(flow, prio),
                              size_bytes=size, deadline=deadline)


def test_dispatch_when_unsaturated():
    async def body():
        fc = FlowController(FlowControlConfig(), saturation_fn=lambda: 0.0)
        await fc.start()
        try:
            outcome = await asyncio.wait_for(
                fc.enqueue_and_wait(_req("a")), timeout=5)
            assert outcome == QueueOutcome.DISPATCHED
        finally:
            await fc.stop()

    run(body())


def test_queue_blocks_under_saturation_then_drains():
    async def body():
        sat = {"v": 2.0}
        fc = FlowController(FlowControlConfig(), saturation_fn=lambda: sat["v"])
        await fc.start()
        try:
            task = asyncio.create_task(fc.enqueue_and_wait(_req("a")))
            await asyncio.sleep(0.1)
            assert not task.done()          # held while saturated
            assert fc.queued_requests == 1
            sat["v"] = 0.5                   # headroom appears
            outcome = await asyncio.wait_for(task, timeout=5)
            assert outcome == QueueOutcome.DISPATCHED
        finally:
            await fc.stop()

    run(body())


def test_strict_priority_dispatch_order():
    async def body():
        sat = {"v": 2.0}
        fc = FlowController(FlowControlConfig(), saturation_fn=lambda: sat["v"])
        await fc.start()
        try:
            order = []

            async def one(rid, prio):
                out = await fc.enqueue_and_wait(_req(rid, flow=rid, prio=prio))
                order.append(rid)
                return out

            tasks = [asyncio.create_task(one("low1", -1)),
                     asyncio.create_task(one("high1", 5)),
                     asyncio.create_task(one("mid1", 0)),
                     asyncio.create_task(one("high2", 5))]
            await asyncio.sleep(0.1)  # everything queued while saturated
            sat["v"] = 0.0
            await asyncio.gather(*tasks)
            assert set(order[:2]) == {"high1", "high2"}
            assert order[2] == "mid1" and order[3] == "low1"
        finally:
            await fc.stop()

    run(body())


def test_capacity_rejection():
    async def body():
        cfg = FlowControlConfig(band_capacity_bytes=100)
        fc = FlowController(cfg, saturation_fn=lambda: 2.0)  # nothing drains
        await fc.start()
        try:
            t1 = asyncio.create_task(fc.enqueue_and_wait(_req("a", size=80)))
            await asyncio.sleep(0.05)
            out2 = await fc.enqueue_and_wait(_req("b", size=50))
            assert out2 == QueueOutcome.REJECTED_CAPACITY
            t1.cancel()
            with pytest.raises(asyncio.CancelledError):
                await t1
        finally:
            await fc.stop()

    run(body())


def test_ttl_eviction():
    async def body():
        import time
        fc = FlowController(FlowControlConfig(default_ttl_s=0.1),
                            saturation_fn=lambda: 2.0)
        await fc.start()
        try:
            out = await asyncio.wait_for(fc.enqueue_and_wait(_req("a")), timeout=5)
            assert out == QueueOutcome.EVICTED_TTL
        finally:
            await fc.stop()

    run(body())


def test_cancellation_eviction():
    async def body():
        fc = FlowController(FlowControlConfig(), saturation_fn=lambda: 2.0)
        await fc.start()
        try:
            task = asyncio.create_task(fc.enqueue_and_wait(_req("a")))
            await asyncio.sleep(0.05)
            task.cancel()
            with pytest.raises(asyncio.CancelledError):
                await task
            assert fc.queued_requests == 0  # dropped from the queue
        finally:
            await fc.stop()

    run(body())


def test_round_robin_fairness_across_flows():
    async def body():
        sat = {"v": 2.0}
        cfg = FlowControlConfig(fairness="round-robin-fairness-policy")
        fc = FlowController(cfg, saturation_fn=lambda: sat["v"])
        await fc.start()
        try:
            order = []

            async def one(rid, flow):
                await fc.enqueue_and_wait(_req(rid, flow=flow))
                order.append((rid, flow))

            tasks = [asyncio.create_task(one(f"{f}{i}", f))
                     for i in range(2) for f in ("A", "B")]
            await asyncio.sleep(0.1)
            sat["v"] = 0.0
            await asyncio.gather(*tasks)
            flows = [f for _, f in order]
            # alternation: no flow serves twice before the other gets a turn
            assert flows[0] != flows[1] and flows[2] != flows[3], flows
        finally:
            await fc.stop()

    run(body())


def test_edf_ordering_within_flow():
    async def body():
        import time
        sat = {"v": 2.0}
        cfg = FlowControlConfig(ordering="edf-ordering-policy", default_ttl_s=60)
        fc = FlowController(cfg, saturation_fn=lambda: sat["v"])
        await fc.start()
        try:
            order = []
            now = time.monotonic()

            async def one(rid, deadline):
                await fc.enqueue_and_wait(_req(rid, deadline=deadline))
                order.append(rid)

            tasks = [asyncio.create_task(one("late", now + 50)),
                     asyncio.create_task(one("soon", now + 5)),
                     asyncio.create_task(one("mid", now + 20))]
            await asyncio.sleep(0.1)
            sat["v"] = 0.0
            await asyncio.gather(*tasks)
            assert order == ["soon", "mid", "late"]
        finally:
            await fc.stop()

    run(body())


def test_gateway_flow_control_gate_sheds_on_saturation():
    """featureGates.flowControl: requests queue while the pool is saturated and
    time out with 429 + x-removal-reason."""
    import httpx
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    cfg = """
featureGates: {flowControl: true}
flowControl: {defaultTTLSeconds: 0.3}
saturationDetector:
  type: utilization-detector
  parameters: {queueDepthThreshold: 1}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18371}
"""

    async def body():
        # slow engine so its waiting queue builds up
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=18371,
                                        max_batch=1, sim_decode_ms_per_token=50.0))
        await eng.start()
        gw = build_gateway(cfg, port=18370, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # saturate: 4 slow requests directly at the engine
                hogs = [asyncio.create_task(c.post(
                    "http://127.0.0.1:18371/v1/completions",
                    json={"prompt": "x", "max_tokens": 40})) for _ in range(4)]
                await asyncio.sleep(0.3)  # collectors see queue depth > threshold
                r = await c.post("http://127.0.0.1:18370/v1/completions",
                                 json={"model": "tiny", "prompt": "y",
                                       "max_tokens": 1})
                assert r.status_code == 429
                assert "ttl" in r.headers.get("x-removal-reason", "").lower()
                await asyncio.gather(*hogs)
        finally:
            await gw.stop()
            await eng.stop()

    run(body())


def test_per_flow_usage_limit():
    """static-usage-limit-policy: per-flow queued caps reject the overflowing
    flow while other flows still enqueue."""
    async def body():
        cfg = FlowControlConfig(per_flow_max_requests=2)
        fc = FlowController(cfg, saturation_fn=lambda: 2.0)  # nothing drains
        await fc.start()
        try:
            tasks = [asyncio.create_task(fc.enqueue_and_wait(_req(f"a{i}", flow="A")))
                     for i in range(2)]
            await asyncio.sleep(0.05)
            out = await fc.enqueue_and_wait(_req("a2", flow="A"))
            assert out == QueueOutcome.REJECTED_CAPACITY  # flow A at its cap
            other = asyncio.create_task(fc.enqueue_and_wait(_req("b0", flow="B")))
            await asyncio.sleep(0.05)
            assert not other.done()  # flow B enqueued fine
            for t in tasks + [other]:
                t.cancel()
            import contextlib
            for t in tasks + [other]:
                with contextlib.suppress(asyncio.CancelledError):
                    await t
        finally:
            await fc.stop()

    run(body())


def test_ttl_eviction_behind_long_ttl_head():
    """Full-queue sweep: an expired item sitting BEHIND a long-TTL head must
    be evicted on schedule, not when it surfaces (VERDICT r1 weak #3)."""
    async def body():
        import time

        fc = FlowController(FlowControlConfig(default_ttl_s=60),
                            saturation_fn=lambda: 2.0)  # saturated: no drain
        await fc.start()
        try:
            now = time.monotonic()
            head = asyncio.create_task(
                fc.enqueue_and_wait(_req("head", deadline=now + 60)))
            await asyncio.sleep(0.01)
            short = asyncio.create_task(
                fc.enqueue_and_wait(_req("short", deadline=now + 0.15)))
            outcome = await asyncio.wait_for(short, timeout=2)
            assert outcome == QueueOutcome.EVICTED_TTL
            assert not head.done()  # the long-TTL head is untouched
            assert fc.queued_requests == 1
            head.cancel()
            with pytest.raises(asyncio.CancelledError):
                await head
        finally:
            await fc.stop()

    run(body())


def test_capacity_nudge_wakes_saturated_shard():
    """notify_capacity interrupts the saturated backoff sleep: dispatch
    happens promptly after the nudge flips saturation, even though the
    backoff had grown far beyond the poll interval."""
    async def body():
        sat = {"v": 2.0}
        fc = FlowController(FlowControlConfig(),
                            saturation_fn=lambda: sat["v"])
        await fc.start()
        try:
            import time

            task = asyncio.create_task(fc.enqueue_and_wait(_req("a")))
            await asyncio.sleep(0.6)  # backoff grows to its 250ms ceiling
            assert not task.done()
            sat["v"] = 0.0
            t0 = time.monotonic()
            fc.notify_capacity()
            outcome = await asyncio.wait_for(task, timeout=2)
            elapsed = time.monotonic() - t0
            assert outcome == QueueOutcome.DISPATCHED
            assert elapsed < 0.2, f"nudge did not wake shard ({elapsed:.3f}s)"
        finally:
            await fc.stop()

    run(body())


def test_idle_flow_gc():
    """Idle FlowKeys disappear after the GC window (reference registry flow
    GC); an active flow's queue state survives."""
    async def body():
        fc = FlowController(FlowControlConfig(flow_gc_s=0.2),
                            saturation_fn=lambda: 0.0)
        await fc.start()
        try:
            await asyncio.wait_for(
                fc.enqueue_and_wait(_req("a", flow="ephemeral")), timeout=5)
            shard = fc.shards[0]
            assert FlowKey("ephemeral", 0) in shard.queues
            # Idle long enough for GC (idle wake period is flow_gc_s/4,
            # floored at 0.5s — nudge the shard to run a sweep cycle).
            for _ in range(8):
                await asyncio.sleep(0.1)
                shard.notify_capacity()
            assert FlowKey("ephemeral", 0) not in shard.queues
            assert FlowKey("ephemeral", 0) not in shard.last_active
        finally:
            await fc.stop()

    run(body())


def test_flowcontrol_bench_scenarios_smoke():
    """Pin the three reference bench scenarios (perf matrix point, mass
    cancellation, topology churn — benchmark_test.go:38-225) at smoke scale
    so the recorded benchmarks/BENCH_flowcontrol.json stays reproducible."""
    import asyncio
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from scripts.flowcontrol_bench import (
        run_mass_cancellation,
        run_matrix_point,
        run_topology_churn,
    )

    pt = asyncio.run(run_matrix_point(limit=8, priorities=2, flows=10,
                                      concurrency=32, n_requests=200))
    assert pt["dispatched"] + pt["rejected"] <= 200
    assert pt["dispatched"] > 0

    mass = asyncio.run(run_mass_cancellation(n=200, cancel_frac=0.5))
    assert mass["evicted"] == 100
    assert mass["survivors_dispatched"] == 100

    churn = asyncio.run(run_topology_churn(n=200, concurrency=32))
    assert churn["dispatched"] == 200
    assert churn["flows_live_at_end"] == 200  # each request registered a flow
