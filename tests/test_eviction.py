"""Mid-flight request eviction: ordering policy, admission retry, gateway 429."""

import asyncio

import httpx
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.flowcontrol.eviction import RequestEvictor
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


def test_evictor_priority_then_time_order():
    ev = RequestEvictor()
    cancelled = []
    ev.register("old-low", -2, lambda: cancelled.append("old-low"))
    ev.register("new-low", -2, lambda: cancelled.append("new-low"))
    mid_key = ev.register("mid", -1, lambda: cancelled.append("mid"))
    ev.register("normal", 0, lambda: cancelled.append("normal"))

    assert ev.evict_n(2) == ["old-low", "new-low"]
    assert cancelled == ["old-low", "new-low"]  # lowest priority, oldest first
    assert ev.evict_n(5) == ["mid"]  # only "mid" remains sheddable
    assert cancelled == ["old-low", "new-low", "mid"]
    assert "normal" not in cancelled  # non-sheddable never evicted
    assert ev.was_evicted(mid_key)


def test_evictor_duplicate_request_ids_tracked_independently():
    """Client-supplied ids can collide; each registration stays evictable."""
    ev = RequestEvictor()
    cancelled = []
    k1 = ev.register("dup", -1, lambda: cancelled.append("first"))
    k2 = ev.register("dup", -1, lambda: cancelled.append("second"))
    assert k1 != k2
    ev.deregister(k1)  # first finishes; second must remain tracked
    assert ev.inflight_count == 1
    assert ev.evict_n(1) == ["dup"]
    assert cancelled == ["second"]
    assert ev.was_evicted(k2) and not ev.was_evicted(k1)


def test_gateway_evicts_inflight_sheddable_with_429():
    cfg = """
objectives:
  - {name: batch-tier, priority: -1}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 18386}
"""

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=18386,
                                        max_batch=2, sim_decode_ms_per_token=50.0))
        await eng.start()
        gw = build_gateway(cfg, port=18385, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                sheddable = asyncio.create_task(c.post(
                    "http://127.0.0.1:18385/v1/completions",
                    json={"model": "tiny", "prompt": "long", "max_tokens": 60},
                    headers={"x-gateway-inference-objective": "batch-tier"}))
                for _ in range(100):
                    await asyncio.sleep(0.02)
                    if gw.evictor.inflight_count == 1:
                        break
                assert gw.evictor.inflight_count == 1

                assert len(gw.evictor.evict_n(1)) == 1
                r = await sheddable
                assert r.status_code == 429
                assert "evicted" in r.headers.get("x-removal-reason", "")
                assert gw.evictor.inflight_count == 0
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_admission_capacity_retry_after_eviction():
    """Non-sheddable request rejected on capacity triggers evict_n + a retry."""
    from llm_d_inference_scheduler_tpu.router.flowcontrol import (
        FlowControlAdmissionController, FlowControlConfig, FlowController)
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        InferenceRequest, InferenceRequestBody, Objectives)

    async def body():
        sat = {"v": 2.0}
        fc = FlowController(FlowControlConfig(max_global_requests=1,
                                              default_ttl_s=5.0),
                            saturation_fn=lambda: sat["v"])
        await fc.start()
        evictor = RequestEvictor()
        admission = FlowControlAdmissionController(fc, evictor=evictor)

        def req(rid, prio):
            return InferenceRequest(
                request_id=rid, target_model="m",
                body=InferenceRequestBody(completions={"prompt": "x"}),
                objectives=Objectives(priority=prio), request_size_bytes=10)

        from llm_d_inference_scheduler_tpu.router.requestcontrol.admission import (
            AdmissionError)

        try:
            # Fill the single queue slot with a sheddable request.
            filler = asyncio.create_task(admission.admit(None, req("filler", -1), []))
            await asyncio.sleep(0.05)
            victim_key = evictor.register("victim", -1, lambda: None)  # sheddable in-flight

            # Non-sheddable arrival: capacity-rejected -> sheds the QUEUED
            # filler (frees the slot), evicts the in-flight victim, and the
            # retry enqueues successfully.
            high = asyncio.create_task(admission.admit(None, req("high", 5), []))
            await asyncio.sleep(0.1)
            assert evictor.was_evicted(victim_key)
            with pytest.raises(AdmissionError) as exc:
                await filler  # shed from the queue -> 429
            assert exc.value.code == 429
            sat["v"] = 0.0  # headroom: the retried high-priority dispatches
            await asyncio.wait_for(high, timeout=5)  # no exception = admitted
        finally:
            await fc.stop()

    asyncio.run(body())
