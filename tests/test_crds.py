"""CRD schema definitions (deploy/crds/) stay honest.

The reference ships controller-gen CRDs (config/crd/bases/); ours are
hand-written against exactly the fields router/kube.py's KubeBinding reads.
These tests (a) pin group/version/plural to the binding's watch paths,
(b) validate realistic CR fixtures against the openAPIV3Schema with a
minimal structural validator, and (c) reject malformed CRs, so a schema or
binding drift fails loudly.
"""

import glob
import os

import yaml

from llm_d_inference_scheduler_tpu.router.kube import CRD_GROUP, CRD_VERSION

CRD_DIR = os.path.join(os.path.dirname(__file__), "..", "deploy", "crds")


def load_crds() -> dict[str, dict]:
    out = {}
    for path in glob.glob(os.path.join(CRD_DIR, "*.yaml")):
        with open(path) as f:
            doc = yaml.safe_load(f)
        assert doc["kind"] == "CustomResourceDefinition"
        out[doc["spec"]["names"]["plural"]] = doc
    return out


def validate(schema: dict, value, path="$") -> list[str]:
    """Minimal openAPIV3Schema structural validator: type, required,
    properties, additionalProperties, items, enum, bounds, lengths."""
    errs: list[str] = []
    t = schema.get("type")
    type_map = {"object": dict, "array": list, "string": str,
                "integer": int, "number": (int, float), "boolean": bool}
    if t and not isinstance(value, type_map[t]):
        return [f"{path}: expected {t}, got {type(value).__name__}"]
    if t == "integer" and isinstance(value, bool):
        return [f"{path}: expected integer, got bool"]
    if "enum" in schema and value not in schema["enum"]:
        errs.append(f"{path}: {value!r} not in enum")
    if t == "object":
        for req in schema.get("required", []):
            if req not in value:
                errs.append(f"{path}.{req}: required")
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties")
        for k, v in value.items():
            if k in props:
                errs += validate(props[k], v, f"{path}.{k}")
            elif isinstance(addl, dict):
                errs += validate(addl, v, f"{path}.{k}")
    if t == "array":
        if "minItems" in schema and len(value) < schema["minItems"]:
            errs.append(f"{path}: fewer than {schema['minItems']} items")
        for i, v in enumerate(value):
            errs += validate(schema.get("items", {}), v, f"{path}[{i}]")
    if t == "integer" and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            errs.append(f"{path}: {value} < minimum")
        if "maximum" in schema and value > schema["maximum"]:
            errs.append(f"{path}: {value} > maximum")
    if t == "string":
        if "minLength" in schema and len(value) < schema["minLength"]:
            errs.append(f"{path}: shorter than minLength")
        if "maxLength" in schema and len(value) > schema["maxLength"]:
            errs.append(f"{path}: longer than maxLength")
    return errs


def crd_schema(crd: dict) -> dict:
    versions = crd["spec"]["versions"]
    assert len(versions) == 1 and versions[0]["storage"]
    return versions[0]["schema"]["openAPIV3Schema"]


def test_crds_match_kube_binding_watch_paths():
    crds = load_crds()
    # KubeBinding watches these collections under /apis/<group>/<version>/
    # (router/kube.py:322-335); the CRDs must declare the same coordinates.
    assert set(crds) == {"inferencepools", "inferenceobjectives",
                        "inferencemodelrewrites"}
    for plural, crd in crds.items():
        assert crd["spec"]["group"] == CRD_GROUP
        assert crd["spec"]["versions"][0]["name"] == CRD_VERSION
        assert crd["metadata"]["name"] == f"{plural}.{CRD_GROUP}"
        assert crd["spec"]["scope"] == "Namespaced"


def test_valid_fixtures_pass():
    crds = load_crds()
    fixtures = {
        "inferencepools": {
            "apiVersion": f"{CRD_GROUP}/{CRD_VERSION}",
            "kind": "InferencePool",
            "metadata": {"name": "pool"},
            "spec": {"selector": {"matchLabels": {"app": "engine"}},
                     "targetPort": 8200, "metricsPort": 8201},
        },
        "inferenceobjectives": {
            "apiVersion": f"{CRD_GROUP}/{CRD_VERSION}",
            "kind": "InferenceObjective",
            "metadata": {"name": "batch"},
            "spec": {"priority": -1, "poolRef": {"name": "pool"}},
        },
        "inferencemodelrewrites": {
            "apiVersion": f"{CRD_GROUP}/{CRD_VERSION}",
            "kind": "InferenceModelRewrite",
            "metadata": {"name": "canary"},
            "spec": {"sourceModel": "llama3", "targets": [
                {"model": "llama3-prod", "weight": 9},
                {"model": "llama3-canary", "weight": 1}]},
        },
    }
    for plural, obj in fixtures.items():
        errs = validate(crd_schema(crds[plural]), obj)
        assert not errs, f"{plural}: {errs}"


def test_malformed_fixtures_fail():
    crds = load_crds()
    bad = {
        # missing required spec.selector
        "inferencepools": {"spec": {"targetPort": 8200}},
        # priority must be an integer
        "inferenceobjectives": {"spec": {"priority": "high"}},
        # targets requires >= 1 item with model set
        "inferencemodelrewrites": {"spec": {"sourceModel": "m", "targets": []}},
    }
    for plural, obj in bad.items():
        errs = validate(crd_schema(crds[plural]), obj)
        assert errs, f"{plural}: malformed object passed validation"


def test_pool_port_bounds():
    crds = load_crds()
    obj = {"spec": {"selector": {}, "targetPort": 70000}}
    assert validate(crd_schema(crds["inferencepools"]), obj)
