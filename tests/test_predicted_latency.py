"""Predicted-latency subsystem: online ridge, scorer/filter semantics,
admitters, and the hermetic SLO-routing e2e (VERDICT r1 item 4: an SLO-aware
profile routes around a slow endpoint with scripted latencies)."""

import asyncio

import httpx
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    Objectives,
)
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    LATENCY_ATTRIBUTE_KEY,
    LatencyPredictionInfo,
)
from llm_d_inference_scheduler_tpu.router.plugins.latency import (
    LatencyScorer,
    SloHeadroomTierFilter,
)
from llm_d_inference_scheduler_tpu.router.requestcontrol.admitters import (
    LatencySloAdmitter,
    ProbabilisticAdmitter,
)
from llm_d_inference_scheduler_tpu.router.requestcontrol.predicted_latency import (
    OnlineRidge,
)


def _ep(port, *, info=None, kv=0.5, running=1, queue=0) -> Endpoint:
    ep = Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1", port=port))
    ep.metrics.kv_cache_usage_percent = kv
    ep.metrics.running_requests_size = running
    ep.metrics.waiting_queue_size = queue
    if info is not None:
        ep.attributes.put(LATENCY_ATTRIBUTE_KEY, info)
    return ep


def _req(priority=0, headers=None) -> InferenceRequest:
    return InferenceRequest(
        request_id="r", target_model="m",
        body=InferenceRequestBody(completions={"prompt": "x"}),
        headers=headers or {}, objectives=Objectives(priority=priority))


def _info(ttft_h, tpot_h, dispatched=1) -> LatencyPredictionInfo:
    return LatencyPredictionInfo(
        ttft_ms=10, tpot_ms=1,
        ttft_headroom_ms=ttft_h, tpot_headroom_ms=tpot_h,
        ttft_valid=ttft_h >= 0, tpot_valid=tpot_h >= 0,
        dispatched=dispatched)


# ---- OnlineRidge ------------------------------------------------------


def test_online_ridge_learns_linear_relation():
    m = OnlineRidge(2, alpha=1e-3)
    for i in range(200):
        x = float(i % 10)
        m.update([1.0, x], 5.0 + 3.0 * x)
    assert abs(m.predict([1.0, 4.0]) - 17.0) < 0.5
    assert abs(m.predict([1.0, 20.0]) - 65.0) < 2.0  # extrapolates


def test_online_ridge_decay_tracks_shift():
    m = OnlineRidge(1, alpha=1e-3, decay=0.9)
    for _ in range(100):
        m.update([1.0], 100.0)
    for _ in range(100):
        m.update([1.0], 10.0)  # regime change
    assert m.predict([1.0]) < 15.0


# ---- latency-scorer ----------------------------------------------------


def test_scorer_positive_beats_negative():
    good, bad = _ep(1, info=_info(50, 5)), _ep(2, info=_info(-50, 5))
    scores = LatencyScorer().score(None, None, _req(), [good, bad])
    assert scores["127.0.0.1:1"] > scores["127.0.0.1:2"] == 0.0


def test_scorer_least_prefers_closest_to_slo():
    near, far = _ep(1, info=_info(10, 10)), _ep(2, info=_info(500, 500))
    scores = LatencyScorer().score(None, None, _req(), [near, far])
    assert scores["127.0.0.1:1"] > scores["127.0.0.1:2"]


def test_scorer_most_prefers_max_margin():
    s = LatencyScorer()
    s.configure({"headroomStrategy": "most"}, None)
    near, far = _ep(1, info=_info(10, 10)), _ep(2, info=_info(500, 500))
    scores = s.score(None, None, _req(), [near, far])
    assert scores["127.0.0.1:2"] > scores["127.0.0.1:1"]


def test_scorer_all_negative_prefers_idle():
    busy = _ep(1, info=_info(-10, -1, dispatched=3))
    idle = _ep(2, info=_info(-400, -9, dispatched=0))
    scores = LatencyScorer().score(None, None, _req(), [busy, idle])
    assert scores["127.0.0.1:2"] > scores["127.0.0.1:1"]


def test_scorer_deficit_buckets_rank_tpot_only_first():
    only_tpot = _ep(1, info=_info(5, -1, dispatched=2))   # TTFT met
    both_neg = _ep(2, info=_info(-5, -1, dispatched=2))
    scores = LatencyScorer().score(None, None, _req(), [only_tpot, both_neg])
    assert scores["127.0.0.1:1"] > scores["127.0.0.1:2"]


def test_scorer_composite_fallback_without_predictions():
    cold = _ep(1, kv=0.1, queue=0)
    hot = _ep(2, kv=0.9, queue=8)
    scores = LatencyScorer().score(None, None, _req(), [cold, hot])
    assert scores["127.0.0.1:1"] > scores["127.0.0.1:2"]


# ---- slo-headroom-tier-filter -----------------------------------------


def test_tier_filter_keeps_positive_tier():
    f = SloHeadroomTierFilter()
    f._rng.random = lambda: 0.99  # never explore
    pos, neg = _ep(1, info=_info(5, 5)), _ep(2, info=_info(-5, 5))
    kept = f.filter(None, None, _req(), [pos, neg])
    assert kept == [pos]


def test_tier_filter_epsilon_explores_negative():
    f = SloHeadroomTierFilter()
    f._rng.random = lambda: 0.0  # always explore
    pos, neg = _ep(1, info=_info(5, 5)), _ep(2, info=_info(-5, 5))
    assert f.filter(None, None, _req(), [pos, neg]) == [neg]


def test_tier_filter_passthrough_without_predictions():
    eps = [_ep(1), _ep(2)]
    assert SloHeadroomTierFilter().filter(None, None, _req(), eps) == eps


# ---- admitters ---------------------------------------------------------


def test_latency_slo_admitter_rejects_hopeless_sheddable():
    async def body():
        adm = LatencySloAdmitter()
        hdrs = {"x-slo-ttft-ms": "100"}
        # All endpoints: invalid prediction, busy, warm.
        eps = [_ep(1, info=_info(-50, -5), kv=0.5, running=2),
               _ep(2, info=_info(-80, -9), kv=0.6, running=1)]
        ok, reason = await adm.admit(None, _req(-1, hdrs), eps)
        assert not ok and "SLO" in reason

        # Non-sheddable always admitted.
        ok, _ = await adm.admit(None, _req(0, hdrs), eps)
        assert ok
        # Idle endpoint → admit.
        eps[0].metrics.running_requests_size = 0
        ok, _ = await adm.admit(None, _req(-1, hdrs), eps)
        assert ok
        # No SLO header → admit.
        eps[0].metrics.running_requests_size = 2
        ok, _ = await adm.admit(None, _req(-1, {}), eps)
        assert ok
        # No predictions → fail open.
        bare = [_ep(1, kv=0.5, running=2)]
        ok, _ = await adm.admit(None, _req(-1, hdrs), bare)
        assert ok

    asyncio.run(body())


def test_probabilistic_admitter_sheds_under_saturation():
    async def body():
        adm = ProbabilisticAdmitter()
        adm._rng.random = lambda: 0.5
        saturated = [_ep(1, kv=0.95, queue=10)]
        relaxed = [_ep(1, kv=0.05, queue=0)]
        ok, reason = await adm.admit(None, _req(-1), saturated)
        assert not ok and "saturation" in reason
        ok, _ = await adm.admit(None, _req(-1), relaxed)
        assert ok
        ok, _ = await adm.admit(None, _req(5), saturated)  # non-sheddable
        assert ok

    asyncio.run(body())


def test_probabilistic_admitter_rejects_bad_params():
    with pytest.raises(ValueError):
        ProbabilisticAdmitter().configure({"power": 0}, None)


# ---- hermetic e2e: route around the slow endpoint ----------------------

FAST, SLOW, GW = 18621, 18622, 18620

SLO_CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {FAST}}}
    - {{address: 127.0.0.1, port: {SLOW}}}
plugins:
  - {{type: predicted-latency-producer}}
  - {{type: slo-headroom-tier-filter}}
  - {{type: latency-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: slo-headroom-tier-filter}}
      - {{pluginRef: latency-scorer}}
"""


def test_slo_routing_steers_around_slow_endpoint():
    async def body():
        fast = EngineServer(EngineConfig(backend="sim", model="tiny", port=FAST,
                                         sim_decode_ms_per_token=1.0))
        slow = EngineServer(EngineConfig(backend="sim", model="tiny", port=SLOW,
                                         sim_decode_ms_per_token=40.0))
        await fast.start()
        await slow.start()
        gw = build_gateway(SLO_CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # Train both per-endpoint models via the subset-hint header
                # (scripted latencies: fast e2e ≈ 10ms, slow ≈ 320ms).
                for port in (FAST, SLOW):
                    for _ in range(6):
                        r = await c.post(
                            f"http://127.0.0.1:{GW}/v1/completions",
                            json={"model": "tiny", "prompt": "warm",
                                  "max_tokens": 8},
                            headers={"x-gateway-destination-endpoint-subset":
                                     f"127.0.0.1:{port}"})
                        assert r.status_code == 200

                # SLO 150ms: fast meets, slow violates → positive tier routing.
                served = []
                for _ in range(10):
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": "hello", "max_tokens": 8},
                        headers={"x-slo-ttft-ms": "150"})
                    assert r.status_code == 200
                    served.append(
                        r.headers["x-gateway-destination-endpoint-served"])
                fast_hits = sum(1 for s in served if s == f"127.0.0.1:{FAST}")
                assert fast_hits >= 9, served
        finally:
            await gw.stop()
            await slow.stop()
            await fast.stop()

    asyncio.run(body())


def test_scorer_all_negative_prefers_least_violating():
    """Among busy negative-headroom endpoints, the one CLOSEST to the SLO
    boundary (least negative) must win — not the deepest violator."""
    deep = _ep(1, info=_info(-400, -5, dispatched=2))
    near = _ep(2, info=_info(-10, -5, dispatched=2))
    scores = LatencyScorer().score(None, None, _req(), [deep, near])
    assert scores["127.0.0.1:2"] > scores["127.0.0.1:1"]


# ---- predictor calibration through the SLO ledger -----------------------


def _predictor_error_count() -> float:
    from llm_d_inference_scheduler_tpu.router.metrics import REGISTRY

    total = 0.0
    for m in REGISTRY.collect():
        if m.name == "router_predictor_error_ms":
            total += sum(s.value for s in m.samples
                         if s.name.endswith("_count"))
    return total


def test_trained_predictor_produces_bounded_error_observations():
    """Trained-then-served requests must close the predict→observe loop:
    each served request lands a ``router_predictor_error_ms`` observation,
    and the ledger's TTFT calibration (MAE) is bounded — the ridge trained
    on the very latencies the sim scripts, so triple-digit-second error
    would mean the ledger compares mismatched quantities."""
    CAL_FAST, CAL_GW = 18625, 18626

    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {CAL_FAST}}}
plugins:
  - {{type: predicted-latency-producer}}
  - {{type: latency-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: latency-scorer}}
"""

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=CAL_FAST,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(cfg, port=CAL_GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # Train past MIN_SAMPLES, then serve with predictions live.
                for _ in range(6):
                    r = await c.post(
                        f"http://127.0.0.1:{CAL_GW}/v1/completions",
                        json={"model": "tiny", "prompt": "warm",
                              "max_tokens": 8})
                    assert r.status_code == 200
                before = _predictor_error_count()
                for _ in range(8):
                    r = await c.post(
                        f"http://127.0.0.1:{CAL_GW}/v1/completions",
                        json={"model": "tiny", "prompt": "serve",
                              "max_tokens": 8},
                        headers={"x-slo-ttft-ms": "60000"})
                    assert r.status_code == 200
                # Every trained-then-served request observed an error.
                assert _predictor_error_count() - before >= 8

                slo = (await c.get(
                    f"http://127.0.0.1:{CAL_GW}/debug/slo")).json()
                ttft = slo["totals"]["predictor"]["ttft"]
                assert ttft["n"] >= 8
                # Bounded: sim e2e is ~10ms; allow generous shared-box slack.
                assert 0 <= ttft["mae_ms"] < 1000
                assert abs(ttft["mean_signed_ms"]) <= ttft["mae_ms"] + 1e-9
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())
