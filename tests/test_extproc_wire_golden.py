"""Golden-byte fixtures pinning the hand-rolled ext-proc v3 codec.

VERDICT r2 weak #3: the codec was only validated against its own decoder —
a field-number or wire-type slip would survive that test shape. These
fixtures are hand-assembled byte-by-byte from the published
envoy/service/ext_proc/v3/external_processor.proto schema (field numbers
commented inline), NOT generated with the production helpers, so any
regression in tag/field encoding fails loudly against literal bytes.

Schema cross-check (published proto):
  ProcessingRequest  oneof: request_headers=2 response_headers=3
                     request_body=4 response_body=5 request_trailers=6
  ProcessingResponse oneof: request_headers=1 response_headers=2
                     request_body=3 response_body=4 request_trailers=5
                     immediate_response=7; dynamic_metadata=8
  CommonResponse: status=1 header_mutation=2 body_mutation=3 trailers=4
                  clear_route_cache=5
  BodyMutation: body=1 clear_body=2 streamed_response=3
  StreamedBodyResponse: body=1 end_of_stream=2
  HeaderMutation: set_headers=1 remove_headers=2
  HeaderValueOption: header=1;  HeaderValue: key=1 value=2 raw_value=3
  ImmediateResponse: status=1{code=1} headers=2 body=3
  HttpHeaders: headers=1{HeaderMap: headers=1} end_of_stream=3
  HttpBody: body=1 end_of_stream=2
"""

from llm_d_inference_scheduler_tpu.router.handlers.extproc import (
    CommonResponse,
    HeaderMutation,
    ImmediateResponse,
    RequestBody,
    RequestHeaders,
)
from llm_d_inference_scheduler_tpu.router.handlers.extproc_grpc import (
    decode_processing_request,
    encode_processing_response,
    encode_processing_responses,
)


def test_golden_streamed_body_response():
    """BodyResponse with StreamedBodyResponse{body="hi", end_of_stream}."""
    got = encode_processing_response(
        CommonResponse(phase="request_body", body=b"hi", body_eos=True))
    golden = (
        b"\x1a\x0c"              # ProcessingResponse.request_body = 3, LD, 12
        b"\x0a\x0a"              # BodyResponse.response = 1 (CommonResponse)
        b"\x1a\x08"              # CommonResponse.body_mutation = 3
        b"\x1a\x06"              # BodyMutation.streamed_response = 3
        b"\x0a\x02hi"            # StreamedBodyResponse.body = 1
        b"\x10\x01"              # StreamedBodyResponse.end_of_stream = 2
    )
    assert got == golden


def test_golden_headers_response_with_mutation_and_route_clear():
    got = encode_processing_response(CommonResponse(
        phase="request_headers",
        header_mutation=HeaderMutation(set_headers={"x-d": "ep"}),
        clear_route_cache=True))
    golden = (
        b"\x0a\x13"              # ProcessingResponse.request_headers = 1, 19
        b"\x0a\x11"              # HeadersResponse.response = 1, len 17
        b"\x12\x0d"              # CommonResponse.header_mutation = 2, len 13
        b"\x0a\x0b"              # HeaderMutation.set_headers = 1 (HVO), 11
        b"\x0a\x09"              # HeaderValueOption.header = 1, len 9
        b"\x0a\x03x-d"           # HeaderValue.key = 1
        b"\x1a\x02ep"            # HeaderValue.raw_value = 3
        b"\x28\x01"              # CommonResponse.clear_route_cache = 5
    )
    assert got == golden


def test_golden_immediate_response_429():
    got = encode_processing_response(ImmediateResponse(
        status=429, headers={"x-removal-reason": "evicted"}, body=b"{}"))
    golden = (
        b"\x3a\x2a"              # ProcessingResponse.immediate_response = 7
        b"\x0a\x03\x08\xad\x03"  # ImmediateResponse.status=1 {code=1: 429}
        b"\x12\x1f"              # ImmediateResponse.headers = 2, len 31
        b"\x0a\x1d"              # HeaderMutation.set_headers = 1, len 29
        b"\x0a\x1b"              # HeaderValueOption.header = 1, len 27
        b"\x0a\x10x-removal-reason"   # key = 1, len 16
        b"\x1a\x07evicted"       # raw_value = 3, len 7
        b"\x1a\x02{}"            # ImmediateResponse.body = 3
    )
    assert got == golden


def test_golden_decode_request_headers():
    frame = (
        b"\x12\x14"              # ProcessingRequest.request_headers = 2, 20
        b"\x0a\x10"              # HttpHeaders.headers = 1 (HeaderMap), 16
        b"\x0a\x0e"              # HeaderMap.headers = 1 (HeaderValue), 14
        b"\x0a\x05:path"         # HeaderValue.key = 1
        b"\x1a\x05/v1/x"         # HeaderValue.raw_value = 3
        b"\x18\x01"              # HttpHeaders.end_of_stream = 3
    )
    msg = decode_processing_request(frame)
    assert isinstance(msg, RequestHeaders)
    assert msg.headers == {":path": "/v1/x"}
    assert msg.end_of_stream is True
    assert msg.path == "/v1/x"


def test_golden_decode_request_body():
    frame = (
        b"\x22\x07"              # ProcessingRequest.request_body = 4, len 7
        b"\x0a\x03abc"           # HttpBody.body = 1
        b"\x10\x01"              # HttpBody.end_of_stream = 2
    )
    msg = decode_processing_request(frame)
    assert isinstance(msg, RequestBody)
    assert msg.chunk == b"abc" and msg.end_of_stream is True


def test_chunk_splitting_math():
    """Multi-frame split: sizes, eos placement, payload reassembly."""
    from llm_d_inference_scheduler_tpu.router.handlers.extproc_grpc import (
        BODY_BYTE_LIMIT,
    )

    body = bytes(range(256)) * 600   # 153600 bytes → 3 chunks
    frames = encode_processing_responses(CommonResponse(
        phase="response_body", body=body, body_eos=True))
    assert len(frames) == 3
    # Decode each frame independently with local (test-side) field walking.
    chunks, eoses = [], []
    for frame in frames:
        from llm_d_inference_scheduler_tpu.router.handlers.vllmgrpc import (
            _fields,
        )

        for f, w, v in _fields(frame):
            assert f == 4            # response_body
            for f1, w1, v1 in _fields(v):
                assert f1 == 1       # CommonResponse
                for f2, w2, v2 in _fields(v1):
                    assert f2 == 3   # body_mutation
                    for f3, w3, v3 in _fields(v2):
                        assert f3 == 3   # streamed_response
                        chunk, eos = b"", False
                        for f4, w4, v4 in _fields(v3):
                            if f4 == 1:
                                chunk = v4
                            elif f4 == 2:
                                eos = bool(v4)
                        chunks.append(chunk)
                        eoses.append(eos)
    assert all(len(c) <= BODY_BYTE_LIMIT for c in chunks)
    assert b"".join(chunks) == body
    assert eoses == [False, False, True]
