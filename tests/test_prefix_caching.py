"""Automatic prefix caching: block reuse correctness and eviction."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
from llm_d_inference_scheduler_tpu.engine.blocks import PrefixCachingAllocator
from llm_d_inference_scheduler_tpu.models import TINY, llama


def test_prefill_with_prefix_matches_full_forward():
    """Prefill of [prefix in cache] + suffix == full-forward logits."""
    cfg = TINY
    block = cfg.kv_block_size
    prompt_len = 3 * block + 5  # 2 cacheable blocks + partial
    prefix_blocks = 2
    prefix_len = prefix_blocks * block

    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.key(1), (1, prompt_len), 0,
                                cfg.vocab_size)
    ref_logits, _ = llama.forward(params, cfg, tokens)

    max_blocks = 8
    n_blocks = 1 + max_blocks
    kshape = (cfg.n_layers, n_blocks, block, cfg.n_kv_heads, cfg.head_dim)
    k_pages = jnp.zeros(kshape, jnp.float32)
    v_pages = jnp.zeros(kshape, jnp.float32)
    table = jnp.arange(1, 1 + max_blocks, dtype=jnp.int32).reshape(1, max_blocks)

    # Stage 1: prefill ONLY the prefix into the pages (simulating cached blocks).
    _, (k_new, v_new) = llama.forward(params, cfg, tokens[:, :prefix_len],
                                      want_kv=True)
    k_pages, v_pages = llama.write_prefill_kv(
        k_pages, v_pages, k_new, v_new, table,
        jnp.array([prefix_len], jnp.int32))

    # Stage 2: prefill the suffix continuing from the cached prefix.
    suffix = tokens[:, prefix_len:]
    pad = 16 - (suffix.shape[1] % 16) if suffix.shape[1] % 16 else 0
    suffix_padded = jnp.pad(suffix, ((0, 0), (0, pad)))
    logits, k_pages, v_pages = llama.prefill_with_prefix(
        params, cfg, suffix_padded,
        jnp.array([suffix.shape[1]], jnp.int32),
        jnp.array([prefix_len], jnp.int32),
        k_pages, v_pages, table)
    np.testing.assert_allclose(np.asarray(logits[0]),
                               np.asarray(ref_logits[0, -1]),
                               rtol=2e-4, atol=2e-4)

    # The pages must now hold the SAME KV as a full prefill would produce.
    _, (k_full, v_full) = llama.forward(params, cfg, tokens, want_kv=True)
    for t in range(prompt_len):
        blk, slot = 1 + t // block, t % block
        np.testing.assert_allclose(np.asarray(k_pages[:, blk, slot]),
                                   np.asarray(k_full[:, 0, t]),
                                   rtol=2e-4, atol=2e-4)


def test_allocator_prefix_reuse_and_eviction():
    a = PrefixCachingAllocator(n_blocks=6, block_size=16)  # 5 usable
    b1 = a.alloc(3)
    a.commit_hashes(b1[:2], [101, 102])
    assert a.match_prefix([101, 102]) == b1[:2]
    assert a.match_prefix([999]) == []
    a.release(b1)
    # 2 parked (hash-committed) + 1 freed + 2 never allocated
    assert a.cached_block_count == 2 and a.free_blocks == 3

    # Reuse: acquire cached, allocate the rest.
    m = a.match_prefix([101, 102, 103])
    assert m == b1[:2]
    a.acquire_cached(m)
    extra = a.alloc(3)  # 1 free + evicts nothing further? 5 usable: 2 held + 3
    assert not set(extra) & set(m)
    a.release(m)
    a.release(extra)

    # Eviction under pressure: allocate everything; parked blocks get evicted
    # and their hashes reported.
    big = a.alloc(5)
    assert 101 in a.last_evicted_hashes or 102 in a.last_evicted_hashes
    assert a.match_prefix([101, 102]) == [] or len(a.match_prefix([101, 102])) < 2
    a.release(big)


def test_engine_prefix_cache_hit_and_consistency():
    """Second identical prompt: cached_tokens > 0 and identical greedy tokens."""
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(EngineConfig(model="tiny", backend="tpu", max_batch=2,
                                     max_model_len=256))
        await eng.start()
        try:
            prompt = [1] + list(range(100, 100 + 40))  # 41 tokens: 2 full blocks

            async def gen(rid):
                out = eng.submit(EngineRequest(request_id=rid,
                                               prompt_token_ids=prompt,
                                               max_tokens=6, ignore_eos=True))
                toks, cached = [], 0
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=60)
                    cached = max(cached, ev.cached_tokens)
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                    if ev.finish_reason is not None:
                        return toks, cached

            t1, c1 = await gen("first")
            assert c1 == 0
            t2, c2 = await gen("second")
            assert c2 == 32  # two cached blocks reused
            assert t2 == t1  # numerically consistent continuation

            # A different prompt must not hit the cache.
            out = eng.submit(EngineRequest(
                request_id="other", prompt_token_ids=[1] + list(range(500, 540)),
                max_tokens=2, ignore_eos=True))
            cached = 0
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=60)
                cached = max(cached, ev.cached_tokens)
                if ev.finish_reason is not None:
                    break
            assert cached == 0
        finally:
            await eng.stop()

    asyncio.run(body())


def test_engine_cache_eviction_under_pressure():
    """Tiny block budget: cache blocks evict instead of wedging admission."""
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

        eng = TpuEngine(EngineConfig(model="tiny", backend="tpu", max_batch=1,
                                     max_model_len=128, hbm_kv_blocks=9))
        await eng.start()
        try:
            async def gen(prompt):
                out = eng.submit(EngineRequest(
                    request_id=f"r{prompt[1]}", prompt_token_ids=prompt,
                    max_tokens=2, ignore_eos=True))
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=60)
                    if ev.finish_reason is not None:
                        return ev

            # Distinct 3-block prompts; budget of 8 usable blocks forces LRU
            # eviction of parked cache blocks across iterations.
            for base in (100, 200, 300, 400):
                ev = await gen([1] + list(range(base, base + 40)))
                assert ev.finish_reason.value in ("length", "stop")
        finally:
            await eng.stop()

    asyncio.run(body())
