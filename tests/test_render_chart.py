"""Chart renderer + tpu-stack chart: rendered manifests must be valid k8s
YAML with the right topology under value overrides (the reference's helm
`template` behavior, config/charts/)."""

from __future__ import annotations

import pathlib
import sys

import yaml

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "scripts"))
from render_chart import render_chart  # noqa: E402

CHART = pathlib.Path(__file__).resolve().parents[1] / "deploy/charts/tpu-stack"


def _docs(overrides=None):
    return [d for d in yaml.safe_load_all(render_chart(CHART, overrides)) if d]


def _by_kind_name(docs):
    return {(d["kind"], d["metadata"]["name"]): d for d in docs}


def test_default_render_topology():
    docs = _by_kind_name(_docs())
    assert ("Deployment", "tpu-pool-epp") in docs
    assert ("Deployment", "tpu-pool-decode") in docs
    assert ("Deployment", "tpu-pool-prefill") in docs
    assert ("ConfigMap", "tpu-pool-epp-config") in docs
    # HA via coordination.k8s.io Lease: RBAC instead of a shared volume.
    assert ("Role", "tpu-pool-epp") in docs
    assert ("RoleBinding", "tpu-pool-epp") in docs
    assert ("PersistentVolumeClaim", "tpu-pool-epp-lease") not in docs
    epp = docs[("Deployment", "tpu-pool-epp")]
    args = epp["spec"]["template"]["spec"]["containers"][0]["args"]
    assert any("--kube-lease-name=epp-" in a for a in args)
    assert not any(v.get("persistentVolumeClaim") for v in
                   epp["spec"]["template"]["spec"].get("volumes", []))
    lease_rule = next(r for r in docs[("Role", "tpu-pool-epp")]["rules"]
                      if "leases" in r["resources"])
    assert set(lease_rule["verbs"]) == {"get", "create", "update"}
    assert ("Deployment", "tpu-pool-encode") not in docs  # disabled default
    # Embedded EndpointPickerConfig is itself valid YAML.
    cfg = yaml.safe_load(
        docs[("ConfigMap", "tpu-pool-epp-config")]["data"]["endpointpicker.yaml"])
    assert any(p["type"] == "prefix-cache-scorer" for p in cfg["plugins"])
    # Decode pod: sidecar + one engine.
    names = [c["name"] for c in docs[("Deployment", "tpu-pool-decode")]
             ["spec"]["template"]["spec"]["containers"]]
    assert names == ["routing-sidecar", "engine-0"]


def test_overrides_and_dp_ranks():
    docs = _by_kind_name(_docs({
        "poolName": "prod",
        "decode": {"replicas": 8, "dp": 4},
        "prefill": {"enabled": False},
        "encode": {"enabled": True},
        "gateway": {"ha": False},
    }))
    assert ("Deployment", "prod-prefill") not in docs
    assert ("Deployment", "prod-encode") in docs
    assert ("Role", "prod-epp") not in docs  # ha off → no lease RBAC
    dec = docs[("Deployment", "prod-decode")]
    assert dec["spec"]["replicas"] == 8
    containers = dec["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in containers] == [
        "routing-sidecar", "engine-0", "engine-1", "engine-2", "engine-3"]
    # Rank port arithmetic: engine i listens on 8200+i.
    ports = [c["args"] for c in containers[1:]]
    assert ["--port=8203" in a for a in ports][3]
    # epp args drop the lease flags when HA is off.
    epp = docs[("Deployment", "prod-epp")]
    args = epp["spec"]["template"]["spec"]["containers"][0]["args"]
    assert not any("kube-lease-name" in a for a in args)


def test_gateway_tls_rendering():
    # Default: secure serving with self-signed fallback — no cert volume.
    docs = _by_kind_name(_docs())
    epp = docs[("Deployment", "tpu-pool-epp")]
    spec = epp["spec"]["template"]["spec"]
    args = spec["containers"][0]["args"]
    assert "--secure-serving" in args
    assert not any("cert-path" in a for a in args)
    assert spec["containers"][0]["readinessProbe"]["httpGet"]["scheme"] == "HTTPS"
    assert not any(v.get("secret") for v in spec.get("volumes", []))

    # certSecret mounts the kubernetes.io/tls pair with reload.
    docs = _by_kind_name(_docs({"gateway": {"certSecret": "epp-tls"}}))
    spec = docs[("Deployment", "tpu-pool-epp")]["spec"]["template"]["spec"]
    args = spec["containers"][0]["args"]
    assert "--cert-path=/certs" in args and "--enable-cert-reload" in args
    assert {"name": "epp-certs", "mountPath": "/certs", "readOnly": True} \
        in spec["containers"][0]["volumeMounts"]
    assert any(v.get("secret", {}).get("secretName") == "epp-tls"
               for v in spec["volumes"])

    # TLS off renders a plain listener.
    docs = _by_kind_name(_docs({"gateway": {"secureServing": False}}))
    spec = docs[("Deployment", "tpu-pool-epp")]["spec"]["template"]["spec"]
    assert "--secure-serving" not in spec["containers"][0]["args"]
    assert "scheme" not in spec["containers"][0]["readinessProbe"]["httpGet"]

    # Sidecar TLS knobs render on the decode pod.
    docs = _by_kind_name(_docs({"decode": {"sidecarTLS": {
        "secureServing": True, "certSecret": "pd-tls",
        "prefillerTLS": True}}}))
    spec = docs[("Deployment", "tpu-pool-decode")]["spec"]["template"]["spec"]
    sidecar = spec["containers"][0]
    assert sidecar["name"] == "routing-sidecar"
    for flag in ("--secure-serving", "--cert-path=/certs",
                 "--use-tls-for-prefiller",
                 "--insecure-skip-verify-prefiller"):
        assert flag in sidecar["args"], flag
    assert any(v.get("secret", {}).get("secretName") == "pd-tls"
               for v in spec["volumes"])
    # Default: no TLS args on the sidecar.
    docs = _by_kind_name(_docs())
    sidecar = docs[("Deployment", "tpu-pool-decode")]["spec"]["template"][
        "spec"]["containers"][0]
    assert not any("tls" in a or "secure" in a for a in sidecar["args"])


def test_cli_set_overrides(tmp_path, capsys):
    from render_chart import main

    out = tmp_path / "o.yaml"
    main([str(CHART), "--set", "decode.replicas=5",
          "--set", "poolName=x", "-o", str(out)])
    docs = _by_kind_name(list(yaml.safe_load_all(out.read_text())))
    assert docs[("Deployment", "x-decode")]["spec"]["replicas"] == 5


def test_pd_pod_tls_rendering():
    """Decode/prefill pod TLS knobs (NEXT.md gap): the sidecar's per-leg
    flags (decoder/encoder join prefiller) and the engines' --secure-serving
    render; defaults stay plain."""
    docs = _by_kind_name(_docs({
        "decode": {"engineTLS": True,
                   "sidecarTLS": {"decoderTLS": True, "encoderTLS": True}},
        "prefill": {"engineTLS": True},
    }))
    dec_spec = docs[("Deployment", "tpu-pool-decode")]["spec"]["template"]["spec"]
    sidecar, engine = dec_spec["containers"][0], dec_spec["containers"][1]
    for flag in ("--use-tls-for-decoder", "--insecure-skip-verify-decoder",
                 "--use-tls-for-encoder", "--insecure-skip-verify-encoder"):
        assert flag in sidecar["args"], flag
    assert "--use-tls-for-prefiller" not in sidecar["args"]
    assert "--secure-serving" in engine["args"]
    assert engine["readinessProbe"]["httpGet"]["scheme"] == "HTTPS"
    pre = docs[("Deployment", "tpu-pool-prefill")]["spec"]["template"][
        "spec"]["containers"][0]
    assert "--secure-serving" in pre["args"]
    assert pre["readinessProbe"]["httpGet"]["scheme"] == "HTTPS"

    # Defaults: no TLS args, plain probes (no scheme key).
    docs = _by_kind_name(_docs())
    dec_spec = docs[("Deployment", "tpu-pool-decode")]["spec"]["template"]["spec"]
    assert not any("tls" in a or "secure" in a
                   for a in dec_spec["containers"][0]["args"])
    assert "--secure-serving" not in dec_spec["containers"][1]["args"]
    assert "scheme" not in dec_spec["containers"][1]["readinessProbe"]["httpGet"]
    pre = docs[("Deployment", "tpu-pool-prefill")]["spec"]["template"][
        "spec"]["containers"][0]
    assert "--secure-serving" not in pre["args"]
    assert "scheme" not in pre["readinessProbe"]["httpGet"]
