"""Pallas paged attention (interpret mode) vs the XLA reference formulation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_inference_scheduler_tpu.ops.attention import paged_decode_attention
from llm_d_inference_scheduler_tpu.ops.pallas_paged_attention import (
    paged_decode_attention_pallas,
)


@pytest.mark.parametrize("seq_lens_spec", [[5], [17, 3], [33, 1, 16]])
def test_pallas_matches_xla_reference(seq_lens_spec):
    B = len(seq_lens_spec)
    H, Hkv, D, block, maxB = 8, 2, 32, 16, 4
    N = 1 + B * maxB
    key = jax.random.key(0)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (B, H, D), jnp.float32)
    k_pages = jax.random.normal(ks[1], (N, block, Hkv, D), jnp.float32)
    v_pages = jax.random.normal(ks[2], (N, block, Hkv, D), jnp.float32)
    cur_k = jax.random.normal(ks[3], (B, Hkv, D), jnp.float32)
    cur_v = jax.random.normal(ks[4], (B, Hkv, D), jnp.float32)
    block_tables = jnp.arange(1, 1 + B * maxB, dtype=jnp.int32).reshape(B, maxB)
    seq_lens = jnp.array(seq_lens_spec, jnp.int32)

    ref = paged_decode_attention(q, k_pages, v_pages, block_tables, seq_lens,
                                 cur_k=cur_k, cur_v=cur_v)
    out = paged_decode_attention_pallas(q, k_pages, v_pages, block_tables,
                                        seq_lens, cur_k, cur_v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_pallas_trash_block_slots_isolated():
    """Padding slots (seq_len=1, table all trash) only see their cur_k column."""
    B, H, Hkv, D, block, maxB = 2, 4, 2, 32, 16, 2
    N = 1 + B * maxB
    key = jax.random.key(1)
    ks = jax.random.split(key, 5)
    q = jax.random.normal(ks[0], (B, H, D), jnp.float32)
    k_pages = jax.random.normal(ks[1], (N, block, Hkv, D), jnp.float32)
    v_pages = jax.random.normal(ks[2], (N, block, Hkv, D), jnp.float32)
    cur_k = jax.random.normal(ks[3], (B, Hkv, D), jnp.float32)
    cur_v = jax.random.normal(ks[4], (B, Hkv, D), jnp.float32)
    block_tables = jnp.array([[1, 2], [0, 0]], jnp.int32)  # row 1: trash
    seq_lens = jnp.array([20, 1], jnp.int32)

    out = paged_decode_attention_pallas(q, k_pages, v_pages, block_tables,
                                        seq_lens, cur_k, cur_v, interpret=True)
    # Row 1 attends only to its own token -> output == cur_v broadcast per group
    expect = jnp.repeat(cur_v[1], H // Hkv, axis=0)
    np.testing.assert_allclose(np.asarray(out[1]), np.asarray(expect),
                               rtol=1e-5, atol=1e-5)


def test_engine_pallas_branch_matches_default():
    """The engine's use_pallas decode branch (interpreted) generates the same
    greedy tokens as the XLA path."""
    import asyncio
    from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    async def gen(cfg):
        eng = TpuEngine(cfg)
        await eng.start()
        try:
            out = eng.submit(EngineRequest(request_id="r",
                                           prompt_token_ids=[1, 7, 8, 9] * 3,
                                           max_tokens=5, stop_token_ids=(-1,)))
            toks = []
            while True:
                ev = await asyncio.wait_for(out.get(), timeout=60)
                if ev.token_id is not None:
                    toks.append(ev.token_id)
                if ev.finish_reason is not None:
                    return toks
        finally:
            await eng.stop()

    base = dict(model="tiny", backend="tpu", max_batch=2, max_model_len=128)
    t_default = asyncio.run(gen(EngineConfig(**base)))
    t_pallas = asyncio.run(gen(EngineConfig(**base, pallas_attention=True,
                                            pallas_interpret=True)))
    assert t_pallas == t_default
