"""gRPC health service: wire codec, readiness semantics, live Check calls."""

import asyncio

import grpc
import grpc.aio

from llm_d_inference_scheduler_tpu.router.health_grpc import (
    EXT_PROC_SERVICE,
    NOT_SERVING,
    SERVICE_UNKNOWN,
    SERVING,
    HealthServer,
    parse_request,
    serialize_response,
)


def test_wire_codec_roundtrip():
    # encode a HealthCheckRequest by hand: field 1, len-delim
    svc = EXT_PROC_SERVICE.encode()
    req = b"\x0a" + bytes([len(svc)]) + svc
    assert parse_request(req) == EXT_PROC_SERVICE
    assert parse_request(b"") == ""
    assert serialize_response(SERVING) == b"\x08\x01"
    assert serialize_response(SERVICE_UNKNOWN) == b"\x08\x03"


def test_health_check_over_real_grpc():
    async def body():
        ready = {"v": False}
        server = HealthServer(ready_fn=lambda: ready["v"])
        port = await server.start()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                check = ch.unary_unary(
                    "/grpc.health.v1.Health/Check",
                    request_serializer=lambda s: (
                        b"\x0a" + bytes([len(s)]) + s.encode() if s else b""),
                    response_deserializer=lambda b: b,
                )
                resp = await check("")
                assert resp == serialize_response(NOT_SERVING)

                ready["v"] = True
                resp = await check("")
                assert resp == serialize_response(SERVING)
                resp = await check(EXT_PROC_SERVICE)
                assert resp == serialize_response(SERVING)
                resp = await check("some.other.Service")
                assert resp == serialize_response(SERVICE_UNKNOWN)
        finally:
            await server.stop()

    asyncio.run(body())


def test_parse_request_truncated_input():
    # truncated length byte / unterminated varint must degrade to "" not raise
    assert parse_request(b"\x0a") == ""
    assert parse_request(b"\x0a\x80") == ""
    assert parse_request(b"\x08\x80") == ""
