"""Decision flight recorder: ring-buffer bounds, kill-switch, scheduler
recording, the gateway debug surface, and the coverage lint.

Unit tier drives DecisionRecorder/Scheduler directly; the e2e tier runs a
hermetic gateway over sim engines and reads /debug/decisions + the
x-debug-decision header echo. The golden disagg-path record (prefill filter
drops + decode scorer table + chaos failover trail) lives in
tests/test_e2e_disagg.py beside the rest of the P/D coverage.
"""

import asyncio
import pathlib
import sys

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.decisions import (
    SCHEMA_VERSION,
    DecisionConfig,
    DecisionRecord,
    DecisionRecorder,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
)
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway


def run(coro):
    return asyncio.run(coro)


# ---- unit tier -----------------------------------------------------------


def test_ring_buffer_bounds_and_index():
    rec = DecisionRecorder(DecisionConfig(capacity=4))
    for i in range(10):
        rec.start(f"r{i}", "m")
    assert len(rec) == 4
    # Oldest evicted, newest retrievable; index follows the ring.
    assert rec.get("r0") is None and rec.get("r5") is None
    assert rec.get("r9") is not None
    assert [r.request_id for r in rec.snapshot()] == ["r9", "r8", "r7", "r6"]
    assert [r.request_id for r in rec.snapshot(2)] == ["r9", "r8"]


def test_ring_does_not_recycle_referenced_records():
    """A record still attached to an in-flight request must not be recycled
    into another request's trail when the ring evicts it."""
    rec = DecisionRecorder(DecisionConfig(capacity=2))
    held = rec.start("held", "m")
    held.record_admission("flow-control", "dispatched")
    for i in range(8):
        rec.start(f"f{i}", "m")
    # The held record keeps its identity and content.
    assert held.request_id == "held"
    assert held.admission["outcome"] == "dispatched"


def test_kill_switch_and_duplicate_ids():
    off = DecisionRecorder(DecisionConfig(enabled=False))
    assert off.start("x", "m") is None
    assert len(off) == 0 and not off.enabled

    on = DecisionRecorder(DecisionConfig(capacity=8))
    first = on.start("dup", "m")
    second = on.start("dup", "m")
    assert on.get("dup") is second is not first  # latest wins the index


def test_record_render_and_summary():
    rec = DecisionRecord("req-1", "tiny", top_k=2)
    rec.record_admission("flow-control", "dispatched", flow_id="f1",
                         priority_band=0, queue_ms=1.23456)
    sec = rec.begin_profile("decode", 3)
    rec.profile_filter(sec, "decode-filter/decode-filter", 3,
                       ["a:1", "b:1"], ["c:1"])
    rec.profile_scorer(sec, "queue-scorer/queue-scorer", 2.0,
                       {"a:1": 0.25, "b:1": 0.75})
    rec.profile_picker(sec, "max-score-picker/max-score-picker",
                       ["b:1"], {"a:1": 0.5, "b:1": 1.5})
    rec.record_attempt("b:1", "connect", reason="upstream-connect-error")
    rec.record_attempt("a:1", "ok", status=200)
    rec.finalize(200, destination="a:1")

    doc = rec.to_dict()
    assert doc["schema_version"] == SCHEMA_VERSION
    assert doc["admission"]["queue_ms"] == 1.235  # rendered rounding
    prof = doc["rounds"][0]["profiles"]["decode"]
    assert prof["filters"][0]["dropped"] == ["c:1"]
    scores = prof["scorers"]["queue-scorer/queue-scorer"]["scores"]
    assert scores["b:1"] == {"raw": 0.75, "weighted": 1.5}
    assert prof["picker"]["picked"] == ["b:1"]
    assert prof["picker"]["margin"] == 1.0
    assert [a.get("outcome") for a in doc["attempts"]] == ["connect", "ok"]
    assert doc["final"]["status"] == 200

    s = rec.summary_line()
    assert "winner=b:1" in s and "runner_up=a:1" in s and "margin=" in s
    assert "decode/decode-filter/decode-filter:1" in s
    assert "attempts=2" in s

    # top-K trimming: K=2 keeps both here; K=1 would trim.
    rec.top_k = 1
    scores = rec.to_dict()["rounds"][0]["profiles"]["decode"][
        "scorers"]["queue-scorer/queue-scorer"]
    assert list(scores["scores"]) == ["b:1"] and scores["candidates"] == 2


def test_render_snapshots_live_dicts():
    """Off-loop scheduling (scheduler-pool workers) can mutate a record
    while GET /debug/decisions renders it on the event loop — the render
    side must snapshot live dicts via an atomic ``dict()`` copy before
    iterating (a retry loop would livelock against a busy writer; see
    ``DecisionRecord._live_items``), never iterate them raw."""

    assert DecisionRecord._live_items({"k": 1}) == [("k", 1)]

    # End-to-end: a worker thread hammers round/profile/scorer inserts
    # while the loop side renders — no RuntimeError, every render a
    # consistent point-in-time document.
    import threading

    rec = DecisionRecord("req-race", "tiny")
    rec.begin_round("schedule", 2)

    def writer():
        # Bounded: renders walk every round, so an unbounded writer makes
        # each render slower than the last and the test quadratic.
        for i in range(2000):
            sec = rec.begin_profile(f"p{i}", 2)
            rec.profile_scorer(sec, f"s{i}", 1.0, {"a:1": 0.5})
            rec.profile_picker(sec, "picker", ["a:1"], {"a:1": 0.5})

    t = threading.Thread(target=writer)
    t.start()
    try:
        while t.is_alive():
            doc = rec.to_dict()
            assert doc["request_id"] == "req-race"
            rec.summary_line()
    finally:
        t.join()
    assert len(rec.to_dict()["rounds"][0]["profiles"]) == 2000


def test_scheduler_records_rounds_and_kill_switch_skips():
    from llm_d_inference_scheduler_tpu.router.plugins.filters import DecodeFilter
    from llm_d_inference_scheduler_tpu.router.plugins.pickers import MaxScorePicker
    from llm_d_inference_scheduler_tpu.router.plugins.scorers import QueueScorer
    from llm_d_inference_scheduler_tpu.router.plugins.profile_handlers import (
        SingleProfileHandler,
    )
    from llm_d_inference_scheduler_tpu.router.scheduling.scheduler import (
        Scheduler,
        SchedulerProfile,
        WeightedScorer,
    )

    eps = []
    for i, role in enumerate(["decode", "prefill", "decode"]):
        ep = Endpoint(EndpointMetadata(name=f"e{i}", address=f"10.1.0.{i}",
                                       port=9000,
                                       labels={"llm-d.ai/role": role}))
        ep.metrics.waiting_queue_size = i
        eps.append(ep)
    profile = SchedulerProfile("decode", [DecodeFilter("decode-filter")],
                               [WeightedScorer(QueueScorer("queue-scorer"), 2.0)],
                               MaxScorePicker("max-score-picker"))
    sched = Scheduler({"decode": profile}, SingleProfileHandler())

    recorder = DecisionRecorder(DecisionConfig())
    req = InferenceRequest(request_id="sched-1", target_model="tiny",
                           body=InferenceRequestBody(completions={"prompt": "x"}))
    req.decision = recorder.start(req.request_id, req.target_model)
    result = sched.schedule(None, req, eps)
    # Second schedule on the same request (the failover reschedule shape).
    sched.schedule(None, req, eps[:1])

    doc = req.decision.to_dict()
    assert [r["reason"] for r in doc["rounds"]] == ["schedule", "reschedule"]
    prof = doc["rounds"][0]["profiles"]["decode"]
    assert prof["candidates_in"] == 3
    # prefill endpoint dropped by the decode filter
    assert prof["filters"][0]["dropped"] == ["10.1.0.1:9000"]
    # per-endpoint weighted scores for both survivors; queue 0 beats queue 2
    qs = prof["scorers"]["queue-scorer/queue-scorer"]
    assert qs["weight"] == 2.0 and len(qs["scores"]) == 2
    assert prof["picker"]["picked"] == ["10.1.0.0:9000"]
    assert prof["picker"]["margin"] > 0
    assert result.primary().target_endpoints[0].metadata.address_port == \
        "10.1.0.0:9000"

    # Kill switch: same cycle records nothing and schedules identically.
    req2 = InferenceRequest(request_id="sched-2", target_model="tiny",
                            body=InferenceRequestBody(completions={"prompt": "x"}))
    req2.decision = DecisionRecorder(
        DecisionConfig(enabled=False)).start("sched-2", "tiny")
    assert req2.decision is None
    result2 = sched.schedule(None, req2, eps)
    assert result2.primary().target_endpoints[0].metadata.address_port == \
        "10.1.0.0:9000"


def test_verify_decisions_lint_clean():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "scripts"))
    import verify_decisions

    assert verify_decisions.check() == []


# ---- e2e tier ------------------------------------------------------------

GW, EA, EB = 18860, 18861, 18862

CFG = f"""
featureGates: {{flowControl: true}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA}}}
    - {{address: 127.0.0.1, port: {EB}}}
plugins:
  - {{type: queue-scorer}}
  - {{type: kv-cache-utilization-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: queue-scorer, weight: 2}}
      - {{pluginRef: kv-cache-utilization-scorer, weight: 2}}
"""


async def _sim(port, **kw):
    kw.setdefault("backend", "sim")
    kw.setdefault("model", "tiny")
    s = EngineServer(EngineConfig(port=port, **kw))
    await s.start()
    return s


def test_gateway_debug_decisions_and_header():
    async def body():
        ea, eb = await _sim(EA), await _sim(EB)
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(
                    f"http://127.0.0.1:{GW}/v1/completions",
                    json={"model": "tiny", "prompt": "hello", "max_tokens": 2},
                    headers={"x-request-id": "dec-e2e-1",
                             "x-debug-decision": "summary"})
                assert r.status_code == 200
                # Header echo: compact one-line verdict.
                summary = r.headers["x-decision-summary"]
                assert "winner=127.0.0.1:" in summary
                assert "admission=dispatched" in summary

                # Recent-decisions page.
                r = await c.get(f"http://127.0.0.1:{GW}/debug/decisions")
                doc = r.json()
                assert doc["schema_version"] == SCHEMA_VERSION and doc["enabled"]
                assert any(d["request_id"] == "dec-e2e-1"
                           for d in doc["decisions"])

                # Full record: admission (flow control: band + queue time) →
                # profile (scorer table + picker) → attempt trail → final.
                r = await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/dec-e2e-1")
                assert r.status_code == 200
                rec = r.json()
                adm = rec["admission"]
                assert adm["mechanism"] == "flow-control"
                assert adm["outcome"] == "dispatched"
                assert adm["priority_band"] == 0 and adm["queue_ms"] >= 0
                prof = rec["rounds"][0]["profiles"]["default"]
                assert len(prof["scorers"]) == 2
                for s in prof["scorers"].values():
                    assert s["scores"]  # per-endpoint table present
                assert prof["picker"]["picked"][0].startswith("127.0.0.1:")
                assert rec["attempts"][-1]["outcome"] == "ok"
                assert rec["final"]["status"] == 200
                assert rec["final"]["destination"].startswith("127.0.0.1:")

                # 404 contract for unknown ids.
                r = await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/nope")
                assert r.status_code == 404
        finally:
            await gw.stop()
            await ea.stop()
            await eb.stop()

    run(body())


def test_gateway_kill_switch_disables_recording():
    cfg = CFG + "\ndecisions: {enabled: false}\n"

    async def body():
        ea, eb = await _sim(EA), await _sim(EB)
        gw = build_gateway(cfg, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(
                    f"http://127.0.0.1:{GW}/v1/completions",
                    json={"model": "tiny", "prompt": "hello", "max_tokens": 2},
                    headers={"x-request-id": "dec-off-1",
                             "x-debug-decision": "summary"})
                assert r.status_code == 200
                assert "x-decision-summary" not in r.headers
                r = await c.get(f"http://127.0.0.1:{GW}/debug/decisions")
                doc = r.json()
                assert doc["enabled"] is False and doc["decisions"] == []
                r = await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/dec-off-1")
                assert r.status_code == 404
        finally:
            await gw.stop()
            await ea.stop()
            await eb.stop()

    run(body())
