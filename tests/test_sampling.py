"""Batched sampler: greedy, temperature, top-k and top-p restriction."""

import jax
import jax.numpy as jnp
import numpy as np

from llm_d_inference_scheduler_tpu.engine.sampling import sample_tokens


def _run(logits, temps, top_k, top_p, n=300, seed=0):
    keys = jax.random.split(jax.random.key(seed), n)
    fn = jax.vmap(lambda k: sample_tokens(
        jnp.asarray(logits), k, jnp.asarray(temps, jnp.float32),
        jnp.asarray(top_k, jnp.int32), jnp.asarray(top_p, jnp.float32)))
    return np.asarray(jax.jit(fn)(keys))  # [n, B]


def test_greedy_is_argmax():
    logits = np.array([[0.1, 3.0, -1.0, 0.5], [2.0, 0.0, 5.0, 1.0]], np.float32)
    out = _run(logits, temps=[0.0, 0.0], top_k=[0, 0], top_p=[1.0, 1.0], n=3)
    assert (out[:, 0] == 1).all() and (out[:, 1] == 2).all()


def test_top_k_restricts_support():
    # Row: top-2 tokens are ids 1 and 3; with top_k=2 nothing else may appear.
    logits = np.array([[0.0, 4.0, 1.0, 3.0, 2.0]], np.float32)
    out = _run(logits, temps=[1.0], top_k=[2], top_p=[1.0])
    assert set(np.unique(out)) <= {1, 3}
    assert {1, 3} <= set(np.unique(out))  # both actually sampled


def test_top_p_keeps_minimal_prefix():
    # Probabilities ~ [0.64, 0.23, 0.09, 0.03, ...]; p=0.5 keeps only the top
    # token plus the one that crosses the boundary (prefix rule keeps token 1).
    logits = np.array([[4.0, 3.0, 2.0, 1.0, 0.0]], np.float32)
    out = _run(logits, temps=[1.0], top_k=[0], top_p=[0.5])
    assert set(np.unique(out)) <= {0, 1}


def test_per_row_independent_settings():
    logits = np.array([[0.0, 5.0, 0.0], [5.0, 0.0, 4.9]], np.float32)
    # Row 0 greedy; row 1 hot temperature with full support.
    out = _run(logits, temps=[0.0, 2.0], top_k=[0, 0], top_p=[1.0, 1.0])
    assert (out[:, 0] == 1).all()
    assert len(np.unique(out[:, 1])) >= 2  # high temp explores


def test_temperature_sharpness():
    logits = np.array([[2.0, 1.0, 0.0]], np.float32)
    cold = _run(logits, temps=[0.2], top_k=[0], top_p=[1.0])
    hot = _run(logits, temps=[3.0], top_k=[0], top_p=[1.0], seed=1)
    # Cold sampling should pick the mode far more often than hot.
    assert (cold == 0).mean() > (hot == 0).mean() + 0.15


def test_temperature_applied_before_top_p():
    # Probabilities at T=1: [0.64, 0.23, 0.09, ...] — p=0.75 keeps {0, 1}.
    # At T=2 the tempered distribution is flatter ([0.44, 0.27, 0.16, 0.10]),
    # so the p=0.75 nucleus widens to {0, 1, 2} (vLLM/OpenAI semantics:
    # truncation runs on the TEMPERED distribution).
    logits = np.array([[4.0, 3.0, 2.0, 1.0]], np.float32)
    cool = _run(logits, temps=[1.0], top_k=[0], top_p=[0.75])
    hot = _run(logits, temps=[2.0], top_k=[0], top_p=[0.75], n=600)
    assert set(np.unique(cool)) <= {0, 1}
    assert 2 in set(np.unique(hot))
    assert 3 not in set(np.unique(hot))
