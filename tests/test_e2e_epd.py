"""E/PD encode disaggregation: multimodal requests prime encode workers."""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.sidecar import Sidecar, SidecarConfig

GW, SC, DEC, PRE, ENC = 18460, 18461, 18462, 18463, 18464

CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
    - {{address: 127.0.0.1, port: {ENC}, labels: {{llm-d.ai/role: encode}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: encode-filter}}
  - {{type: queue-scorer}}
  - {{type: approx-prefix-cache-producer}}
  - {{type: prefix-cache-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 16}}
      encodeDecider: always-disagg-multimodal-decider
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
  - name: encode
    plugins:
      - {{pluginRef: encode-filter}}
      - {{pluginRef: queue-scorer}}
"""


def test_epd_encode_fanout():
    async def body():
        servers = [
            EngineServer(EngineConfig(backend="sim", model="tiny", port=p,
                                      role=role))
            for p, role in ((DEC, "decode"), (PRE, "prefill"), (ENC, "encode"))]
        for s in servers:
            await s.start()
        enc_server = servers[2]
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   ssrf_allowlist=[f"127.0.0.1:{PRE}",
                                                   f"127.0.0.1:{ENC}"]))
        await sc.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            long_text = "describe this image in detail please " * 4
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post(f"http://127.0.0.1:{GW}/v1/chat/completions", json={
                    "model": "tiny", "max_tokens": 3,
                    "messages": [{"role": "user", "content": [
                        {"type": "text", "text": long_text},
                        {"type": "image_url", "image_url": {"url": "http://x/cat.png"}},
                        {"type": "image_url", "image_url": {"url": "http://x/dog.png"}},
                    ]}]})
                assert r.status_code == 200
                # encoder was primed with both items
                assert sum(enc_server.ec_store.values()) == 2

                m = await c.get(f"http://127.0.0.1:{GW}/metrics")
                assert 'decision_type="encode-prefill-decode"' in m.text

                # text-only request: no encode stage
                before = dict(enc_server.ec_store)
                r = await c.post(f"http://127.0.0.1:{GW}/v1/chat/completions", json={
                    "model": "tiny", "max_tokens": 2,
                    "messages": [{"role": "user", "content": "plain text"}]})
                assert r.status_code == 200
                assert enc_server.ec_store == before
        finally:
            await gw.stop()
            await sc.stop()
            for s in servers:
                await s.stop()

    asyncio.run(body())
