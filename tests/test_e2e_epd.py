"""E/PD encode disaggregation: multimodal requests prime encode workers."""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.sidecar import Sidecar, SidecarConfig

GW, SC, DEC, PRE, ENC = 18460, 18461, 18462, 18463, 18464

CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
    - {{address: 127.0.0.1, port: {ENC}, labels: {{llm-d.ai/role: encode}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: encode-filter}}
  - {{type: queue-scorer}}
  - {{type: approx-prefix-cache-producer}}
  - {{type: prefix-cache-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 16}}
      encodeDecider: always-disagg-multimodal-decider
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
  - name: encode
    plugins:
      - {{pluginRef: encode-filter}}
      - {{pluginRef: queue-scorer}}
"""


def test_epd_encode_fanout():
    async def body():
        servers = [
            EngineServer(EngineConfig(backend="sim", model="tiny", port=p,
                                      role=role))
            for p, role in ((DEC, "decode"), (PRE, "prefill"), (ENC, "encode"))]
        for s in servers:
            await s.start()
        enc_server = servers[2]
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   ssrf_allowlist=[f"127.0.0.1:{PRE}",
                                                   f"127.0.0.1:{ENC}"]))
        await sc.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            long_text = "describe this image in detail please " * 4
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post(f"http://127.0.0.1:{GW}/v1/chat/completions", json={
                    "model": "tiny", "max_tokens": 3,
                    "messages": [{"role": "user", "content": [
                        {"type": "text", "text": long_text},
                        {"type": "image_url", "image_url": {"url": "http://x/cat.png"}},
                        {"type": "image_url", "image_url": {"url": "http://x/dog.png"}},
                    ]}]})
                assert r.status_code == 200
                # encoder was primed with both items: one staged entry whose
                # embedding rows cover 2 images × n_patches each
                assert len(enc_server.ec_store) == 1
                (rec,) = enc_server.ec_store.values()
                from llm_d_inference_scheduler_tpu.models import TINY
                from llm_d_inference_scheduler_tpu.models.vision import VIT_TINY
                # Tower projects into the served model's d_model.
                assert rec["embeds"].shape == (2 * VIT_TINY.n_patches,
                                               TINY.d_model)
                assert rec["indices"] == [0, 1]

                m = await c.get(f"http://127.0.0.1:{GW}/metrics")
                assert 'decision_type="encode-prefill-decode"' in m.text

                # text-only request: no encode stage
                before = list(enc_server.ec_store)
                r = await c.post(f"http://127.0.0.1:{GW}/v1/chat/completions", json={
                    "model": "tiny", "max_tokens": 2,
                    "messages": [{"role": "user", "content": "plain text"}]})
                assert r.status_code == 200
                assert list(enc_server.ec_store) == before
        finally:
            await gw.stop()
            await sc.stop()
            for s in servers:
                await s.stop()

    asyncio.run(body())


def test_vision_tower_shapes_and_determinism():
    import jax
    import numpy as np

    from llm_d_inference_scheduler_tpu.models.vision import (
        VIT_TINY,
        encode_image,
        init_vision_params,
    )

    params = init_vision_params(VIT_TINY, jax.random.key(0))
    px = np.random.default_rng(0).standard_normal(
        (2, VIT_TINY.image_size, VIT_TINY.image_size, 3)).astype(np.float32)
    out = encode_image(params, VIT_TINY, px)
    assert out.shape == (2, VIT_TINY.n_patches, VIT_TINY.out_dim)
    out2 = encode_image(params, VIT_TINY, px)
    assert np.allclose(out, out2)
    # Different images → different embeddings.
    assert not np.allclose(out[0], out[1])


def test_epd_embeddings_reach_prefill_and_change_output():
    """Phase 2 (BASELINE config 5 shape): the encode worker's embeddings are
    pulled by the serving engine and spliced into prefill — two different
    images must produce different generations for the same text."""
    DEC2, ENC2, SC2 = 18470, 18471, 18472

    async def body():
        dec = EngineServer(EngineConfig(backend="tpu", model="tiny", port=DEC2,
                                        max_batch=4, max_model_len=256,
                                        kv_events_port=0))
        enc = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENC2,
                                        role="encode"))
        await dec.start()
        await enc.start()
        sc = Sidecar(SidecarConfig(port=SC2, decoder_url=f"http://127.0.0.1:{DEC2}"))
        await sc.start()
        try:
            async def ask(image_seed):
                pixels = [[[float(image_seed)] * 3] * 4] * 4  # tiny 4x4 patch
                async with httpx.AsyncClient(timeout=90) as c:
                    r = await c.post(
                        f"http://127.0.0.1:{SC2}/v1/chat/completions",
                        json={"model": "tiny", "max_tokens": 6,
                              "temperature": 0, "ignore_eos": True,
                              "messages": [{"role": "user", "content": [
                                  {"type": "text", "text": "what is this?"},
                                  {"type": "image_url", "pixels": pixels},
                              ]}]},
                        headers={"x-encoder-hosts-ports": f"127.0.0.1:{ENC2}"})
                assert r.status_code == 200, r.text
                return r.json()["choices"][0]["message"]["content"]

            a = await ask(1.0)
            b = await ask(-3.0)
            plain = None
            async with httpx.AsyncClient(timeout=90) as c:
                r = await c.post(
                    f"http://127.0.0.1:{SC2}/v1/chat/completions",
                    json={"model": "tiny", "max_tokens": 6, "temperature": 0,
                          "ignore_eos": True,
                          "messages": [{"role": "user",
                                        "content": "what is this?"}]})
                plain = r.json()["choices"][0]["message"]["content"]
            # The injected embeddings must actually steer generation.
            assert a != b or a != plain
            assert len(a) > 0 and len(b) > 0
        finally:
            await sc.stop()
            await enc.stop()
            await dec.stop()

    asyncio.run(body())


def test_epd_item_order_preserved_across_hosts():
    """3 images round-robined over 2 encode hosts must splice back in the
    ORIGINAL order (indices ride the primer payload and the /ec response)."""
    DEC3, ENCA, ENCB, SC3 = 18475, 18476, 18477, 18478

    async def body():
        dec = EngineServer(EngineConfig(backend="tpu", model="tiny", port=DEC3,
                                        max_batch=4, max_model_len=256,
                                        kv_events_port=0))
        enc_a = EngineServer(EngineConfig(backend="sim", model="tiny",
                                          port=ENCA, role="encode"))
        enc_b = EngineServer(EngineConfig(backend="sim", model="tiny",
                                          port=ENCB, role="encode"))
        for s in (dec, enc_a, enc_b):
            await s.start()
        sc = Sidecar(SidecarConfig(port=SC3, decoder_url=f"http://127.0.0.1:{DEC3}"))
        await sc.start()
        try:
            import numpy as np

            def img(seed):
                return {"type": "image_url",
                        "pixels": [[[float(seed)] * 3] * 4] * 4}

            rid = "order-test-1"
            async with httpx.AsyncClient(timeout=90) as c:
                r = await c.post(
                    f"http://127.0.0.1:{SC3}/v1/chat/completions",
                    json={"model": "tiny", "max_tokens": 3, "temperature": 0,
                          "ignore_eos": True, "request_id": rid,
                          "messages": [{"role": "user", "content":
                                        [{"type": "text", "text": "see"}]
                                        + [img(s) for s in (1.0, 2.0, 3.0)]}]},
                    headers={"x-encoder-hosts-ports":
                             f"127.0.0.1:{ENCA},127.0.0.1:{ENCB}"})
            assert r.status_code == 200, r.text
            # Round-robin put images 0,2 on host A and 1 on host B.
            rec_a = enc_a.ec_store[rid]
            rec_b = enc_b.ec_store[rid]
            assert rec_a["indices"] == [0, 2]
            assert rec_b["indices"] == [1]

            # The reassembly the serving engine performs must restore global
            # order 0,1,2: A-rows[item0], B-rows[item1], A-rows[item2].
            _, mm, mm_pos = await dec._resolve_multimodal(
                {"request_id": rid,
                 "ec_sources": [f"127.0.0.1:{ENCA}", f"127.0.0.1:{ENCB}"]},
                [5, 6])
            per = rec_a["embeds"].shape[0] // 2
            expected = np.concatenate([rec_a["embeds"][:per],
                                       rec_b["embeds"],
                                       rec_a["embeds"][per:]])
            assert mm.shape == expected.shape
            assert np.allclose(mm, expected)
            assert mm_pos == list(range(mm.shape[0]))
        finally:
            await sc.stop()
            for s in (dec, enc_a, enc_b):
                await s.stop()

    asyncio.run(body())
