"""Envoy ext-proc gRPC wire binding: real FULL_DUPLEX_STREAMED frames over a
live grpc.aio channel (VERDICT r1 item 5 — the header-mutation and
ImmediateResponse semantics of reference handlers/server.go:202-414)."""

import asyncio
import json

import grpc
import grpc.aio
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

METHOD = "/envoy.service.ext_proc.v3.ExternalProcessor/Process"


# ---- independent protobuf encoding (pins the wire format) ---------------


def _vi(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        out += bytes([b | (0x80 if v else 0)])
        if not v:
            return out


def _tag(f: int, w: int) -> bytes:
    return _vi((f << 3) | w)


def _ld(f: int, p: bytes) -> bytes:
    return _tag(f, 2) + _vi(len(p)) + p


def _header_map(headers: dict[str, str]) -> bytes:
    out = b""
    for k, v in headers.items():
        out += _ld(1, _ld(1, k.encode()) + _ld(2, v.encode()))
    return out


def req_headers_frame(headers: dict[str, str], eos: bool = False) -> bytes:
    msg = _ld(1, _header_map(headers))
    if eos:
        msg += _tag(3, 0) + _vi(1)
    return _ld(2, msg)  # ProcessingRequest.request_headers = 2


def req_body_frame(body: bytes, eos: bool = True) -> bytes:
    msg = _ld(1, body)
    if eos:
        msg += _tag(2, 0) + _vi(1)
    return _ld(4, msg)  # ProcessingRequest.request_body = 4 (interleaved!)


def resp_headers_frame(headers: dict[str, str]) -> bytes:
    return _ld(3, _ld(1, _header_map(headers)))  # response_headers = 3


def resp_body_frame(body: bytes, eos: bool = True) -> bytes:
    msg = _ld(1, body)
    if eos:
        msg += _tag(2, 0) + _vi(1)
    return _ld(5, msg)  # response_body = 5


# ---- minimal response decoding ------------------------------------------


def _fields(buf: bytes):
    pos = 0
    while pos < len(buf):
        tag = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            tag |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        field, wire = tag >> 3, tag & 0x7
        if wire == 0:
            v = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, v
        elif wire == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            yield field, wire, buf[pos:pos + ln]
            pos += ln
        else:
            raise AssertionError(f"unexpected wire type {wire}")


def decode_response(data: bytes) -> dict:
    """Flattens a ProcessingResponse into {oneof, set_headers, body, status}."""
    out = {"oneof": None, "set_headers": {}, "body": None, "status": None,
           "has_dynamic_metadata": False, "body_eos": None}
    names = {1: "request_headers", 2: "response_headers", 3: "request_body",
             4: "response_body", 5: "request_trailers", 6: "response_trailers",
             7: "immediate"}

    def walk_common(buf):
        for f, w, v in _fields(buf):
            if f == 2 and w == 2:  # header_mutation
                walk_mutation(v)
            elif f == 3 and w == 2:  # body_mutation
                for f2, w2, v2 in _fields(v):
                    if f2 == 1:          # body (buffered mode)
                        out["body"] = v2
                    elif f2 == 3:        # streamed_response (duplex mode)
                        chunk, eos = b"", False
                        for f3, w3, v3 in _fields(v2):
                            if f3 == 1:
                                chunk = v3
                            elif f3 == 2:
                                eos = bool(v3)
                        out["body"] = (out["body"] or b"") + chunk
                        out["body_eos"] = eos

    def walk_mutation(buf):
        for f, w, v in _fields(buf):
            if f == 1 and w == 2:  # HeaderValueOption
                for f2, w2, v2 in _fields(v):
                    if f2 == 1 and w2 == 2:  # HeaderValue
                        key = raw = val = None
                        for f3, w3, v3 in _fields(v2):
                            if f3 == 1:
                                key = v3.decode()
                            elif f3 == 2:
                                val = v3.decode()
                            elif f3 == 3:
                                raw = v3.decode()
                        if key:
                            out["set_headers"][key] = raw or val or ""

    for field, wire, value in _fields(data):
        if field in names and wire == 2:
            out["oneof"] = names[field]
            if field == 7:  # ImmediateResponse
                for f, w, v in _fields(value):
                    if f == 1 and w == 2:  # HttpStatus
                        for f2, w2, v2 in _fields(v):
                            if f2 == 1:
                                out["status"] = v2
                    elif f == 2 and w == 2:
                        walk_mutation(v)
                    elif f == 3 and w == 2:
                        out["body"] = v
            else:
                for f, w, v in _fields(value):
                    if f == 1 and w == 2:  # CommonResponse
                        walk_common(v)
        elif field == 8 and wire == 2:
            out["has_dynamic_metadata"] = True
    return out


async def _call(channel, frames):
    call = channel.stream_stream(
        METHOD,
        request_serializer=lambda b: b,
        response_deserializer=lambda b: b)
    out = []
    stream = call(iter_frames(frames))
    async for raw in stream:
        out.append(decode_response(raw))
    return out


async def iter_frames(frames):
    for f in frames:
        yield f


ENG, GW = 18671, 18670


def test_ext_proc_grpc_full_stream():
    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {ENG}}}
modelRewrites:
  - {{source: alias-model, targets: [{{model: tiny, weight: 1}}]}}
""", port=GW, poll_interval=0.02, grpc_ext_proc_port=0)
        await gw.start()
        try:
            port = gw.grpc_ext_proc.port
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                req = json.dumps({"model": "alias-model", "prompt": "hi",
                                  "max_tokens": 2}).encode()
                resps = await _call(ch, [
                    req_headers_frame({":path": "/v1/completions",
                                       "content-type": "application/json"}),
                    req_body_frame(req),
                    resp_headers_frame({":status": "200"}),
                    resp_body_frame(json.dumps(
                        {"model": "tiny", "usage": {"completion_tokens": 2}}
                    ).encode()),
                ])
            assert [r["oneof"] for r in resps] == [
                "request_headers", "request_body",
                "response_headers", "response_body"]
            # Deferred headers response carries the destination mutation +
            # dynamic metadata (server.go:362); the body response carries
            # the mutated body.
            hdr_resp, body_resp = resps[0], resps[1]
            assert hdr_resp["set_headers"][
                "x-gateway-destination-endpoint"] == f"127.0.0.1:{ENG}"
            assert hdr_resp["has_dynamic_metadata"]
            # model rewrite applied on the way in...
            assert json.loads(body_resp["body"])["model"] == "tiny"
            # ...and un-rewritten on the way out (server.go:471-485)
            assert resps[2]["set_headers"][
                "x-gateway-destination-endpoint-served"] == f"127.0.0.1:{ENG}"
            assert json.loads(resps[3]["body"])["model"] == "alias-model"
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_ext_proc_grpc_immediate_response_on_bad_body():
    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {ENG}}}
""", port=GW, poll_interval=0.02, grpc_ext_proc_port=0)
        await gw.start()
        try:
            async with grpc.aio.insecure_channel(
                    f"127.0.0.1:{gw.grpc_ext_proc.port}") as ch:
                resps = await _call(ch, [
                    req_headers_frame({":path": "/v1/completions"}),
                    req_body_frame(b"this is not json"),
                ])
            assert resps[-1]["oneof"] == "immediate"
            assert resps[-1]["status"] == 400
            assert "x-removal-reason" in resps[-1]["set_headers"]
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_ext_proc_grpc_bodyless_fallback():
    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {ENG}}}
""", port=GW, poll_interval=0.02, grpc_ext_proc_port=0)
        await gw.start()
        try:
            async with grpc.aio.insecure_channel(
                    f"127.0.0.1:{gw.grpc_ext_proc.port}") as ch:
                resps = await _call(ch, [
                    req_headers_frame({":path": "/v1/completions"}, eos=True),
                ])
            # Bodyless → random-endpoint fallback (request.go:40-47).
            assert resps[0]["oneof"] == "request_headers"
            assert resps[0]["set_headers"][
                "x-gateway-destination-endpoint"] == f"127.0.0.1:{ENG}"
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_ext_proc_grpc_body_chunking_round_trip():
    """A mutated body >64 KB must reach Envoy as ≤62000-byte streamed chunks
    (Envoy rejects larger streamed chunks; reference chunking.go:24-58):
    header mutation on the first frame, end_of_stream + dynamic metadata on
    the last, reassembly byte-identical."""
    from llm_d_inference_scheduler_tpu.router.handlers.extproc_grpc import (
        BODY_BYTE_LIMIT,
    )

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {ENG}}}
""", port=GW, poll_interval=0.02, grpc_ext_proc_port=0)
        await gw.start()
        try:
            req = json.dumps({"model": "tiny",
                              "prompt": "long " * 30000,   # ~150 KB
                              "max_tokens": 1}).encode()
            assert len(req) > 2 * BODY_BYTE_LIMIT
            # Inbound side is chunked too (Envoy streams the request body).
            in_chunks = [req[i:i + BODY_BYTE_LIMIT]
                         for i in range(0, len(req), BODY_BYTE_LIMIT)]
            frames = [req_headers_frame({":path": "/v1/completions"})]
            frames += [req_body_frame(c, eos=False) for c in in_chunks[:-1]]
            frames.append(req_body_frame(in_chunks[-1], eos=True))
            async with grpc.aio.insecure_channel(
                    f"127.0.0.1:{gw.grpc_ext_proc.port}") as ch:
                resps = await _call(ch, frames)
            # Deferred headers response first (destination + metadata)...
            assert resps[0]["oneof"] == "request_headers"
            assert resps[0]["set_headers"][
                "x-gateway-destination-endpoint"] == f"127.0.0.1:{ENG}"
            assert resps[0]["has_dynamic_metadata"]
            # ...then the mutated body as ≤62000-byte streamed chunks.
            body_frames = [r for r in resps if r["oneof"] == "request_body"
                           and r["body"] is not None]
            assert len(body_frames) == len(in_chunks) >= 3
            assert all(not f["set_headers"] for f in body_frames)
            # end_of_stream on the last chunk only.
            assert [f["body_eos"] for f in body_frames] == \
                [False] * (len(body_frames) - 1) + [True]
            # Chunk sizes respect the limit; reassembly is byte-identical.
            assert all(len(f["body"]) <= BODY_BYTE_LIMIT for f in body_frames)
            assert b"".join(f["body"] for f in body_frames) == req
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())


def test_ext_proc_grpc_mid_stream_eviction():
    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny", port=ENG,
                                        sim_decode_ms_per_token=1.0))
        await eng.start()
        gw = build_gateway(f"""
objectives:
  - {{name: batch, priority: -1}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {ENG}}}
""", port=GW, poll_interval=0.02, grpc_ext_proc_port=0)
        await gw.start()
        try:
            async with grpc.aio.insecure_channel(
                    f"127.0.0.1:{gw.grpc_ext_proc.port}") as ch:
                call = ch.stream_stream(METHOD,
                                        request_serializer=lambda b: b,
                                        response_deserializer=lambda b: b)
                send_q: asyncio.Queue = asyncio.Queue()

                async def frames():
                    while True:
                        f = await send_q.get()
                        if f is None:
                            return
                        yield f

                stream = call(frames())
                req = json.dumps({"model": "tiny", "prompt": "x",
                                  "max_tokens": 50}).encode()
                await send_q.put(req_headers_frame({
                    ":path": "/v1/completions",
                    "x-gateway-inference-objective": "batch"}))
                await send_q.put(req_body_frame(req))
                r1 = decode_response(await stream.read())
                r2 = decode_response(await stream.read())
                assert r2["oneof"] == "request_body"
                # The scheduled sheddable request is now registered; evict it.
                assert gw.evictor.inflight_count == 1
                assert len(gw.evictor.evict_n(1)) == 1
                r3 = decode_response(await stream.read())
                assert r3["oneof"] == "immediate"
                assert r3["status"] == 429
                assert "x-removal-reason" in r3["set_headers"]
                await send_q.put(None)
        finally:
            await gw.stop()
            await eng.stop()

    asyncio.run(body())
