"""Full P/D disaggregation path: gateway → sidecar → prefill/decode engines.

BASELINE config #3 shape at CPU-test scale: the disagg profile handler gates a
remote prefill on the decode pod's prefix state, the sidecar runs the 2-phase
tpu-dcn connector, and the decode engine imports the prefilled KV.
"""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.sidecar import Sidecar, SidecarConfig

GW, SC, DEC, PRE = 18360, 18361, 18362, 18363

CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - {{type: approx-prefix-cache-producer}}
  - {{type: prefix-cache-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 16}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: prefix-cache-scorer, weight: 3}}
      - {{pluginRef: queue-scorer, weight: 2}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

LONG_PROMPT = "please summarise the following very important document: " * 4
SHORT_PROMPT = "hi"


def _engine(port, role):
    return EngineServer(EngineConfig(backend="tpu", model="tiny", port=port,
                                     max_batch=4, max_model_len=256, role=role))


def test_disagg_path_end_to_end():
    async def body():
        dec = _engine(DEC, "decode")
        pre = _engine(PRE, "prefill")
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   ssrf_allowlist=[f"127.0.0.1:{PRE}"]))
        await sc.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                # Monolithic reference answer straight from the decode engine.
                r = await c.post(f"http://127.0.0.1:{DEC}/v1/completions",
                                 json={"prompt": LONG_PROMPT, "max_tokens": 6,
                                       "temperature": 0})
                mono_text = r.json()["choices"][0]["text"]

                pre_prompt_tokens_before = _counter_value(
                    pre, "jetstream:prompt_tokens_total")

                # Through the router: long prompt → P/D split. SLO headers
                # opt the request into a defined-SLO ledger verdict.
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": LONG_PROMPT,
                                       "max_tokens": 6, "temperature": 0},
                                 headers={"x-request-id": "disagg-slo-1",
                                          "x-slo-ttft-ms": "60000"})
                assert r.status_code == 200
                assert r.headers["x-gateway-destination-endpoint-served"] == \
                    f"127.0.0.1:{SC}"
                assert r.json()["choices"][0]["text"] == mono_text

                # The prefill engine really prefilled.
                assert _counter_value(pre, "jetstream:prompt_tokens_total") > \
                    pre_prompt_tokens_before

                # Short prompt below threshold → decode-only (no prefill growth).
                pre_after = _counter_value(pre, "jetstream:prompt_tokens_total")
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": SHORT_PROMPT,
                                       "max_tokens": 2})
                assert r.status_code == 200
                assert _counter_value(pre, "jetstream:prompt_tokens_total") == pre_after

                # Router counted both decision types.
                m = await c.get(f"http://127.0.0.1:{GW}/metrics")
                assert 'disagg_decision_total{decision_type="prefill-decode"}' in m.text
                assert 'disagg_decision_total{decision_type="decode"}' in m.text

                # SLO-ledger outcome block on the decision record: predicted
                # vs actual vs SLO plus the per-pair transfer row (the P/D
                # request's KV pull was measured by the decode engine and
                # relayed sidecar → gateway).
                r = await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/disagg-slo-1")
                out = r.json()["outcome"]
                assert out["slo_met"] is True
                assert out["slo"] == {"ttft_ms": 60000.0, "tpot_ms": 0.0,
                                      "defined": True}
                assert out["actual"]["ttft_ms"] > 0
                assert out["actual"]["tokens"] == 6
                tr = out["transfer"]
                assert tr["prefill"] == f"127.0.0.1:{PRE}"
                assert tr["decode"] == f"127.0.0.1:{SC}"
                assert tr["pull_ms"] > 0 and tr["bytes"] > 0
                assert tr["prefill_ms"] > 0

                # Fleet rollups are non-empty: /debug/slo attainment + the
                # /debug/transfers per-pair EWMA row.
                slo = (await c.get(f"http://127.0.0.1:{GW}/debug/slo")).json()
                assert slo["totals"]["requests"] >= 2
                assert slo["totals"]["slo_met"] >= 2
                assert f"127.0.0.1:{SC}" in slo["endpoints"]
                transfers = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/transfers")).json()
                pair = next(p for p in transfers["pairs"]
                            if p["prefill"] == f"127.0.0.1:{PRE}"
                            and p["decode"] == f"127.0.0.1:{SC}")
                assert pair["pulls"] >= 1
                assert pair["ewma_pull_ms"] > 0
                assert pair["bytes_total"] > 0
                assert pair["ewma_prefill_ms"] > 0

                # And the router metric families observed the same pull.
                m = await c.get(f"http://127.0.0.1:{GW}/metrics")
                assert "router_kv_transfer_ms_count" in m.text
                assert 'router_goodput_tokens_total{model="tiny"}' in m.text

                # Golden cache block, P/D split (router/kvobs.py): the
                # first long-prompt request ran the 2-phase protocol, so
                # the sidecar relayed the PREFILL leg's engine-confirmed
                # hit headers (beside x-prefill-duration-ms, with
                # x-kv-prefiller naming the pod) and the DecisionRecord
                # joined them against the schedule-time per-candidate
                # prediction — decode pick AND prefill candidate.
                d = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/disagg-slo-1")
                    ).json()
                cache = d["cache"]
                assert f"127.0.0.1:{SC}" in cache["predicted"]
                assert f"127.0.0.1:{PRE}" in cache["predicted"]
                assert cache["chosen"] == f"127.0.0.1:{SC}"
                actual = cache["actual"]
                assert actual["pod"] == f"127.0.0.1:{PRE}"  # x-kv-prefiller
                assert actual["source"] == "headers"
                assert actual["tokens"] == 0  # cold prefill engine

                # Warm repeat: the approx index now knows the decode pod
                # holds the blocks, so the PD decider keeps it local — the
                # sidecar's local-decode fallback relays the DECODE
                # engine's hit headers instead, and the join attributes
                # the (real, >0) hit to the decode pod.
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": LONG_PROMPT,
                                       "max_tokens": 6, "temperature": 0},
                                 headers={"x-request-id": "disagg-kv-2"})
                assert r.status_code == 200
                assert int(r.headers["x-kv-hit-tokens"]) > 0  # relayed
                d = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/decisions/disagg-kv-2")
                    ).json()
                actual = d["cache"]["actual"]
                assert actual["pod"] == f"127.0.0.1:{SC}"
                assert actual["source"] == "headers"
                assert actual["tokens"] > 0 and actual["ratio"] > 0
                kv = (await c.get(f"http://127.0.0.1:{GW}/debug/kv")).json()
                assert kv["confirmed_joins"] >= 2
                assert f"127.0.0.1:{PRE}" in kv["pods"]
                assert f"127.0.0.1:{SC}" in kv["pods"]
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_disagg_fallback_when_prefill_dead():
    async def body():
        dec = _engine(DEC, "decode")
        await dec.start()
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   prefill_timeout_s=2.0))
        await sc.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)  # PRE never started
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": LONG_PROMPT,
                                       "max_tokens": 4})
                # Prefill target is dead: sidecar must fall back to local decode.
                assert r.status_code == 200
                assert len(r.json()["choices"][0]["text"]) > 0
        finally:
            await gw.stop()
            await sc.stop()
            await dec.stop()

    asyncio.run(body())


def test_sidecar_ssrf_allowlist():
    async def body():
        dec = _engine(DEC, "decode")
        await dec.start()
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   ssrf_allowlist=["10.0.0.1:9999"]))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post(f"http://127.0.0.1:{SC}/v1/completions",
                                 json={"prompt": "x", "max_tokens": 1},
                                 headers={"x-prefiller-host-port": "evil:1"})
                assert r.status_code == 403
        finally:
            await sc.stop()
            await dec.stop()

    asyncio.run(body())


def _counter_value(server: EngineServer, metric: str) -> float:
    text = server.engine.telemetry.render().decode()
    for line in text.splitlines():
        if line.startswith(metric + " ") or line.startswith(metric + "_total "):
            return float(line.split()[-1])
    return 0.0


def test_gateway_strips_client_injected_disagg_headers():
    """A client must not be able to steer the sidecar via x-prefiller-host-port
    (SSRF/decider bypass): the gateway strips router-owned headers."""
    async def body():
        dec = _engine(DEC, "decode")
        await dec.start()
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}"))
        await sc.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                # Short prompt (decode-only decision) + injected prefiller
                # header pointing at an attacker target.
                r = await c.post(
                    f"http://127.0.0.1:{GW}/v1/completions",
                    json={"model": "tiny", "prompt": SHORT_PROMPT,
                          "max_tokens": 2},
                    headers={"x-prefiller-host-port": "127.0.0.1:1"})
                # Served normally (no prefill attempt against the bogus host;
                # a forwarded header would stall the sidecar on connect).
                assert r.status_code == 200
                assert len(r.json()["choices"][0]["text"]) > 0
        finally:
            await gw.stop()
            await sc.stop()
            await dec.stop()

    asyncio.run(body())


def test_sidecar_chunked_decode_and_dp_ranks():
    """Chunked decode reassembles full text across max_tokens slices; DP rank
    listeners dispatch to per-rank decoder ports."""
    SC2, DEC2 = 18390, 18394  # SC2+rank must not collide with engine ports

    async def body():
        # two sim "DP rank" engines on consecutive ports
        e0 = EngineServer(EngineConfig(backend="sim", model="tiny", port=DEC2))
        e1 = EngineServer(EngineConfig(backend="sim", model="tiny", port=DEC2 + 1))
        await e0.start()
        await e1.start()
        sc = Sidecar(SidecarConfig(port=SC2, decoder_url=f"http://127.0.0.1:{DEC2}",
                                   decode_chunk_size=3, data_parallel_size=2))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # chunked: 8 tokens in chunks of 3 -> "lorem ip" reassembled
                r = await c.post(f"http://127.0.0.1:{SC2}/v1/completions",
                                 json={"prompt": "x", "max_tokens": 8})
                assert r.status_code == 200
                doc = r.json()
                assert doc["usage"]["completion_tokens"] == 8
                assert len(doc["choices"][0]["text"]) == 8

                # DP rank 1 listener dispatches to engine on DEC2+1
                r = await c.post(f"http://127.0.0.1:{SC2 + 1}/v1/completions",
                                 json={"prompt": "x", "max_tokens": 2})
                assert r.status_code == 200
        finally:
            await sc.stop()
            await e1.stop()
            await e0.stop()

    asyncio.run(body())


def test_shared_storage_connector():
    """Decode-first probe: cold cache -> cache_threshold -> remote prefill ->
    retry; warm cache -> served locally without touching the prefiller."""
    SC3, DEC3, PRE3 = 18396, 18397, 18398

    async def body():
        dec = EngineServer(EngineConfig(backend="tpu", model="tiny", port=DEC3,
                                        max_batch=4, max_model_len=256))
        pre = EngineServer(EngineConfig(backend="tpu", model="tiny", port=PRE3,
                                        max_batch=4, max_model_len=256,
                                        role="prefill"))
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC3, decoder_url=f"http://127.0.0.1:{DEC3}",
                                   connector="shared-storage",
                                   cache_hit_threshold=0.5))
        await sc.start()
        try:
            prompt = [1] + list(range(50, 98))  # 49 tokens, 3 full blocks
            async with httpx.AsyncClient(timeout=120) as c:
                pre_before = _counter_value(pre, "jetstream:prompt_tokens_total")
                r = await c.post(f"http://127.0.0.1:{SC3}/v1/completions",
                                 json={"prompt": prompt, "max_tokens": 4,
                                       "ignore_eos": True},
                                 headers={"x-prefiller-host-port":
                                          f"127.0.0.1:{PRE3}"})
                assert r.status_code == 200
                text1 = r.json()["choices"][0]["text"]
                # Cold cache -> the prefill leg ran remotely.
                assert _counter_value(pre, "jetstream:prompt_tokens_total") > pre_before

                # Second identical request: decode-side cache is warm (KV was
                # imported), so it's served locally without another prefill.
                # (Token equality across the imported-KV vs prefix-recompute
                # numeric paths is NOT asserted: with random weights, near-tie
                # argmaxes can flip between the two bitwise-different but
                # equally-valid computations.)
                pre_mid = _counter_value(pre, "jetstream:prompt_tokens_total")
                r = await c.post(f"http://127.0.0.1:{SC3}/v1/completions",
                                 json={"prompt": prompt, "max_tokens": 4,
                                       "ignore_eos": True},
                                 headers={"x-prefiller-host-port":
                                          f"127.0.0.1:{PRE3}"})
                assert r.status_code == 200
                assert len(r.json()["choices"][0]["text"]) > 0 and text1
                assert _counter_value(pre, "jetstream:prompt_tokens_total") == pre_mid
        finally:
            await sc.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_data_parallel_profile_handler():
    """DP handler writes x-data-parallel-host-port from the dp-size label; the
    sidecar dispatches to that rank's engine; out-of-range targets ignored."""
    GW4, SC4, E0, E1 = 18440, 18441, 18445, 18446

    cfg = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC4},
       labels: {{llm-d.ai/role: decode, llm-d.ai/dp-size: "2"}}}}
plugins:
  - {{type: queue-scorer}}
  - {{type: data-parallel-profile-handler}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: queue-scorer}}
"""

    async def body():
        engines = [EngineServer(EngineConfig(backend="sim", model="tiny", port=p))
                   for p in (E0, E1)]
        for e in engines:
            await e.start()
        sc = Sidecar(SidecarConfig(port=SC4, decoder_url=f"http://127.0.0.1:{E0}",
                                   data_parallel_size=2))
        await sc.start()
        gw = build_gateway(cfg, port=GW4, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                served_ranks = set()
                for _ in range(4):
                    r = await c.post(f"http://127.0.0.1:{GW4}/v1/completions",
                                     json={"model": "tiny", "prompt": "x",
                                           "max_tokens": 2})
                    assert r.status_code == 200
                # round-robin must have touched both rank engines
                m0 = (await c.get(f"http://127.0.0.1:{E0}/metrics")).text
                m1 = (await c.get(f"http://127.0.0.1:{E1}/metrics")).text
                for m in (m0, m1):
                    for line in m.splitlines():
                        if line.startswith("jetstream:generation_tokens_total "):
                            served_ranks.add(float(line.split()[-1]) > 0)
                assert served_ranks == {True}

                # out-of-range header at the sidecar -> ignored, still served
                r = await c.post(f"http://127.0.0.1:{SC4}/v1/completions",
                                 json={"prompt": "x", "max_tokens": 1},
                                 headers={"x-data-parallel-host-port":
                                          "127.0.0.1:9"})
                assert r.status_code == 200
        finally:
            await gw.stop()
            await sc.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())

def test_sidecar_proxies_kv_events_stream():
    """The precise-prefix SSE subscriber must work against sidecar-fronted
    decode endpoints: GET /kv_events is stream-proxied (ADVICE r1)."""
    DEC6, SC6 = 18375, 18376

    async def body():
        dec = _engine(DEC6, "decode")
        await dec.start()
        sc = Sidecar(SidecarConfig(port=SC6, decoder_url=f"http://127.0.0.1:{DEC6}"))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                # Generate so the engine publishes stored block hashes.
                r = await c.post(f"http://127.0.0.1:{DEC6}/v1/completions",
                                 json={"prompt": "hello " * 20, "max_tokens": 2})
                assert r.status_code == 200

                got_stored = False
                async with c.stream(
                        "GET", f"http://127.0.0.1:{SC6}/kv_events") as resp:
                    assert resp.status_code == 200
                    assert "text/event-stream" in resp.headers["content-type"]
                    async for line in resp.aiter_lines():
                        if line.startswith("data: ") and '"stored"' in line:
                            got_stored = True
                            break
                assert got_stored
        finally:
            await sc.stop()
            await dec.stop()

    asyncio.run(body())


def test_golden_decision_record_disagg_with_chaos_failover():
    """Golden DecisionRecord through the disagg path: the full record for one
    request must show admission (flow control: queue time + band), the
    prefill profile's filter drops, the decode profile's per-endpoint scorer
    table and picker pick, and a chaos-induced failover attempt trail —
    first attempt against a chaos-reset decode endpoint, reschedule, then
    success via the healthy sidecar-fronted decode pod."""
    GW7, EA7, SC7, DEC7, PRE7 = 18960, 18961, 18962, 18963, 18964

    cfg = f"""
featureGates: {{flowControl: true}}
decisions: {{topK: 4}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {EA7}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {SC7}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE7}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: header-based-testing-filter}}
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 16}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: header-based-testing-filter}}
      - {{pluginRef: queue-scorer, weight: 2}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

    async def body():
        # Chaos decode endpoint: resets every connection (deterministic shim).
        ea = EngineServer(EngineConfig(backend="sim", model="tiny", port=EA7,
                                       chaos="reset:100"))
        dec = _engine(DEC7, "decode")
        pre = _engine(PRE7, "prefill")
        await ea.start()
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC7,
                                   decoder_url=f"http://127.0.0.1:{DEC7}",
                                   ssrf_allowlist=[f"127.0.0.1:{PRE7}"]))
        await sc.start()
        gw = build_gateway(cfg, port=GW7, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                r = await c.post(
                    f"http://127.0.0.1:{GW7}/v1/completions",
                    json={"model": "tiny", "prompt": LONG_PROMPT,
                          "max_tokens": 4, "temperature": 0},
                    headers={"x-request-id": "golden-disagg-1",
                             "x-debug-decision": "summary",
                             "test-epp-endpoint-selection":
                                 f"127.0.0.1:{EA7}"})
                assert r.status_code == 200
                # Failover landed on the healthy sidecar-fronted pod.
                assert r.headers["x-gateway-destination-endpoint-served"] == \
                    f"127.0.0.1:{SC7}"
                assert ea.chaos.triggered["reset"] > 0
                assert f"winner=127.0.0.1:" in r.headers["x-decision-summary"]

                r = await c.get(f"http://127.0.0.1:{GW7}"
                                "/debug/decisions/golden-disagg-1")
                assert r.status_code == 200
                rec = r.json()
                assert rec["schema_version"] == 1

                # Admission: flow-control verdict with queue time + band.
                adm = rec["admission"]
                assert adm["mechanism"] == "flow-control"
                assert adm["outcome"] == "dispatched"
                assert adm["priority_band"] == 0
                assert adm["queue_ms"] >= 0

                # Round 1 (schedule): decode profile — filter drops recorded
                # per filter, per-endpoint weighted scorer table, picker pick
                # of the (chaos) endpoint the test header forced.
                assert [rd["reason"] for rd in rec["rounds"]] == \
                    ["schedule", "reschedule"]
                d1 = rec["rounds"][0]["profiles"]["decode"]
                by_plugin = {f["plugin"].split("/")[0]: f
                             for f in d1["filters"]}
                assert f"127.0.0.1:{PRE7}" in \
                    by_plugin["decode-filter"]["dropped"]
                assert f"127.0.0.1:{SC7}" in \
                    by_plugin["header-based-testing-filter"]["dropped"]
                qs = d1["scorers"]["queue-scorer/queue-scorer"]
                assert qs["weight"] == 2.0
                assert f"127.0.0.1:{EA7}" in qs["scores"]
                assert set(qs["scores"][f"127.0.0.1:{EA7}"]) == \
                    {"raw", "weighted"}
                assert d1["picker"]["picked"] == [f"127.0.0.1:{EA7}"]

                # Round 1: prefill profile — role filter drops both decode
                # endpoints, prefill pod picked.
                p1 = rec["rounds"][0]["profiles"]["prefill"]
                pf = next(f for f in p1["filters"]
                          if f["plugin"].startswith("prefill-filter"))
                assert set(pf["dropped"]) == {f"127.0.0.1:{EA7}",
                                              f"127.0.0.1:{SC7}"}
                assert p1["picker"]["picked"] == [f"127.0.0.1:{PRE7}"]

                # Round 2 (failover reschedule): the healthy pod wins.
                d2 = rec["rounds"][1]["profiles"]["decode"]
                assert d2["picker"]["picked"] == [f"127.0.0.1:{SC7}"]

                # Attempt trail: chaos connect failure → reschedule event
                # (excluding the broken pod) → success on the sidecar.
                attempts = rec["attempts"]
                assert attempts[0]["endpoint"] == f"127.0.0.1:{EA7}"
                assert attempts[0]["outcome"] == "connect"
                resched = next(a for a in attempts if a.get("event") ==
                               "reschedule")
                assert f"127.0.0.1:{EA7}" in resched["excluded"]
                ok = attempts[-1]
                assert ok["endpoint"] == f"127.0.0.1:{SC7}"
                assert ok["outcome"] == "ok" and ok["status"] == 200

                assert rec["final"]["status"] == 200
                assert rec["final"]["destination"] == f"127.0.0.1:{SC7}"

                # Outcome block closes the loop even on the failover path:
                # no SLO headers → vacuously met, e2e/TTFT still measured.
                out = rec["outcome"]
                assert out["slo_met"] is True
                assert out["slo"]["defined"] is False
                assert out["actual"]["e2e_ms"] > 0
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()
            await ea.stop()

    asyncio.run(body())


def test_golden_disagg_waterfall_and_stream_header_time_join():
    """Golden tail waterfall through the full disagg path (router/tails.py):
    the decision record's waterfall block must decompose the request into
    queue (flow-control wait) + sched + prefill + kv_transfer + decode
    residual — every stage > 0, stages summing back to the TTFT — and the
    /debug/tails cohort ledger must have absorbed it. Second half: the
    per-pair TransferTable row must land at HEADER time for STREAMED
    responses too (the PR 10 gap), observable while the stream is open."""
    GW8, SC8, DEC8, PRE8 = 18990, 18991, 18992, 18993

    cfg = f"""
featureGates: {{flowControl: true}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC8}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE8}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 16}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer, weight: 2}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

    async def body():
        dec = _engine(DEC8, "decode")
        pre = _engine(PRE8, "prefill")
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC8,
                                   decoder_url=f"http://127.0.0.1:{DEC8}",
                                   ssrf_allowlist=[f"127.0.0.1:{PRE8}"]))
        await sc.start()
        gw = build_gateway(cfg, port=GW8, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                r = await c.post(f"http://127.0.0.1:{GW8}/v1/completions",
                                 json={"model": "tiny", "prompt": LONG_PROMPT,
                                       "max_tokens": 4, "temperature": 0},
                                 headers={"x-request-id": "wf-gold-1",
                                          "x-debug-decision": "summary"})
                assert r.status_code == 200
                # The echo header leaves before the waterfall closes, so
                # it carries the pre-close summary; the post-close list
                # view's summary (below) gains the TTFT note.
                assert "winner=" in r.headers["x-decision-summary"]

                lst = (await c.get(f"http://127.0.0.1:{GW8}"
                                   "/debug/decisions?n=5")).json()
                row = next(d for d in lst["decisions"]
                           if d["request_id"] == "wf-gold-1")
                assert "ttft=" in row["summary"]

                rec = (await c.get(f"http://127.0.0.1:{GW8}"
                                   "/debug/decisions/wf-gold-1")).json()
                wf = rec["waterfall"]
                assert wf["verdict"] == "ok"
                assert wf["cohort"] == "tiny|b0|unary"
                st = wf["stages"]
                # Every critical-path stage measured and positive: the
                # flow-control queue wait, the scheduling cycle, the
                # prefill leg, the measured KV pull, and the decode
                # residual that absorbs the rest of the TTFT.
                for stage in ("queue", "sched", "prefill", "kv_transfer",
                              "decode"):
                    assert st.get(stage, 0) > 0, f"stage {stage} missing"
                # Non-streamed: TTFT == e2e, and the stages (decode being
                # the residual) reassemble it to rounding tolerance.
                assert wf["ttft_ms"] > 0
                assert abs(wf["e2e_ms"] - wf["ttft_ms"]) < 5.0
                assert abs(sum(st.values()) - wf["ttft_ms"]) < 5.0
                assert wf["pair"] == \
                    f"127.0.0.1:{PRE8}→127.0.0.1:{SC8}"

                # The tail observatory absorbed the served request.
                tails = (await c.get(
                    f"http://127.0.0.1:{GW8}/debug/tails")).json()
                assert tails["enabled"] is True
                cohort = tails["cohorts"]["tiny|b0|unary"]
                assert cohort["closed"] >= 1
                assert cohort["digests"]["kv_transfer"]["n"] >= 1

                # And the stage histogram family saw the same close.
                m = await c.get(f"http://127.0.0.1:{GW8}/metrics")
                assert 'router_stage_ms_count{stage="kv_transfer"}' in m.text

                # ---- streamed header-time pair landing (PR 10 gap) ----
                tr = (await c.get(
                    f"http://127.0.0.1:{GW8}/debug/transfers")).json()
                row = next(p for p in tr["pairs"]
                           if p["prefill"] == f"127.0.0.1:{PRE8}")
                stamp_before = row["last_unix"]

                # A DIFFERENT long prompt (cold for the approx index, so
                # the PD decider splits again), streamed this time.
                stream_prompt = ("stream this other important document: "
                                 * 4)
                async with c.stream(
                        "POST", f"http://127.0.0.1:{GW8}/v1/completions",
                        json={"model": "tiny", "prompt": stream_prompt,
                              "max_tokens": 64, "stream": True},
                        headers={"x-request-id": "wf-stream-1"}) as sr:
                    assert sr.status_code == 200
                    # Response headers are on the wire but the token
                    # stream is NOT consumed yet: the pair row must have
                    # landed already (header-time join — pre-PR-18 it
                    # waited for the terminal usage chunk).
                    tr = (await c.get(
                        f"http://127.0.0.1:{GW8}/debug/transfers")).json()
                    row = next(p for p in tr["pairs"]
                               if p["prefill"] == f"127.0.0.1:{PRE8}")
                    assert row["last_unix"] > stamp_before
                    assert row["ewma_prefill_ms"] > 0
                    async for _ in sr.aiter_bytes():
                        pass
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_pd_pipeline_token_parity_exposed_cost_and_waterfall():
    """Pipelined P/D (ISSUE 20): with `pipeline_enabled` the sidecar
    dispatches the decode leg on first-chunk ack and the decode engine
    chunk-streams the KV while prefill computes. Gates: token parity with
    the serial 2-phase arm; the serial arm's response headers bit-identical
    to the pre-PR protocol (no exposed stamp — kill-switch contract); the
    pipelined response carries x-kv-transfer-exposed-ms <= x-kv-transfer-ms;
    the waterfall's kv_transfer stage holds the EXPOSED cost so stage sums
    still reconcile vs TTFT; /debug/transfers lands the exposed EWMA."""
    GW9, SC9, SC9P, DEC9, PRE9 = 18860, 18861, 18862, 18863, 18864

    cfg = f"""
featureGates: {{flowControl: true}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC9P}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE9}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider: always-disagg-pd-decider
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

    async def body():
        dec = _engine(DEC9, "decode")
        pre = _engine(PRE9, "prefill")
        await dec.start()
        await pre.start()
        sc_serial = Sidecar(SidecarConfig(
            port=SC9, decoder_url=f"http://127.0.0.1:{DEC9}",
            ssrf_allowlist=[f"127.0.0.1:{PRE9}"]))
        sc_pipe = Sidecar(SidecarConfig(
            port=SC9P, decoder_url=f"http://127.0.0.1:{DEC9}",
            ssrf_allowlist=[f"127.0.0.1:{PRE9}"],
            pipeline_enabled=True))
        await sc_serial.start()
        await sc_pipe.start()
        gw = build_gateway(cfg, port=GW9, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                # Pipelined arm through the gateway, cold caches: the
                # decode leg MUST chunk-stream the KV (a warm decode-side
                # prefix would skip the pull and hide the transfer).
                r = await c.post(
                    f"http://127.0.0.1:{GW9}/v1/completions",
                    json={"model": "tiny", "prompt": LONG_PROMPT,
                          "max_tokens": 6, "temperature": 0},
                    headers={"x-request-id": "pipe-gold-1"})
                assert r.status_code == 200
                pipe_text = r.json()["choices"][0]["text"]

                # Token parity: the serial 2-phase arm over the same
                # prompt (prefixes now warm — that changes timing, never
                # greedy logits) produces the identical continuation.
                r = await c.post(
                    f"http://127.0.0.1:{SC9}/v1/completions",
                    json={"prompt": LONG_PROMPT, "max_tokens": 6,
                          "temperature": 0},
                    headers={"x-prefiller-host-port": f"127.0.0.1:{PRE9}"})
                assert r.status_code == 200, r.text
                assert r.json()["choices"][0]["text"] == pipe_text

                # Kill-switch contract on a cold prompt: the serial
                # sidecar's headers stay bit-identical to the pre-pipeline
                # protocol — raw pull stamped, NO exposed stamp.
                r = await c.post(
                    f"http://127.0.0.1:{SC9}/v1/completions",
                    json={"prompt": "a different saga about container "
                          "fleets sailing the high seas " * 4,
                          "max_tokens": 6, "temperature": 0},
                    headers={"x-prefiller-host-port": f"127.0.0.1:{PRE9}"})
                assert r.status_code == 200
                assert float(r.headers["x-kv-transfer-ms"]) > 0
                assert "x-kv-transfer-exposed-ms" not in r.headers

                # Waterfall: the gateway consumed the transfer headers
                # (they are not relayed to clients) — kv_transfer carries
                # the EXPOSED cost, overlap_ms rides beside it excluded
                # from the accounted sum, and stage sums still reconcile
                # vs TTFT (no double-counted transfer time). overlap_ms
                # present at all proves the chunk-streamed pull ran: the
                # serial 2-phase path never stamps an exposed split.
                rec = (await c.get(f"http://127.0.0.1:{GW9}"
                                   "/debug/decisions/pipe-gold-1")).json()
                wf = rec["waterfall"]
                assert wf["verdict"] == "ok"
                st = wf["stages"]
                exposed = st.get("kv_transfer", 0.0)
                overlap = wf["overlap_ms"]
                assert exposed >= 0 and overlap > 0
                assert abs(sum(st.values()) - wf["ttft_ms"]) < 10.0
                assert wf["pair"] == f"127.0.0.1:{PRE9}→127.0.0.1:{SC9P}"

                # The pair EWMA table landed the exposed cost beside the
                # raw pull EWMA.
                tr = (await c.get(
                    f"http://127.0.0.1:{GW9}/debug/transfers")).json()
                pair = next(p for p in tr["pairs"]
                            if p["prefill"] == f"127.0.0.1:{PRE9}"
                            and p["decode"] == f"127.0.0.1:{SC9P}")
                assert pair["pulls"] >= 1
                assert pair["ewma_pull_ms"] > 0
                assert pair["exposed_ms"] <= pair["ewma_pull_ms"]

                # And the new histogram families observed the request.
                m = (await c.get(f"http://127.0.0.1:{GW9}/metrics")).text
                v = next(ln.split()[-1] for ln in m.splitlines()
                         if ln.startswith("router_kv_transfer_exposed_ms_count"))
                assert float(v) >= 1
                ms = (await c.get(
                    f"http://127.0.0.1:{SC9P}/metrics")).text
                v = next(ln.split()[-1] for ln in ms.splitlines()
                         if ln.startswith("sidecar_kv_overlap_ms_count"))
                assert float(v) >= 1
        finally:
            await gw.stop()
            await sc_pipe.stop()
            await sc_serial.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_kv_chunk_longpoll_timeout_and_gap_edges():
    """The /kv chunk surface's protocol edges (ISSUE 20): a bounded
    long-poll for a not-yet-staged chunk expires 202 (not a hang, not an
    error); a chunk index past the end of a COMPLETE export answers 204
    with the final metadata; an unknown rid 404s even with a wait; the ack
    probe releases as soon as the first chunk stages."""
    E10 = 18865

    async def body():
        # Slow streamed prefill: 64 tokens at 10 ms/token over 16-token
        # windows -> 4 chunks ~160 ms apart, plenty to observe mid-stream.
        srv = EngineServer(EngineConfig(
            backend="sim", model="tiny", port=E10, max_batch=4,
            prefill_chunk=16, sim_prefill_ms_per_token=10.0))
        await srv.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                gen = asyncio.create_task(c.post(
                    f"http://127.0.0.1:{E10}/v1/completions",
                    json={"prompt": list(range(3, 67)), "max_tokens": 1,
                          "request_id": "lp-1",
                          "kv_transfer_params": {"do_remote_decode": True,
                                                 "stream_chunks": True}}))
                base = f"http://127.0.0.1:{E10}/kv/lp-1"
                # Unknown rid (export not created yet is indistinguishable
                # from never-existed): bounded wait, then 404.
                r = await c.get(f"http://127.0.0.1:{E10}/kv/nope",
                                params={"chunk": 0, "wait_ms": 30})
                assert r.status_code == 404

                # Ack long-poll: 200 the moment the first chunk stages.
                t0 = asyncio.get_event_loop().time()
                while True:
                    r = await c.get(base, params={"ack": "1",
                                                  "wait_ms": 1000})
                    if r.status_code == 200:
                        break
                    assert r.status_code in (202, 404)
                    assert asyncio.get_event_loop().time() - t0 < 20
                assert int(r.headers["x-kv-chunks-staged"]) >= 1

                # A far-future chunk with a short wait: 202 (mid-stream,
                # chunk not staged yet), carrying the staging progress.
                r = await c.get(base, params={"chunk": 30, "wait_ms": 40})
                if r.headers.get("x-kv-complete") != "1":
                    assert r.status_code == 202
                    assert int(r.headers["x-kv-chunks-staged"]) < 30

                # Chunk 0 is staged: served immediately (sim: headers only).
                r = await c.get(base, params={"chunk": 0, "wait_ms": 100})
                assert r.status_code == 200
                assert r.headers["x-kv-chunk"] == "0"
                assert int(r.headers["x-kv-chunk-blocks"]) >= 1

                resp = await gen
                assert resp.status_code == 200

                # Complete export: a past-the-end chunk answers 204 with
                # the terminal metadata (the puller's stop signal).
                # Long-poll until the completion flag lands.
                t0 = asyncio.get_event_loop().time()
                while True:
                    r = await c.get(base, params={"chunk": 99,
                                                  "wait_ms": 500})
                    if r.status_code == 204:
                        break
                    assert asyncio.get_event_loop().time() - t0 < 20
                assert r.headers["x-kv-complete"] == "1"
                staged = int(r.headers["x-kv-chunks-staged"])
                assert staged >= 2
                assert int(r.headers["x-kv-blocks-staged"]) >= 4

                # Every staged chunk is individually addressable.
                blocks = 0
                for i in range(staged):
                    r = await c.get(base, params={"chunk": i})
                    assert r.status_code == 200
                    blocks += int(r.headers["x-kv-chunk-blocks"])
                assert blocks == int(r.headers["x-kv-blocks-staged"])

                r = await c.delete(base)
                assert r.status_code == 200
                r = await c.get(base, params={"chunk": 0, "wait_ms": 10})
                assert r.status_code == 404
        finally:
            await srv.stop()

    asyncio.run(body())
