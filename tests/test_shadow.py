"""Shadow policy evaluation (router/shadow.py): config plumbing, the
transfer-pair policy's verdict/judge matrix, the evaluator's single-worker
ledger, the ?divergent decision filter, fleet merges, the sim per-peer
transfer topology, and the live e2e where a seeded skew makes the policy
diverge and the judged regret lands at /debug/decisions/<id>."""

import asyncio
import time
import types

import httpx
import pytest

from llm_d_inference_scheduler_tpu.router.datalayer.transfers import (
    TransferTable,
)
from llm_d_inference_scheduler_tpu.router.decisions import (
    DecisionConfig,
    DecisionRecorder,
    record_matches,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    ProfileRunResult,
    SchedulingResult,
)
from llm_d_inference_scheduler_tpu.router.shadow import (
    ShadowConfig,
    ShadowEvaluator,
    TransferAwarePairPolicy,
    UNMEASURED_PAIR_SCORE,
    merge_shadow,
    transfer_pair_scores,
)

DEC = "127.0.0.1:9001"
P0, P1, P2 = "127.0.0.1:9101", "127.0.0.1:9102", "127.0.0.1:9103"


def _ep(addr: str) -> Endpoint:
    host, _, port = addr.rpartition(":")
    return Endpoint(EndpointMetadata(name=addr, address=host, port=int(port)))


def _result(prefill: str = P0, decode: str = DEC,
            totals: dict | None = None) -> SchedulingResult:
    pr = ProfileRunResult(target_endpoints=[_ep(prefill)],
                          totals=totals if totals is not None
                          else {P0: 1.0, P1: 1.0})
    dr = ProfileRunResult(target_endpoints=[_ep(decode)])
    return SchedulingResult(profile_results={"decode": dr, "prefill": pr},
                            primary_profile_name="decode")


def _req(rid: str = "req-1", recorder: DecisionRecorder | None = None
         ) -> InferenceRequest:
    req = InferenceRequest(
        request_id=rid, target_model="tiny",
        body=InferenceRequestBody(completions={"prompt": "p"}))
    if recorder is not None:
        req.decision = recorder.start(rid, "tiny")
    return req


def _datastore() -> types.SimpleNamespace:
    return types.SimpleNamespace(transfers=TransferTable())


def _pair_cfg(**kw) -> ShadowConfig:
    spec = {"policies": [{"type": "transfer-pair",
                          "parameters": {"weight": 2.0}}], **kw}
    return ShadowConfig.from_spec(spec)


# ---- config ---------------------------------------------------------------


def test_shadow_config_parse_and_validation():
    cfg = ShadowConfig.from_spec(None)
    assert cfg.enabled and cfg.policies == [] and cfg.sample_rate == 1.0
    cfg = ShadowConfig.from_spec({"enabled": False, "sampleRate": 0.25,
                                  "capacity": 7,
                                  "policies": ["transfer-pair"]})
    assert not cfg.enabled and cfg.sample_rate == 0.25 and cfg.capacity == 7
    with pytest.raises(ValueError):
        ShadowConfig.from_spec({"sampleRate": 1.5})


def test_unknown_policy_raises_at_build():
    with pytest.raises(ValueError, match="unknown shadow policy"):
        ShadowEvaluator(ShadowConfig.from_spec({"policies": ["bogus"]}),
                        datastore=_datastore())


# ---- pair scoring ---------------------------------------------------------


def test_transfer_pair_scores_normalization():
    table = TransferTable()
    table.record(P0, DEC, pull_ms=40.0)
    table.record(P1, DEC, pull_ms=4.0)
    scores = transfer_pair_scores(table, DEC, [P0, P1, P2])
    assert scores[P1] == 1.0 and scores[P0] == 0.0
    assert scores[P2] == UNMEASURED_PAIR_SCORE  # no row: neutral
    # One distinct measured cost (all-equal, or a single measured pair)
    # carries no comparative signal → everything neutral. A sole measured
    # slow pair must NOT outrank unmeasured alternatives, or the live
    # scorer self-reinforces onto it and never explores.
    flat = TransferTable()
    flat.record(P0, DEC, pull_ms=5.0)
    flat.record(P1, DEC, pull_ms=5.0)
    assert transfer_pair_scores(flat, DEC, [P0, P1]) == \
        {P0: UNMEASURED_PAIR_SCORE, P1: UNMEASURED_PAIR_SCORE}
    solo = TransferTable()
    solo.record(P0, DEC, pull_ms=50.0)  # slow, and the only measurement
    assert transfer_pair_scores(solo, DEC, [P0, P1]) == \
        {P0: UNMEASURED_PAIR_SCORE, P1: UNMEASURED_PAIR_SCORE}
    # No measured pair at all → None (the policy abstains, not noise).
    assert transfer_pair_scores(TransferTable(), DEC, [P0, P1]) is None


def test_policy_diverges_to_cheap_pair():
    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=40.0)
    ds.transfers.record(P1, DEC, pull_ms=4.0)
    policy = TransferAwarePairPolicy({"weight": 2.0}, ds)
    entry = policy.evaluate(_req(), _result(prefill=P0))
    assert entry["verdict"] == "diverge"
    assert entry["shadow"]["prefill"] == P1
    assert entry["live"] == {"prefill": P0, "decode": DEC}
    assert entry["margin"] > 0


def test_policy_agrees_when_live_pair_is_cheapest():
    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=4.0)
    ds.transfers.record(P1, DEC, pull_ms=40.0)
    policy = TransferAwarePairPolicy({"weight": 2.0}, ds)
    entry = policy.evaluate(_req(), _result(prefill=P0))
    assert entry["verdict"] == "agree"
    # Equal costs → tie; ties keep the live pick (a tie must never mint
    # a divergence — there is no counterfactual benefit to judge).
    flat = _datastore()
    flat.transfers.record(P0, DEC, pull_ms=5.0)
    flat.transfers.record(P1, DEC, pull_ms=5.0)
    entry = TransferAwarePairPolicy({}, flat).evaluate(
        _req(), _result(prefill=P0))
    assert entry["verdict"] == "agree"


def test_policy_live_twin_active_no_double_count():
    """With transfer-aware-pair-scorer ALREADY in the live profile, the
    live totals include its weighted contribution — re-adding it would
    score base + 2w×t and mint false divergences against the very policy
    that is live. The counterfactual then IS the live policy: agree."""
    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=40.0)
    ds.transfers.record(P1, DEC, pull_ms=4.0)
    policy = TransferAwarePairPolicy({"weight": 2.0}, ds)
    # Live totals where the pair term was applied but the base score
    # still carried P0 to the win: queue=1.0/0.0 + 2*t(0.0/1.0) would be
    # p0=1.0+0=1.0... pick a case where re-adding 2*t WOULD flip: base
    # favors P0 by 1.0, pair favors P1 by 2.0*1.0 → live totals P0=1.0,
    # P1=2.0 → live (pair-aware) picked P1. Shadow must NOT re-add and
    # report divergence against P1's runner-up.
    res = _result(prefill=P1, totals={P0: 1.0, P1: 2.0})
    res.profile_results["prefill"].raw_scores = {
        "transfer-aware-pair-scorer/transfer-aware-pair-scorer":
            {P0: 0.0, P1: 1.0},
        "queue-scorer/queue-scorer": {P0: 1.0, P1: 0.0},
    }
    entry = policy.evaluate(_req(), res)
    assert entry["verdict"] == "agree"
    assert entry.get("live_twin_active") is True
    # Without the guard the same totals WOULD diverge (sanity check that
    # the scenario is discriminating): base-only totals diverge to P1.
    res2 = _result(prefill=P0, totals={P0: 1.0, P1: 0.0})
    assert policy.evaluate(_req(), res2)["verdict"] == "diverge"


def test_policy_ineligible_and_no_signal():
    ds = _datastore()
    policy = TransferAwarePairPolicy({}, ds)
    # No prefill profile (decode-only / classifier skip) → ineligible.
    res = _result()
    del res.profile_results["prefill"]
    assert policy.evaluate(_req(), res) is None
    # Prefill ran but the table is empty → no_signal (abstain).
    entry = policy.evaluate(_req(), _result())
    assert entry["verdict"] == "no_signal"


def test_policy_judge_matrix():
    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=40.0)
    ds.transfers.record(P1, DEC, pull_ms=4.0)
    policy = TransferAwarePairPolicy({}, ds)
    # Divergence judged against this request's MEASURED pull.
    entry = policy.evaluate(_req(), _result(prefill=P0))
    verdict, regret = policy.judge(
        entry, {"transfer": {"prefill": P0, "decode": DEC, "pull_ms": 38.0}})
    assert verdict == "diverge"
    assert regret == pytest.approx(38.0 - 4.0)
    assert entry["judged"]["live_source"] == "measured"
    # Second judge call is a no-op (first wins via the judged marker).
    assert policy.judge(entry, {"transfer": {"pull_ms": 1.0}}) is None
    # Streamed response (no pull stats) → live falls back to its own EWMA.
    entry = policy.evaluate(_req(), _result(prefill=P0))
    verdict, regret = policy.judge(entry, {"transfer": None})
    assert verdict == "diverge" and regret == pytest.approx(40.0 - 4.0)
    assert entry["judged"]["live_source"] == "ewma"
    # Shadow pair with no EWMA → estimate unavailable, never guessed.
    ds2 = _datastore()
    ds2.transfers.record(P0, DEC, pull_ms=40.0)
    p2 = TransferAwarePairPolicy({}, ds2)
    e2 = p2.evaluate(_req(), _result(prefill=P0,
                                     totals={P0: 0.0, P1: 2.0}))
    assert e2["verdict"] == "diverge"  # P1 unmeasured 0.5 but huge base
    verdict, regret = p2.judge(e2, {"transfer": None})
    assert verdict == "diverge" and regret is None
    assert e2["judged"] == {"estimate": "unavailable"}
    # Agreement credits the measured value; an EWMA-fallback agreement
    # (streamed response, no pull stats) must NOT feed the measured tally
    # — that would blend the table's own estimates into it.
    e3 = policy.evaluate(_req(), _result(prefill=P1))
    assert e3["verdict"] == "agree"
    verdict, value = policy.judge(
        e3, {"transfer": {"pull_ms": 3.5}})
    assert verdict == "agree" and value == 3.5
    e4 = policy.evaluate(_req(), _result(prefill=P1))
    verdict, value = policy.judge(e4, {"transfer": None})
    assert verdict == "agree" and value is None
    assert e4["judged"]["source"] == "ewma"


# ---- evaluator ------------------------------------------------------------


def test_evaluator_end_to_end_rollup():
    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=40.0)
    ds.transfers.record(P1, DEC, pull_ms=4.0)
    ev = ShadowEvaluator(_pair_cfg(), datastore=ds)
    recorder = DecisionRecorder(DecisionConfig())
    try:
        req = _req("shadow-roll-1", recorder)
        ev.submit(req, _result(prefill=P0))
        assert ev.flush()
        assert req.shadow is not None and req.shadow.entries is not None
        # The record carries the block the worker stamped.
        block = recorder.get("shadow-roll-1").shadow
        assert block["diverged"] is True
        assert block["policies"]["transfer-pair"]["verdict"] == "diverge"
        assert "shadow=diverge:transfer-pair" in \
            recorder.get("shadow-roll-1").summary_line()
        # Judge with a measured outcome.
        ev.observe_response(req, transfer={"prefill": P0, "decode": DEC,
                                           "pull_ms": 38.0}, status=200)
        assert ev.flush()
        snap = ev.snapshot()
        row = snap["policies"]["transfer-pair"]
        assert snap["submitted"] == 1 and row["evaluated"] == 1
        assert row["divergences"] == 1 and row["agreement_rate"] == 0.0
        assert row["coverage"] == 1.0
        assert row["judged"]["divergences"] == 1
        assert row["est_regret_ms"]["n"] == 1
        assert row["est_regret_ms"]["mean"] == pytest.approx(34.0, abs=0.01)
        div = row["recent_divergences"][0]
        assert div["request_id"] == "shadow-roll-1"
        assert div["est_regret_ms"] == pytest.approx(34.0, abs=0.01)
        # A second observe for the same request is a no-op (done guard).
        ev.observe_response(req, transfer={"pull_ms": 1.0})
        assert ev.flush()
        assert ev.snapshot()["policies"]["transfer-pair"][
            "est_regret_ms"]["n"] == 1
        # Agreement credits both arms.
        req2 = _req("shadow-roll-2", recorder)
        ev.submit(req2, _result(prefill=P1))
        ev.observe_response(req2, transfer={"prefill": P1, "decode": DEC,
                                            "pull_ms": 3.0})
        assert ev.flush()
        row = ev.snapshot()["policies"]["transfer-pair"]
        assert row["agreements"] == 1 and row["agreement_rate"] == 0.5
        assert row["judged"]["agreements"] == 1
        assert row["agree_measured_pull_ms_mean"] == 3.0
        assert ev.evaluated_total == 2 and ev.diverged_total == 1
        assert ev.regret_ms_sum == pytest.approx(34.0, abs=0.01)
    finally:
        ev.stop()


def test_evaluator_resubmit_replaces_verdict_on_failover():
    """A failover reschedule re-evaluates the SAME request (the PR 11
    classifier precedent): the superseded verdict is backed out of the
    rollup, the record block refreshes in place, and the judge grades the
    pick that actually served."""
    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=40.0)
    ds.transfers.record(P1, DEC, pull_ms=4.0)
    ev = ShadowEvaluator(_pair_cfg(), datastore=ds)
    recorder = DecisionRecorder(DecisionConfig())
    try:
        req = _req("shadow-fo-1", recorder)
        ev.submit(req, _result(prefill=P0))     # diverges toward P1
        assert ev.flush()
        assert req.shadow.entries["transfer-pair"]["verdict"] == "diverge"
        # Failover reschedule lands on P1 — the shadow pick serves.
        ev.submit(req, _result(prefill=P1), resubmit=True)
        assert ev.flush()
        snap = ev.snapshot()["policies"]["transfer-pair"]
        assert snap["evaluated"] == 1          # replaced, not re-counted
        assert snap["agreements"] == 1 and snap["divergences"] == 0
        block = recorder.get("shadow-fo-1").shadow
        assert block["diverged"] is False
        assert block["policies"]["transfer-pair"]["live"]["prefill"] == P1
        ev.observe_response(req, transfer={"prefill": P1, "decode": DEC,
                                           "pull_ms": 3.0})
        assert ev.flush()
        snap = ev.snapshot()["policies"]["transfer-pair"]
        assert snap["judged"]["agreements"] == 1
        assert snap["agree_measured_pull_ms_mean"] == 3.0
        # A reschedule of an UNSAMPLED request stays unsampled.
        req2 = _req("shadow-fo-2")
        ev.submit(req2, _result(), resubmit=True)
        assert req2.shadow is None
        assert ev.snapshot()["submitted"] == 1
        # A reschedule that makes the request INELIGIBLE (decode-only —
        # e.g. the dead pod was the last prefill candidate) drops the
        # stale verdict instead of judging it against a pair that never
        # served.
        req3 = _req("shadow-fo-3", recorder)
        ev.submit(req3, _result(prefill=P0))    # diverge toward P1
        assert ev.flush()
        decode_only = _result()
        del decode_only.profile_results["prefill"]
        ev.submit(req3, decode_only, resubmit=True)
        assert ev.flush()
        assert req3.shadow.entries == {}
        assert recorder.get("shadow-fo-3").shadow["diverged"] is False
        snap = ev.snapshot()["policies"]["transfer-pair"]
        assert snap["evaluated"] == 1           # fo-1 only
        assert snap["divergences"] == 0
        ev.observe_response(req3, transfer=None)  # nothing left to judge
        assert ev.flush()
        assert ev.snapshot()["policies"]["transfer-pair"][
            "judged"]["divergences"] == 0
    finally:
        ev.stop()


def test_evaluator_ineligible_skips_terminal_enqueue():
    """No policy produced an entry (decode-only traffic): entries == {}
    marks the observation closed — the terminal hook skips its worker
    wakeup instead of enqueuing a no-op done event."""
    ev = ShadowEvaluator(_pair_cfg(), datastore=_datastore())
    try:
        req = _req("shadow-inel-1")
        res = _result()
        del res.profile_results["prefill"]   # ineligible for the policy
        ev.submit(req, res)
        assert ev.flush()
        assert req.shadow.entries == {}
        ev.observe_response(req, transfer=None)
        assert req.shadow.done
        assert ev.flush()
        assert ev.snapshot()["policies"]["transfer-pair"]["evaluated"] == 0
    finally:
        ev.stop()


def test_evaluator_sampling_deterministic():
    ds = _datastore()
    cfg = _pair_cfg(sampleRate=0.5)
    ev1 = ShadowEvaluator(cfg, datastore=ds)
    ev2 = ShadowEvaluator(cfg, datastore=ds)
    try:
        picked1, picked2 = [], []
        for i in range(64):
            for ev, picked in ((ev1, picked1), (ev2, picked2)):
                req = _req(f"sample-{i}")
                ev.submit(req, _result())
                picked.append(req.shadow is not None)
        # Deterministic: both evaluators sample the SAME ids (fleet shards
        # must agree), and roughly half are in.
        assert picked1 == picked2
        assert 8 < sum(picked1) < 56
    finally:
        ev1.stop()
        ev2.stop()


def test_evaluator_inert_paths():
    # No policies configured (the default) → one attribute check, nothing
    # stamped, snapshot says inactive.
    ev = ShadowEvaluator(ShadowConfig.from_spec(None),
                         datastore=_datastore())
    req = _req()
    ev.submit(req, _result())
    assert req.shadow is None and not ev.active
    assert ev.snapshot() == {"enabled": True, "active": False,
                             "sample_rate": 1.0, "submitted": 0,
                             "policies": {}}
    # Hard kill-switch with a policy listed.
    ev = ShadowEvaluator(_pair_cfg(enabled=False), datastore=_datastore())
    ev.submit(req, _result())
    assert req.shadow is None and not ev.active
    ev.observe_response(req, transfer=None)  # no-op, no worker started
    ev.stop()


# ---- decisions filter -----------------------------------------------------


def test_record_matches_divergent_filter():
    divergent = {"shadow": {"diverged": True, "policies": {}}}
    agree = {"shadow": {"diverged": False, "policies": {}}}
    assert record_matches(divergent, divergent=True)
    assert not record_matches(agree, divergent=True)
    assert not record_matches({}, divergent=True)  # no shadow block
    assert record_matches(agree, divergent=False)
    assert record_matches({}, divergent=False)
    # AND-composes with the other filters.
    assert not record_matches(divergent, divergent=True, verdict="met")
    # Unknown values match nothing, loudly-by-empty (the ?profile
    # convention): ?divergent=no must not silently mean divergent=1.
    assert not record_matches(divergent, divergent="invalid")
    assert not record_matches(agree, divergent="invalid")


# ---- fleet merge ----------------------------------------------------------


def test_merge_shadow_weighted():
    doc_a = {"enabled": True, "submitted": 10, "policies": {"transfer-pair": {
        "evaluated": 8, "agreements": 6, "divergences": 2, "no_signal": 0,
        "judged": {"agreements": 5, "divergences": 2, "estimate_missing": 0},
        "est_regret_ms": {"n": 2, "sum": 20.0, "mean": 10.0,
                          "mean_abs": 10.0},
        # 5 judged agreements but only 4 carried a measured pull — the
        # merge must weight the mean by agree_measured_n, not by judged
        # agreements.
        "agree_measured_pull_ms_mean": 4.0,
        "agree_measured_n": 4,
        "recent_divergences": [{"request_id": "a-1"}],
    }}}
    doc_b = {"enabled": True, "submitted": 30, "policies": {"transfer-pair": {
        "evaluated": 24, "agreements": 12, "divergences": 6, "no_signal": 6,
        "judged": {"agreements": 10, "divergences": 6,
                   "estimate_missing": 1},
        "est_regret_ms": {"n": 6, "sum": -6.0, "mean": -1.0,
                          "mean_abs": 3.0},
        "agree_measured_pull_ms_mean": 8.0,
        "agree_measured_n": 10,
        "recent_divergences": [{"request_id": "b-1"}],
    }}}
    out = merge_shadow([(0, doc_a), (1, doc_b)])
    row = out["policies"]["transfer-pair"]
    assert out["submitted"] == 40 and row["evaluated"] == 32
    assert row["agreements"] == 18 and row["divergences"] == 8
    assert row["agreement_rate"] == round(18 / 26, 4)
    assert row["coverage"] == round(26 / 40, 4)
    # Regret merged by summing (n, sum) — n-weighted, never averaged.
    assert row["est_regret_ms"]["n"] == 8
    assert row["est_regret_ms"]["sum"] == 14.0
    assert row["est_regret_ms"]["mean"] == round(14.0 / 8, 3)
    # Agreement-measured mean weighted by the count each shard's mean was
    # taken over (agree_measured_n), NOT by judged agreements — shard A
    # judged 5 but measured only 4.
    assert row["agree_measured_pull_ms_mean"] == round(
        (4.0 * 4 + 8.0 * 10) / 14, 3)
    shards = {d["shard"] for d in row["recent_divergences"]}
    assert shards == {0, 1}
    # Zero workers (verify-debug boots the admin with none) stays valid.
    assert merge_shadow([]) == {"workers": 0, "enabled": False,
                                "submitted": 0, "policies": {}}


# ---- pair scorer plugin (the config-activatable live twin) ---------------


def test_transfer_pair_scorer_plugin():
    from llm_d_inference_scheduler_tpu.router.plugins.scorers import (
        TransferAwarePairScorer,
    )

    ds = _datastore()
    ds.transfers.record(P0, DEC, pull_ms=40.0)
    ds.transfers.record(P1, DEC, pull_ms=4.0)
    scorer = TransferAwarePairScorer("t")
    scorer.configure({}, types.SimpleNamespace(datastore=ds))
    req = _req()
    eps = [_ep(P0), _ep(P1)]
    # No decode pick stamped yet → no signal, base scorers rank alone.
    assert scorer.score(None, None, req, eps) == {}
    req.decode_pick = DEC
    scores = scorer.score(None, None, req, eps)
    assert scores[P1] == 1.0 and scores[P0] == 0.0
    # The scorer and the shadow policy share one scoring function — the
    # shadow verdict IS the live activation's behavior.
    assert scores == transfer_pair_scores(ds.transfers, DEC, [P0, P1])


def test_disagg_handler_stamps_decode_pick():
    from llm_d_inference_scheduler_tpu.router.plugins.disagg import (
        AlwaysDisaggPdDecider,
        DisaggProfileHandler,
    )

    handler = DisaggProfileHandler("h")
    handler.pd_decider = AlwaysDisaggPdDecider("d")
    req = _req()
    decode_res = ProfileRunResult(target_endpoints=[_ep(DEC)])
    to_run = handler.pick_profiles(
        None, req, {"prefill": object()}, {"decode": decode_res})
    assert "prefill" in to_run
    assert req.decode_pick == DEC


# ---- timeline series ------------------------------------------------------


def test_timeline_shadow_series():
    from llm_d_inference_scheduler_tpu.router.timeline import (
        TimelineConfig,
        TimelineSampler,
    )

    shadow = types.SimpleNamespace(active=True, evaluated_total=0,
                                   diverged_total=0, regret_ms_sum=0.0)
    clock = {"t": 1000.0}
    sampler = TimelineSampler(TimelineConfig.from_spec({"tickS": 1.0}),
                              shadow=shadow, wall=lambda: clock["t"])
    s1 = sampler.tick()
    assert s1["shadow"] == {"evaluated": 0, "diverged": 0, "regret_ms": 0.0}
    shadow.evaluated_total, shadow.diverged_total = 5, 2
    shadow.regret_ms_sum = 12.5
    clock["t"] += 1
    s2 = sampler.tick()
    assert s2["shadow"] == {"evaluated": 5, "diverged": 2,
                            "regret_ms": 12.5}
    clock["t"] += 1
    s3 = sampler.tick()  # no movement → zero deltas
    assert s3["shadow"]["evaluated"] == 0


# ---- sim per-peer transfer topology (satellite) ---------------------------


def test_sim_per_peer_pull_map():
    from llm_d_inference_scheduler_tpu.engine.config import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.request import EngineRequest
    from llm_d_inference_scheduler_tpu.engine.sim import SimEngine

    def run_import(cfg, remote_host, remote_port):
        eng = SimEngine(cfg)

        async def body():
            out = eng.submit(EngineRequest(
                request_id=f"imp-{remote_port}",
                prompt_token_ids=list(range(64)), max_tokens=1,
                kv_transfer_params={
                    "remote_block_ids": list(range(10)),
                    "remote_host": remote_host,
                    "remote_port": remote_port,
                }))
            while True:
                evt = await out.get()
                if evt.finish_reason is not None:
                    break
            return eng.kv_import_stats[f"imp-{remote_port}"]["ms"]

        return asyncio.run(body())

    base = dict(backend="sim", model="tiny", max_batch=4,
                sim_decode_ms_per_token=0.0, sim_kv_pull_ms_per_block=0.5)
    # Flat scalar (map empty) — bit-identical legacy behavior.
    assert run_import(EngineConfig(**base), "10.0.0.1", 8200) == \
        pytest.approx(5.0)
    # Per-peer skew: the mapped peer gets its own rate, unmapped peers
    # keep the flat fallback.
    skewed = EngineConfig(**base, sim_kv_pull_ms_per_peer={
        "10.0.0.1:8200": 2.0})
    assert run_import(skewed, "10.0.0.1", 8200) == pytest.approx(20.0)
    assert run_import(skewed, "10.0.0.2", 8200) == pytest.approx(5.0)


# ---- live e2e -------------------------------------------------------------

GW, SC, DEC_E, PRE_A, PRE_B = 19030, 19031, 19032, 19033, 19034

E2E_CFG = f"""
shadow:
  policies:
    - {{type: transfer-pair, parameters: {{weight: 2.0}}}}
# This test's premise is a PAIR-BLIND live arm (the shadow policy must
# diverge from it): opt out of the loader's default transfer-aware-pair
# -scorer injection, which would make the live pick pair-aware.
disagg:
  pairScorer: {{enabled: false}}
scheduling:
  pickSeed: 1234
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE_A}, labels: {{llm-d.ai/role: prefill}}}}
    - {{address: 127.0.0.1, port: {PRE_B}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider: {{type: always-disagg-pd-decider}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""


def test_shadow_divergence_live():
    """Live divergence e2e: a seeded transfer skew makes the transfer-pair
    policy disagree with the live (queue-scored) prefill pick; the judged
    regret lands in the shadow block at /debug/decisions/<id>,
    ?divergent=1 isolates the record, /debug/shadow rolls it up, and the
    metric families move."""
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
    from llm_d_inference_scheduler_tpu.router.sidecar import (
        Sidecar,
        SidecarConfig,
    )

    async def body():
        def sim(port, role):
            return EngineServer(EngineConfig(
                backend="sim", model="tiny", port=port, role=role,
                max_batch=4, max_model_len=2048))

        engines = [sim(DEC_E, "decode"), sim(PRE_A, "prefill"),
                   sim(PRE_B, "prefill")]
        for e in engines:
            await e.start()
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC_E}"))
        await sc.start()
        gw = build_gateway(E2E_CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # Round 1: empty table → the policy abstains (no_signal),
                # and we learn the deterministic (pickSeed) live prefill
                # pick for this request id.
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "x " * 80,
                                       "max_tokens": 2},
                                 headers={"x-request-id": "shadow-e2e-1"})
                assert r.status_code == 200
                assert gw.shadow_eval.flush()
                d = (await c.get(f"http://127.0.0.1:{GW}"
                                 "/debug/decisions/shadow-e2e-1")).json()
                block = d["shadow"]["policies"]["transfer-pair"]
                assert block["verdict"] == "no_signal"
                live_pre = block["live"]["prefill"]
                decode = block["live"]["decode"]
                other = (f"127.0.0.1:{PRE_B}"
                         if live_pre == f"127.0.0.1:{PRE_A}"
                         else f"127.0.0.1:{PRE_A}")

                # Seed the skew: the OTHER prefill is the fast pair, so
                # the counterfactual must diverge away from the live pick
                # (queue-scorer ties re-pick the same pod per pickSeed).
                gw.datastore.transfers.record(live_pre, decode,
                                              pull_ms=50.0)
                gw.datastore.transfers.record(other, decode, pull_ms=0.5)

                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "x " * 80,
                                       "max_tokens": 2},
                                 headers={"x-request-id": "shadow-e2e-1"})
                assert r.status_code == 200
                assert gw.shadow_eval.flush()
                d = (await c.get(f"http://127.0.0.1:{GW}"
                                 "/debug/decisions/shadow-e2e-1")).json()
                block = d["shadow"]
                entry = block["policies"]["transfer-pair"]
                assert block["diverged"] is True
                assert entry["verdict"] == "diverge"
                assert entry["live"]["prefill"] == live_pre
                assert entry["shadow"]["prefill"] == other
                # Judged in place: measured live pull vs the shadow pair's
                # EWMA — positive regret (the seeded skew is real).
                assert "judged" in entry
                assert entry["judged"]["est_regret_ms"] > 0

                # ?divergent=1 isolates it; ?divergent=0 excludes it.
                lst = (await c.get(f"http://127.0.0.1:{GW}"
                                   "/debug/decisions?divergent=1")
                       ).json()["decisions"]
                assert [x["request_id"] for x in lst] == ["shadow-e2e-1"]
                # ?divergent=0 returns only non-divergent records (the
                # round-1 no_signal record rides there — same id, its own
                # ring slot).
                lst = (await c.get(f"http://127.0.0.1:{GW}"
                                   "/debug/decisions?divergent=0")
                       ).json()["decisions"]
                assert lst
                assert all(not (x.get("shadow") or {}).get("diverged")
                           for x in lst)

                # /debug/shadow rollup.
                snap = (await c.get(
                    f"http://127.0.0.1:{GW}/debug/shadow")).json()
                row = snap["policies"]["transfer-pair"]
                assert snap["active"] and snap["submitted"] >= 2
                assert row["divergences"] >= 1
                assert row["est_regret_ms"]["n"] >= 1
                assert row["est_regret_ms"]["mean"] > 0
                assert row["recent_divergences"][0]["request_id"] == \
                    "shadow-e2e-1"

                # Metric families present and moving.
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert 'router_shadow_decisions_total{' in m
                assert 'verdict="diverge"' in m
                assert "router_shadow_regret_ms_count" in m
        finally:
            await gw.stop()
            await sc.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())


# ---- TransferTable LRU churn (satellite; companion tests in test_slo.py) --


def test_transfer_table_churn_reappears_fresh():
    """Pod churn evicts a pair; when the pair re-appears it must start a
    FRESH EWMA (pulls=1, value = the new observation) — a resurrected
    stale row would poison the transfer-cost scorer's ranking with
    pre-churn wire costs."""
    t = TransferTable()
    t.MAX_PAIRS = 3
    t.record("p:1", "d:1", pull_ms=100.0, nbytes=10)
    t.record("p:2", "d:1", pull_ms=2.0)
    t.record("p:3", "d:1", pull_ms=3.0)
    before = time.time()
    # Churn: a fourth pair evicts the oldest (p:1).
    t.record("p:4", "d:1", pull_ms=4.0)
    assert t.pair("p:1", "d:1") is None
    # The evicted pair re-appears (pod rescheduled onto the same ip:port):
    # fresh row, not the stale 100ms EWMA resurrected.
    t.record("p:1", "d:1", pull_ms=5.0)
    s = t.pair("p:1", "d:1")
    assert s.pulls == 1
    assert s.ewma_pull_ms == 5.0
    assert s.bytes_total == 0
    assert s.last_unix >= before
    # Reading a pair (scorer path) must NOT touch LRU order: p:2 is still
    # the eviction victim even after a lookup.
    t.pair("p:2", "d:1")
    t.record("p:5", "d:1", pull_ms=6.0)
    assert t.pair("p:2", "d:1") is None
