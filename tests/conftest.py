"""Test env: force an 8-device virtual CPU mesh before JAX initialises.

Mirrors the reference's "multi-node without cluster" strategy (SURVEY.md §4):
envtest/simulators there, virtual CPU devices here.

Note: the axon TPU plugin in this image overrides the JAX_PLATFORMS env var,
so the backend must be pinned via jax.config before first device use.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
