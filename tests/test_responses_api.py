"""OpenAI Responses API (/v1/responses) end-to-end: parser body model,
engine surface, and the disagg path with max_output_tokens semantics
(reference proxy.go:48,391-408, types.go:326-343)."""

import asyncio
import json

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.handlers.parsers import OpenAIParser
from llm_d_inference_scheduler_tpu.router.sidecar import Sidecar, SidecarConfig

GW, SC, DEC, PRE = 18460, 18461, 18462, 18463


def test_parser_responses_and_conversations_shapes():
    p = OpenAIParser("p")
    body = {"model": "m", "input": "hello world", "instructions": "be brief",
            "max_output_tokens": 5, "cache_salt": "tenant-a"}
    r = p.parse(json.dumps(body).encode(), {}, path="/v1/responses")
    assert r.body.responses is not None and r.model == "m"
    assert r.body.prompt_text() == "hello world"
    assert r.body.cache_salt() == "tenant-a"
    assert r.body.payload["model"] == "m"  # model rewrite works on payload

    # Item-array input serializes for scoring.
    r = p.parse(json.dumps({"model": "m", "input": [
        {"type": "message", "role": "user", "content": "q1"}]}).encode(),
        {}, path="/v1/responses")
    assert "q1" in r.body.prompt_text()

    r = p.parse(json.dumps({"model": "m", "items": [
        {"type": "message", "content": "ctx"}]}).encode(),
        {}, path="/v1/conversations")
    assert r.body.conversations is not None
    assert "ctx" in r.body.prompt_text()

    # Shape-based detection without a path: input+instructions → responses,
    # bare input stays embeddings.
    r = p.parse(json.dumps({"input": "x", "instructions": "y"}).encode(), {})
    assert r.body.responses is not None
    r = p.parse(json.dumps({"input": "x"}).encode(), {})
    assert r.body.embeddings is not None


def _engine(port, role="decode"):
    return EngineServer(EngineConfig(backend="tpu", model="tiny", port=port,
                                     max_batch=4, max_model_len=256, role=role))


def test_engine_responses_surface_matches_chat():
    """/v1/responses renders instructions+input through the same template as
    chat, so greedy outputs agree; the reply is Responses-shaped with
    input/output token usage and honors max_output_tokens."""
    async def body():
        eng = _engine(DEC)
        await eng.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                chat = await c.post(
                    f"http://127.0.0.1:{DEC}/v1/chat/completions",
                    json={"messages": [{"role": "system", "content": "sys"},
                                       {"role": "user", "content": "tell me"}],
                          "max_tokens": 5, "temperature": 0})
                r = await c.post(
                    f"http://127.0.0.1:{DEC}/v1/responses",
                    json={"input": "tell me", "instructions": "sys",
                          "max_output_tokens": 5, "temperature": 0})
                assert r.status_code == 200
                doc = r.json()
                assert doc["object"] == "response"
                msg = doc["output"][0]
                assert msg["type"] == "message" and msg["role"] == "assistant"
                text = msg["content"][0]["text"]
                assert text == chat.json()["choices"][0]["message"]["content"]
                u = doc["usage"]
                assert u["output_tokens"] <= 5
                assert u["total_tokens"] == u["input_tokens"] + u["output_tokens"]

                # Streaming: semantic delta events reassemble to the same text.
                async with c.stream(
                        "POST", f"http://127.0.0.1:{DEC}/v1/responses",
                        json={"input": "tell me", "instructions": "sys",
                              "max_output_tokens": 5, "temperature": 0,
                              "stream": True}) as s:
                    acc, completed = "", False
                    async for line in s.aiter_lines():
                        if not line.startswith("data: ") or line == "data: [DONE]":
                            continue
                        ev = json.loads(line[6:])
                        if ev["type"] == "response.output_text.delta":
                            acc += ev["delta"]
                        elif ev["type"] == "response.completed":
                            completed = True
                assert completed and acc == text
        finally:
            await eng.stop()

    asyncio.run(body())


CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - {{type: approx-prefix-cache-producer}}
  - {{type: prefix-cache-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider:
        type: prefix-based-pd-decider
        parameters: {{thresholdTokens: 16}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: prefix-cache-scorer, weight: 3}}
      - {{pluginRef: queue-scorer, weight: 2}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

LONG_INPUT = "summarise this very important document carefully please: " * 4


def _counter_value(server, name) -> float:
    text = server.engine.telemetry.render().decode()
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    return 0.0


def test_responses_through_disagg():
    """/v1/responses through gateway → sidecar P/D: the prefill leg runs
    with max_output_tokens=1 (not max_tokens), the decode leg restores the
    caller's limit, and the answer matches the monolithic engine."""
    async def body():
        dec = _engine(DEC, "decode")
        pre = _engine(PRE, "prefill")
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC, decoder_url=f"http://127.0.0.1:{DEC}",
                                   ssrf_allowlist=[f"127.0.0.1:{PRE}"]))
        await sc.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=120) as c:
                mono = await c.post(f"http://127.0.0.1:{DEC}/v1/responses",
                                    json={"input": LONG_INPUT,
                                          "max_output_tokens": 6,
                                          "temperature": 0})
                mono_text = mono.json()["output"][0]["content"][0]["text"]

                pre_before = _counter_value(pre, "jetstream:prompt_tokens_total")
                r = await c.post(f"http://127.0.0.1:{GW}/v1/responses",
                                 json={"model": "tiny", "input": LONG_INPUT,
                                       "max_output_tokens": 6,
                                       "temperature": 0})
                assert r.status_code == 200
                assert r.headers["x-gateway-destination-endpoint-served"] == \
                    f"127.0.0.1:{SC}"
                doc = r.json()
                assert doc["object"] == "response"
                text = doc["output"][0]["content"][0]["text"]
                assert text == mono_text
                # Decode leg kept the caller's limit (6 tokens, not 1).
                assert doc["usage"]["output_tokens"] == 6
                # The prefill engine really prefilled.
                assert _counter_value(pre, "jetstream:prompt_tokens_total") > \
                    pre_before
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())
