"""Observability: W3C trace propagation, engine step telemetry, SSE usage
tail, sidecar drain, and the cross-component gateway→sidecar→engine trace."""

import asyncio
import json

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router import tracing
from llm_d_inference_scheduler_tpu.router.gateway import (
    _sse_tail_append,
    _usage_from_sse,
    build_gateway,
)
from llm_d_inference_scheduler_tpu.router.sidecar.proxy import (
    Sidecar,
    SidecarConfig,
)


def run(coro):
    return asyncio.run(coro)


# ---------- traceparent inject/extract ----------

def test_traceparent_roundtrip():
    t = tracing.Tracer(enabled=True, sample_ratio=1.0)
    with t.span("root") as root:
        headers: dict = {}
        t.inject_headers(headers)
    tp = headers["traceparent"]
    parsed = tracing.parse_traceparent(tp)
    assert parsed is not None
    trace_id, span_id, sampled = parsed
    assert trace_id == root.trace_id.rjust(32, "0")
    assert span_id == root.span_id
    assert sampled is True
    assert "tracestate" not in headers  # none set → not emitted


def test_traceparent_malformed_and_flags():
    bad = [
        "",                                               # empty
        "00-abc-def-01",                                  # wrong widths
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",        # all-zero trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",        # all-zero span id
        "ff-" + "1" * 32 + "-" + "2" * 16 + "-01",        # forbidden version
        "00-" + "g" * 32 + "-" + "2" * 16 + "-01",        # non-hex
        "garbage",
    ]
    for v in bad:
        assert tracing.parse_traceparent(v) is None, v
    # sampled flag honored both ways
    tid, sid = "a" * 32, "b" * 16
    assert tracing.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid, True)
    assert tracing.parse_traceparent(f"00-{tid}-{sid}-00") == (tid, sid, False)


def test_span_from_headers_joins_and_drops():
    t = tracing.Tracer(enabled=True, sample_ratio=0.0)  # locally sample NOTHING
    tid, sid = "c" * 32, "d" * 16
    # sampled=1 from upstream overrides the local ratio
    with t.span_from_headers("srv", {"traceparent": f"00-{tid}-{sid}-01",
                                     "tracestate": "vendor=x"}):
        inner: dict = {}
        t.inject_headers(inner)
    spans = t.snapshot()
    assert [s["name"] for s in spans] == ["srv"]
    assert spans[0]["trace_id"] == tid
    assert spans[0]["parent_id"] == sid
    # tracestate passes through to the next hop
    assert inner["tracestate"] == "vendor=x"
    assert tracing.parse_traceparent(inner["traceparent"])[0] == tid

    # sampled=0 from upstream drops the local subtree even at ratio 1.0
    t2 = tracing.Tracer(enabled=True, sample_ratio=1.0)
    with t2.span_from_headers("srv", {"traceparent": f"00-{tid}-{sid}-00"}):
        with t2.span("child"):
            pass
    assert t2.snapshot() == []

    # malformed header → fresh root, local sampling applies
    t3 = tracing.Tracer(enabled=True, sample_ratio=1.0)
    with t3.span_from_headers("srv", {"traceparent": "not-a-context"}):
        pass
    (s,) = t3.snapshot()
    assert s["parent_id"] is None and s["trace_id"] != tid

    # a locally sampled-out trace still propagates its DROP decision
    # downstream (flags 00), so the next hop doesn't re-roll into an
    # orphan partial trace
    t4 = tracing.Tracer(enabled=True, sample_ratio=0.0)
    with t4.span("root"):
        dropped: dict = {}
        t4.inject_headers(dropped)
    parsed = tracing.parse_traceparent(dropped["traceparent"])
    assert parsed is not None and parsed[2] is False
    # strict hex validation: int()-tolerated junk is rejected
    assert tracing.parse_traceparent(
        "00-+" + "a" * 31 + "-" + "b" * 16 + "-01") is None
    assert tracing.parse_traceparent(
        "00-" + "a" * 32 + "-" + "b" * 16 + "-01-extra") is None


# ---------- SSE usage tail ----------

def test_sse_tail_keeps_large_terminal_usage_event():
    usage = {"prompt_tokens": 7, "completion_tokens": 3,
             "total_tokens": 10}
    big = {"choices": [{"text": "x" * 9000}], "usage": usage}
    stream = b"".join(
        b'data: {"choices": [{"text": "tok%d"}]}\n\n' % i for i in range(50)
    ) + b"data: " + json.dumps(big).encode() + b"\n\ndata: [DONE]\n\n"
    tail = b""
    for i in range(0, len(stream), 1000):  # transport-chunked
        tail = _sse_tail_append(tail, stream[i:i + 1000])
    # the >4KiB terminal usage event survives trimming intact
    assert _usage_from_sse(tail) == usage


def test_sse_tail_trims_on_event_boundaries():
    tail = b""
    for i in range(100):
        tail = _sse_tail_append(tail, b'data: {"choices": [{"text": "t%03d"}]}\n\n' % i)
    assert len(tail) <= 4096 + 64
    assert tail.startswith(b"data: ")  # always at an event boundary

    # CRLF event terminators (valid SSE) trim just the same
    usage = {"completion_tokens": 5}
    tail = b""
    for i in range(200):
        tail = _sse_tail_append(
            tail, b'data: {"choices": [{"text": "t%03d"}]}\r\n\r\n' % i)
    tail = _sse_tail_append(
        tail, b"data: " + json.dumps({"usage": usage}).encode()
        + b"\r\n\r\ndata: [DONE]\r\n\r\n")
    assert len(tail) <= 4096 + 64
    assert tail.startswith(b"data: ")
    assert _usage_from_sse(tail) == usage


# ---------- metrics registries ----------

def test_verify_metrics_registries_clean():
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "verify_metrics",
        pathlib.Path(__file__).resolve().parents[1] / "scripts"
        / "verify_metrics.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.check() == []


def test_engine_metrics_families_on_sim():
    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=18655))
        await eng.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                r = await c.post("http://127.0.0.1:18655/v1/completions",
                                 json={"prompt": "hello", "max_tokens": 3})
                assert r.status_code == 200
                text = (await c.get("http://127.0.0.1:18655/metrics")).text
            for family in ("jetstream:num_free_kv_blocks",
                           "jetstream:batch_fill_ratio",
                           "jetstream:num_cached_kv_blocks",
                           "jetstream:prefill_step_duration_seconds",
                           "jetstream:decode_step_duration_seconds",
                           "jetstream:compile_events_total",
                           "jetstream:kv_cache_usage_perc"):
                assert family in text, family
            # the sim observed real steps
            assert "jetstream:decode_step_duration_seconds_count 3.0" in text
        finally:
            await eng.stop()

    run(body())


def test_tpu_engine_step_and_compile_metrics():
    async def body():
        from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
        from llm_d_inference_scheduler_tpu.engine import EngineRequest

        eng = TpuEngine(EngineConfig(backend="tpu", model="tiny",
                                     max_batch=2, max_model_len=128))
        await eng.start()
        try:
            for i in range(2):
                out = eng.submit(EngineRequest(
                    request_id=f"m{i}", prompt_token_ids=[1] + [9] * 5,
                    max_tokens=4))
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=60)
                    if ev.finish_reason is not None:
                        break
        finally:
            await eng.stop()
        text = eng.telemetry.render().decode()
        # first prefill/decode dispatches were counted as compile events …
        assert 'jetstream:compile_events_total{bucket="1x16",op="prefill"}' in text
        assert 'op="decode"' in text
        # … and the repeat decode dispatches landed in the step histogram
        decode_count = next(
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("jetstream:decode_step_duration_seconds_count"))
        assert decode_count >= 1
        # occupancy gauges settle back to all-free
        assert f"jetstream:num_free_kv_blocks {float(eng.n_blocks - 1)}" in text

    run(body())


# ---------- e2e: one trace across gateway → sidecar → engine ----------

def test_e2e_single_trace_across_components():
    EPORT, SPORT, GPORT = 18656, 18657, 18658

    async def body():
        old = (tracing.tracer.enabled, tracing.tracer.sample_ratio)
        tracing.tracer.enabled, tracing.tracer.sample_ratio = True, 1.0
        tracing.tracer.finished.clear()
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=EPORT))
        await eng.start()
        sc = Sidecar(SidecarConfig(port=SPORT,
                                   decoder_url=f"http://127.0.0.1:{EPORT}"))
        await sc.start()
        gw = build_gateway(f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SPORT}}}
""", port=GPORT, poll_interval=0.02)
        await gw.start()
        try:
            client_tp = "00-" + "e" * 32 + "-" + "f" * 16 + "-01"
            async with httpx.AsyncClient(timeout=30) as c:
                r = await c.post(f"http://127.0.0.1:{GPORT}/v1/completions",
                                 json={"model": "tiny", "prompt": "hi",
                                       "max_tokens": 2},
                                 headers={"traceparent": client_tp})
                assert r.status_code == 200
                spans = (await c.get(
                    f"http://127.0.0.1:{GPORT}/debug/traces?merge=1")
                         ).json()["spans"]
            by_name = {}
            for s in spans:
                by_name.setdefault(s["name"], []).append(s)
            for name in ("gateway.request", "gateway.request_orchestration",
                         "sidecar.request", "engine.request",
                         "engine.prefill", "engine.decode"):
                assert name in by_name, (name, sorted(by_name))
            gwr = by_name["gateway.request"][0]
            # the gateway joined the CLIENT's trace
            assert gwr["trace_id"] == "e" * 32
            assert gwr["parent_id"] == "f" * 16
            # every component's spans share that one trace id …
            for name, group in by_name.items():
                for s in group:
                    assert s["trace_id"] == "e" * 32, (name, s)
            # … with correct cross-component parent links
            sidecar = by_name["sidecar.request"][0]
            assert sidecar["parent_id"] == gwr["span_id"]
            engine = by_name["engine.request"][0]
            assert engine["parent_id"] == sidecar["span_id"]
            assert by_name["engine.prefill"][0]["parent_id"] == engine["span_id"]
            assert by_name["engine.decode"][0]["parent_id"] == engine["span_id"]
        finally:
            tracing.tracer.enabled, tracing.tracer.sample_ratio = old
            await gw.stop()
            await sc.stop()
            await eng.stop()

    run(body())


# ---------- sidecar drain ----------

def test_sidecar_drain_stops_listener_and_reports():
    EPORT, SPORT = 18661, 18662

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=EPORT,
                                        sim_decode_ms_per_token=30.0))
        await eng.start()
        sc = Sidecar(SidecarConfig(port=SPORT,
                                   decoder_url=f"http://127.0.0.1:{EPORT}"))
        await sc.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                base = f"http://127.0.0.1:{SPORT}"
                assert (await c.get(f"{base}/health")).status_code == 200
                text = (await c.get(f"{base}/metrics")).text
                assert "sidecar_draining 0.0" in text
                # engine families relay through the same scrape
                assert "jetstream:num_requests_running" in text

                # in-flight request survives the drain
                gen = asyncio.create_task(c.post(
                    f"{base}/v1/completions",
                    json={"prompt": "hi", "max_tokens": 10}))
                await asyncio.sleep(0.1)
                await sc.begin_drain()
                resp = await gen
                assert resp.status_code == 200
                assert resp.json()["usage"]["completion_tokens"] == 10
                # drain window, from a FRESH connection: readiness 503s, new
                # generate work gets a clean retryable 503, and the drain
                # gauge is scrapeable (the listener closes only at stop())
                async with httpx.AsyncClient(timeout=5) as fresh:
                    r = await fresh.get(f"{base}/health")
                    assert r.status_code == 503
                    assert r.json()["status"] == "draining"
                    r = await fresh.post(f"{base}/v1/completions",
                                         json={"prompt": "x", "max_tokens": 1})
                    assert r.status_code == 503
                    assert r.headers["x-removal-reason"] == "sidecar-draining"
                    text = (await fresh.get(f"{base}/metrics")).text
                    assert "sidecar_draining 1.0" in text
            assert sc.draining
        finally:
            await sc.stop()
            await eng.stop()

    run(body())
