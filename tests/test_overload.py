"""Goodput-max overload control (router/overload.py): predictive SLO
admission, the degrade ladder, Retry-After shedding, predicted-unmeetable
queue eviction, and the distinct shed ledger verdict.

Unit tier: drain-rate estimator, feasibility math (fail-open rules,
headroom, degrade vs shed rungs), degrade application, queue eviction +
priority decay, ledger shed accounting. E2E tier: a real gateway with a
trained predictor sheds a predictively-hopeless request with 429 + a finite
Retry-After and a fully-explained DecisionRecord, while the kill-switch
config serves the identical request."""

import asyncio

import httpx
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.decisions import DecisionRecord
from llm_d_inference_scheduler_tpu.router.flowcontrol import (
    FlowControlConfig,
    FlowController,
)
from llm_d_inference_scheduler_tpu.router.flowcontrol.types import (
    FlowControlRequest,
    FlowKey,
    QueueOutcome,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    Objectives,
)
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.overload import (
    DrainRateEstimator,
    OverloadConfig,
    OverloadController,
    QueueOverloadPolicy,
)
from llm_d_inference_scheduler_tpu.router.slo import SloConfig, SloLedger


def run(coro):
    return asyncio.run(coro)


def _req(priority=0, headers=None, max_tokens=64, model="m"):
    return InferenceRequest(
        request_id="r-1", target_model=model,
        body=InferenceRequestBody(
            completions={"prompt": "x", "max_tokens": max_tokens}),
        headers=headers or {}, objectives=Objectives(priority=priority))


class _FakePredictor:
    """admission_estimate stand-in with a scripted answer."""

    def __init__(self, ttft=None, tpot=None):
        self.ttft, self.tpot = ttft, tpot

    def admission_estimate(self, request, endpoints):
        if self.ttft is None:
            return None
        return self.ttft, self.tpot


class _FakeFlow:
    def __init__(self, queued=0):
        self.queued_requests = queued
        self.dispatch_observer = None
        self.queue_policy = None


def _ctl(spec=None, *, predictor=None, flow=None, clock=None):
    kw = {"ledger": SloLedger(SloConfig(enabled=True)),
          "predictor": predictor}
    if clock is not None:
        kw["clock"] = clock
    ctl = OverloadController(OverloadConfig.from_spec(
        {"enabled": True, **(spec or {})}), **kw)
    if flow is not None:
        ctl.attach_flow(flow)
    return ctl


# ---- drain-rate estimator ----------------------------------------------


def test_drain_rate_estimator_converges_and_decays():
    clock = [0.0]
    est = DrainRateEstimator(halflife_s=2.0, clock=lambda: clock[0])
    assert est.rate() == 0.0 and est.total == 0
    # 10 dispatches/second for 12 seconds → rate ≈ 10.
    for _ in range(12):
        for _ in range(10):
            est.note()
        clock[0] += 1.0
    assert est.rate() == pytest.approx(10.0, rel=0.2)
    # Silence decays the estimate toward zero instead of freezing it.
    clock[0] += 30.0
    assert est.rate() < 0.1
    # A fresh burst registers through the live-window blend.
    est.note(20)
    clock[0] += 0.5
    assert est.rate() > 10.0


# ---- feasibility / ladder ----------------------------------------------


def test_assess_none_when_disabled_exempt_or_no_slo():
    ctl = OverloadController(OverloadConfig(), predictor=_FakePredictor(999))
    assert ctl.assess(_req(headers={"x-slo-ttft-ms": "10"}), []) is None

    ctl = _ctl(predictor=_FakePredictor(999.0))
    # Priority above maxPriority is exempt even with a hopeless prediction.
    assert ctl.assess(_req(priority=5, headers={"x-slo-ttft-ms": "10"}),
                      []) is None
    # No SLO on either axis → nothing to protect.
    assert ctl.assess(_req(), []) is None


def test_assess_fail_open_cold_router():
    # No trained predictor, no queue: a cold router must admit.
    ctl = _ctl(predictor=None, flow=_FakeFlow(queued=0))
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "100"}), [])
    assert v is not None and v.action == "admit"
    assert v.predicted_ttft_ms == 0.0
    # Queue present but drain estimator has never seen a dispatch:
    # still fail open (total == 0).
    ctl = _ctl(predictor=None, flow=_FakeFlow(queued=50))
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "100"}), [])
    assert v.action == "admit"


def test_assess_sheds_on_predicted_ttft_miss_with_retry_after():
    clock = [0.0]
    flow = _FakeFlow(queued=20)
    ctl = _ctl({"retryAfterMinS": 1.0, "retryAfterMaxS": 30.0},
               predictor=_FakePredictor(ttft=50.0), flow=flow,
               clock=lambda: clock[0])
    # Teach the drain estimator ~2 req/s.
    for _ in range(10):
        ctl.note_dispatch(2)
        clock[0] += 1.0
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "400"}), [])
    # queue wait ≈ 20/2 = 10s ≫ 400ms → shed.
    assert v.action == "shed" and v.reason == "predicted_ttft_miss"
    # ~20 queued / ~1.5-2 req/s EWMA → several seconds of predicted wait.
    assert 5_000 < v.queue_wait_ms < 25_000
    assert v.retry_after_s is not None and 1.0 <= v.retry_after_s <= 30.0
    # The decision block explains predicted vs SLO vs drain.
    b = v.block()
    assert b["slo_ttft_ms"] == 400.0 and b["drain_rate_rps"] > 0
    assert b["predicted_ttft_ms"] > b["slo_ttft_ms"]
    assert b["retry_after_s"] == v.retry_after_s


def test_assess_admits_within_headroom():
    ctl = _ctl(predictor=_FakePredictor(ttft=150.0, tpot=5.0),
               flow=_FakeFlow(queued=0))
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "400",
                                 "x-slo-tpot-ms": "50"}), [])
    assert v.action == "admit"
    assert v.service_ttft_ms == 150.0 and v.predicted_tpot_ms == 5.0


def test_assess_sheds_on_tpot_miss_without_rewrite():
    ctl = _ctl(predictor=_FakePredictor(ttft=10.0, tpot=80.0))
    v = ctl.assess(_req(headers={"x-slo-tpot-ms": "50"}), [])
    assert v.action == "shed" and v.reason == "predicted_tpot_miss"
    assert v.retry_after_s is not None


def test_degrade_rung_marginal_miss_then_shed_beyond_ratio():
    spec = {"degrade": {"maxTokensClamp": 8, "admitRatio": 1.5}}
    # Marginal miss (1 < ratio <= 1.5): degrade-and-admit.
    ctl = _ctl(spec, predictor=_FakePredictor(ttft=500.0))
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "400"}), [])
    assert v.action == "degrade"
    assert v.degrade_actions == ("clamp_max_tokens",)
    # Deep miss (> 1.5x): shed even though degrade is configured.
    ctl = _ctl(spec, predictor=_FakePredictor(ttft=2000.0))
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "400"}), [])
    assert v.action == "shed"
    # TPOT-only miss: clamping tokens can't fix per-token latency → shed...
    ctl = _ctl(spec, predictor=_FakePredictor(ttft=10.0, tpot=80.0))
    assert ctl.assess(_req(headers={"x-slo-tpot-ms": "50"}), []).action == "shed"
    # ...but a model rewrite can → degrade.
    ctl = _ctl({"degrade": {"modelRewrite": "m-fast"}},
               predictor=_FakePredictor(ttft=10.0, tpot=80.0))
    v = ctl.assess(_req(headers={"x-slo-tpot-ms": "50"}), [])
    assert v.action == "degrade" and v.degrade_actions == ("model_rewrite",)


def test_apply_degrade_clamps_and_rewrites_in_place():
    ctl = _ctl({"degrade": {"maxTokensClamp": 8, "modelRewrite": "m-fast",
                            "admitRatio": 2.0}},
               predictor=_FakePredictor(ttft=500.0))
    req = _req(headers={"x-slo-ttft-ms": "400"}, max_tokens=64)
    v = ctl.assess(req, [])
    assert v.action == "degrade"
    applied = ctl.apply_degrade(req, v)
    assert applied == ["clamp_max_tokens", "model_rewrite"]
    assert req.body.payload["max_tokens"] == 8
    assert req.target_model == "m-fast" and req.degraded is True
    # Idempotent-ish: a request already below the clamp / on the cheap
    # model degrades to a no-op.
    req2 = _req(headers={"x-slo-ttft-ms": "400"}, max_tokens=4, model="m-fast")
    assert ctl.apply_degrade(req2, v) == []
    assert req2.body.payload["max_tokens"] == 4


def test_stamp_hint_carries_feasibility_to_flow_control():
    ctl = _ctl(predictor=_FakePredictor(ttft=150.0))
    req = _req(headers={"x-slo-ttft-ms": "400"})
    v = ctl.assess(req, [])
    ctl.stamp_hint(req, v)
    assert req._overload_hint.service_ttft_ms == 150.0
    assert req._overload_hint.slo_ttft_ms == 400.0


def test_stamp_hint_budget_tracks_admission_bar_never_below_slo():
    """Review hardening: the in-queue renege budget follows the bar the
    request was ADMITTED at — a headroomFactor > 1 admit (or a degrade
    band with h*ratio < 1) must not be evicted for exceeding a tighter
    budget than its admission tolerated."""
    # h > 1: admitted with predicted 500 > SLO 400 — budget scales to 600.
    ctl = _ctl({"headroomFactor": 1.5}, predictor=_FakePredictor(ttft=500.0))
    req = _req(headers={"x-slo-ttft-ms": "400"})
    v = ctl.assess(req, [])
    assert v.action == "admit"
    ctl.stamp_hint(req, v)
    assert req._overload_hint.slo_ttft_ms == 600.0
    # h < 1 degrade band (h*ratio = 0.55): budget clamps at the RAW SLO,
    # not 0.55x of it.
    ctl = _ctl({"headroomFactor": 0.5,
                "degrade": {"maxTokensClamp": 8, "admitRatio": 1.1}},
               predictor=_FakePredictor(ttft=210.0))
    req = _req(headers={"x-slo-ttft-ms": "400"})
    v = ctl.assess(req, [])
    assert v.action == "degrade"
    ctl.stamp_hint(req, v)
    assert req._overload_hint.slo_ttft_ms == 400.0


def test_retry_after_always_finite_and_bounded():
    ctl = _ctl({"retryAfterMinS": 2.0, "retryAfterMaxS": 10.0})
    assert ctl.retry_after_s(0.0) == 2.0
    assert ctl.retry_after_s(5_000.0) == 5.0
    assert ctl.retry_after_s(1e12) == 10.0
    assert ctl.retry_after_s(float("inf")) == 10.0


def test_overload_config_validation():
    with pytest.raises(ValueError):
        OverloadConfig.from_spec({"headroomFactor": 0})
    with pytest.raises(ValueError):
        OverloadConfig.from_spec({"degrade": {"admitRatio": 0.5}})
    with pytest.raises(ValueError):
        OverloadConfig.from_spec({"retryAfterMinS": 5, "retryAfterMaxS": 1})


def test_idle_router_with_decayed_drain_fails_open():
    """Review hardening: the arriving request counts itself in-flight, and
    a drain EWMA decayed to ~nothing is no evidence of queueing — an idle
    router must not shed its first request after a quiet spell."""
    clock = [0.0]
    flow = _FakeFlow(queued=0)
    ctl = _ctl(predictor=None, flow=flow, clock=lambda: clock[0])
    ctl.inflight_fn = lambda: 1  # only the request being assessed
    # A burst long ago, then 30s of silence: rate decays below the floor.
    ctl.note_dispatch(20)
    clock[0] += 30.0
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "500"}), [])
    assert v is not None and v.action == "admit", (v.action, v.detail)
    assert v.queue_wait_ms == 0.0
    # But explicitly QUEUED work with no drain is a stalled pipeline.
    flow.queued_requests = 3
    ctl.inflight_fn = lambda: 4
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "500"}), [])
    assert v.action == "shed"


def test_admission_estimate_minima_are_independent_per_axis():
    """Review hardening: feasibility asks whether ANY endpoint can meet
    each axis — the TPOT estimate must not be coupled to the TTFT-winning
    endpoint."""
    from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
        Endpoint,
        EndpointMetadata,
    )
    from llm_d_inference_scheduler_tpu.router.requestcontrol.predicted_latency import (  # noqa: E501
        PredictedLatencyProducer,
    )

    prod = PredictedLatencyProducer()
    eps = []
    # A: fast TTFT (50ms), terrible TPOT (100ms). B: slower TTFT (60ms),
    # fine TPOT (10ms).
    for port, ttft, tpot in ((1, 50.0, 100.0), (2, 60.0, 10.0)):
        ep = Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1",
                                       port=port))
        ep.metrics.kv_cache_usage_percent = 0.5
        ep.metrics.running_requests_size = 1
        for _ in range(PredictedLatencyProducer.MIN_SAMPLES + 1):
            prod._ttft_model_for(ep.metadata.address_port).update(
                prod._ttft_features(_req(), ep), ttft)
            prod._tpot_model_for(ep.metadata.address_port).update(
                prod._tpot_features(ep), tpot)
        eps.append(ep)
    est = prod.admission_estimate(_req(), eps)
    assert est is not None
    ttft_est, tpot_est = est
    # Ridge regularization shrinks small-sample constant targets a bit.
    assert ttft_est == pytest.approx(50.0, abs=10.0)  # A's TTFT
    assert tpot_est == pytest.approx(10.0, abs=3.0)   # B's TPOT
    # With those estimates the controller admits (B satisfies TPOT).
    ctl = _ctl(predictor=prod)
    v = ctl.assess(_req(headers={"x-slo-ttft-ms": "200",
                                 "x-slo-tpot-ms": "20"}), eps)
    assert v.action == "admit"


def test_record_shed_escalation_keeps_prior_block():
    """Review hardening: a degraded-then-admitted request later evicted
    from the queue must explain the EVICTION (with its Retry-After), not
    the rung it was admitted on; the superseded block survives as prior."""
    rec = DecisionRecord("r", "m")
    rec.record_shed({"action": "degrade", "degrade_actions": ["clamp"]})
    rec.record_shed({"action": "shed"})  # non-escalating write is dropped
    assert rec.shed["action"] == "degrade"
    rec.record_shed({"action": "evict_unmeetable", "retry_after_s": 2.0},
                    escalate=True)
    assert rec.shed["action"] == "evict_unmeetable"
    assert rec.shed["prior"]["action"] == "degrade"


# ---- flow-control queue behaviors --------------------------------------


def test_queue_unmeetable_eviction_before_ttl():
    async def body():
        fc = FlowController(FlowControlConfig(default_ttl_s=30.0),
                            saturation_fn=lambda: 2.0)  # saturated: queue holds
        fc.queue_policy = QueueOverloadPolicy(eviction_enabled=True)
        await fc.start()
        try:
            # Unmeetable: 100ms SLO budget, 10s predicted service time.
            doomed = FlowControlRequest(
                request_id="doomed", flow_key=FlowKey("f", 0), size_bytes=1,
                slo_ttft_ms=100.0, predicted_service_ms=10_000.0)
            # Meetable: generous budget — must survive the sweep.
            fine = FlowControlRequest(
                request_id="fine", flow_key=FlowKey("f", 0), size_bytes=1,
                slo_ttft_ms=60_000.0, predicted_service_ms=1.0)
            t_doomed = asyncio.ensure_future(fc.enqueue_and_wait(doomed))
            t_fine = asyncio.ensure_future(fc.enqueue_and_wait(fine))
            outcome = await asyncio.wait_for(t_doomed, timeout=5.0)
            assert outcome == QueueOutcome.EVICTED_UNMEETABLE
            assert not t_fine.done()  # still queued, not collateral damage
            t_fine.cancel()
            try:
                await t_fine
            except asyncio.CancelledError:
                pass
        finally:
            await fc.stop()

    run(body())


def test_queue_unmeetable_disabled_by_default():
    async def body():
        fc = FlowController(FlowControlConfig(default_ttl_s=0.4),
                            saturation_fn=lambda: 2.0)
        await fc.start()
        try:
            doomed = FlowControlRequest(
                request_id="doomed", flow_key=FlowKey("f", 0), size_bytes=1,
                slo_ttft_ms=100.0, predicted_service_ms=10_000.0)
            # Kill-switch off: the stamp is inert — the item rides to its
            # TTL exactly as pre-overload.
            outcome = await asyncio.wait_for(
                fc.enqueue_and_wait(doomed), timeout=5.0)
            assert outcome == QueueOutcome.EVICTED_TTL
        finally:
            await fc.stop()

    run(body())


def test_shed_queued_priority_decay_prefers_stale_items():
    async def body():
        fc = FlowController(FlowControlConfig(),
                            saturation_fn=lambda: 2.0)
        fc.queue_policy = QueueOverloadPolicy(decay_per_s=2.0)
        await fc.start()
        try:
            # Band -1 item that has waited 1s: decayed to -1 - 2*1 = -3,
            # below the fresh band -2 item (-2). The stale higher-band item
            # loses its slot first.
            import time as _t
            old = FlowControlRequest(
                request_id="old-minus1", flow_key=FlowKey("a", -1),
                size_bytes=1)
            old.enqueue_time = _t.monotonic() - 1.0
            fresh = FlowControlRequest(
                request_id="fresh-minus2", flow_key=FlowKey("b", -2),
                size_bytes=1)
            t_old = asyncio.ensure_future(fc.enqueue_and_wait(old))
            t_fresh = asyncio.ensure_future(fc.enqueue_and_wait(fresh))
            await asyncio.sleep(0.05)
            assert fc.shed_queued(1) == ["old-minus1"]
            assert await asyncio.wait_for(t_old, 2) == QueueOutcome.EVICTED_SHED
            # Without decay the same state sheds the LOWEST band first.
            fc.queue_policy = QueueOverloadPolicy(decay_per_s=0.0)
            assert fc.shed_queued(1) == ["fresh-minus2"]
            assert await asyncio.wait_for(t_fresh, 2) == QueueOutcome.EVICTED_SHED
        finally:
            await fc.stop()

    run(body())


# ---- ledger shed verdict ------------------------------------------------


def test_ledger_shed_is_distinct_verdict_not_miss():
    ledger = SloLedger(SloConfig(enabled=True, default_ttft_ms=100.0))
    rec = DecisionRecord("r-shed", "m")
    req = _req()
    req.decision = rec
    import time as _t
    ledger.start(req, _t.monotonic())
    ledger.complete(req, status=429, reason="overload shed: predicted TTFT",
                    shed=True)
    snap = ledger.snapshot()
    assert snap["totals"]["requests"] == 1
    assert snap["totals"]["shed"] == 1
    # Attainment is judged over SERVED requests only — one shed alone
    # leaves it undefined, not 0.0.
    assert snap["totals"]["attainment"] is None
    assert snap["miss_reasons"] == {}
    assert snap["shed_reasons"] == {"overload": 1}
    assert rec.outcome["shed"] is True and rec.outcome["slo_met"] is False

    # A served-and-met request alongside: attainment 1.0, not 0.5.
    req2 = _req()
    ledger.start(req2, _t.monotonic())
    ledger.complete(req2, status=200, usage={"completion_tokens": 4})
    snap = ledger.snapshot()
    assert snap["totals"]["requests"] == 2 and snap["totals"]["shed"] == 1
    assert snap["totals"]["attainment"] == 1.0
    assert snap["totals"]["goodput_tokens"] == 4


def test_capacity_shed_records_victim_ids():
    """The capacity-shed retry path names its victims in the shedding
    request's admission record (/debug/decisions explains who was evicted
    and why)."""
    from llm_d_inference_scheduler_tpu.router.flowcontrol import (
        FlowControlAdmissionController,
    )
    from llm_d_inference_scheduler_tpu.router.flowcontrol.eviction import (
        RequestEvictor,
    )

    async def body():
        sat = {"v": 2.0}
        fc = FlowController(FlowControlConfig(max_global_requests=1,
                                              default_ttl_s=5.0),
                            saturation_fn=lambda: sat["v"])
        await fc.start()
        evictor = RequestEvictor()
        evictor.register("victim-inflight", -1, lambda: None)
        admission = FlowControlAdmissionController(fc, evictor=evictor)
        try:
            # Fill the queue with a sheddable item.
            victim = _req(priority=-1)
            victim.request_id = "victim-queued"
            vt = asyncio.ensure_future(admission.admit(None, victim, []))
            await asyncio.sleep(0.02)
            # The band-0 arrival hits capacity, sheds both victims, retries.
            rec = DecisionRecord("beneficiary", "m")
            shedder = _req(priority=0)
            shedder.request_id = "beneficiary"
            shedder.decision = rec
            st = asyncio.ensure_future(admission.admit(None, shedder, []))
            await asyncio.sleep(0.05)
            sat["v"] = 0.0  # let the retry dispatch
            await asyncio.wait_for(st, timeout=5.0)
            assert rec.admission["retried_after_shed"] is True
            assert rec.admission["shed_victims"] == ["victim-queued",
                                                     "victim-inflight"]
            with pytest.raises(Exception):
                await vt
        finally:
            await fc.stop()

    run(body())


# ---- e2e: gateway sheds with Retry-After, kill-switch serves ------------

E2E_ENG, E2E_GW, E2E_GW_OFF = 18820, 18821, 18822

E2E_CFG = f"""
featureGates: {{flowControl: true}}
overload: {{enabled: true}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {E2E_ENG}}}
plugins:
  - {{type: predicted-latency-producer}}
  - {{type: queue-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: queue-scorer}}
"""

E2E_CFG_OFF = E2E_CFG.replace("overload: {enabled: true}",
                              "overload: {enabled: false}")


def test_e2e_gateway_sheds_with_retry_after_killswitch_serves():
    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=E2E_ENG,
                                        sim_decode_ms_per_token=2.0))
        await eng.start()
        gw = build_gateway(E2E_CFG, port=E2E_GW, poll_interval=0.02)
        await gw.start()
        gw_off = build_gateway(E2E_CFG_OFF, port=E2E_GW_OFF,
                               poll_interval=0.02)
        await gw_off.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                # Train the per-endpoint ridge past MIN_SAMPLES on both
                # gateways (each holds its own producer instance).
                for port in (E2E_GW, E2E_GW_OFF):
                    for i in range(7):
                        r = await c.post(
                            f"http://127.0.0.1:{port}/v1/completions",
                            json={"model": "tiny", "prompt": f"t{i}",
                                  "max_tokens": 2})
                        assert r.status_code == 200

                # A 0.01ms TTFT SLO is predictively hopeless → shed.
                r = await c.post(
                    f"http://127.0.0.1:{E2E_GW}/v1/completions",
                    json={"model": "tiny", "prompt": "p", "max_tokens": 2},
                    headers={"x-request-id": "ovl-shed",
                             "x-slo-ttft-ms": "0.01"})
                assert r.status_code == 429, r.text
                ra = int(r.headers["retry-after"])
                assert ra >= 1
                assert r.json()["retry_after_s"] >= 1.0
                assert "overload" in r.headers["x-removal-reason"]

                # The shed is fully explained at /debug/decisions.
                d = (await c.get(f"http://127.0.0.1:{E2E_GW}"
                                 "/debug/decisions/ovl-shed")).json()
                shed = d["shed"]
                assert shed["action"] == "shed"
                assert shed["predicted_ttft_ms"] > shed["slo_ttft_ms"]
                assert "drain_rate_rps" in shed and "queue_wait_ms" in shed
                assert d["outcome"]["shed"] is True
                # Ledger: distinct verdict, stamped exactly once.
                slo = (await c.get(
                    f"http://127.0.0.1:{E2E_GW}/debug/slo")).json()
                assert slo["totals"]["shed"] == 1
                assert slo["totals"]["requests"] == 8
                # Metric family present (the registry is process-global,
                # so assert presence, not an exact count).
                m = (await c.get(f"http://127.0.0.1:{E2E_GW}/metrics")).text
                assert ('router_admission_shed_total'
                        '{reason="predicted_ttft_miss"}') in m
                assert "router_queue_drain_rate" in m

                # Kill-switch: the identical hopeless request is served
                # (and judged an SLO miss, as pre-PR).
                r = await c.post(
                    f"http://127.0.0.1:{E2E_GW_OFF}/v1/completions",
                    json={"model": "tiny", "prompt": "p", "max_tokens": 2},
                    headers={"x-request-id": "ovl-off",
                             "x-slo-ttft-ms": "0.01"})
                assert r.status_code == 200
                slo = (await c.get(
                    f"http://127.0.0.1:{E2E_GW_OFF}/debug/slo")).json()
                assert slo["totals"]["shed"] == 0
        finally:
            await gw_off.stop()
            await gw.stop()
            await eng.stop()

    run(body())


def test_e2e_degrade_ladder_clamps_and_serves():
    """A marginal predicted miss takes degrade rung 1: max_tokens clamped,
    request served, decision record explains the action."""
    cfg = E2E_CFG.replace(
        "overload: {enabled: true}",
        "overload: {enabled: true, headroomFactor: 1.0, "
        "degrade: {maxTokensClamp: 4, admitRatio: 100000}}")

    async def body():
        eng = EngineServer(EngineConfig(backend="sim", model="tiny",
                                        port=E2E_ENG,
                                        sim_decode_ms_per_token=2.0))
        await eng.start()
        gw = build_gateway(cfg, port=E2E_GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=30) as c:
                for i in range(7):
                    r = await c.post(
                        f"http://127.0.0.1:{E2E_GW}/v1/completions",
                        json={"model": "tiny", "prompt": f"t{i}",
                              "max_tokens": 2})
                    assert r.status_code == 200
                # Hopeless TTFT SLO, but admitRatio is huge → degrade rung.
                r = await c.post(
                    f"http://127.0.0.1:{E2E_GW}/v1/completions",
                    json={"model": "tiny", "prompt": "p", "max_tokens": 32},
                    headers={"x-request-id": "ovl-degrade",
                             "x-slo-ttft-ms": "0.01"})
                assert r.status_code == 200, r.text
                # The clamp reached the engine: at most 4 tokens generated.
                assert r.json()["usage"]["completion_tokens"] <= 4
                d = (await c.get(f"http://127.0.0.1:{E2E_GW}"
                                 "/debug/decisions/ovl-degrade")).json()
                assert d["shed"]["action"] == "degrade"
                assert d["shed"]["degrade_actions"] == ["clamp_max_tokens"]
                m = (await c.get(f"http://127.0.0.1:{E2E_GW}/metrics")).text
                assert ('router_degraded_requests_total'
                        '{action="clamp_max_tokens"}') in m
        finally:
            await gw.stop()
            await eng.stop()

    run(body())
