"""Fleet flight recorder (ISSUE 12, router/timeline.py).

Hermetic tiers: pure units (ring bounds, bucket alignment, burn-rate
windows, incident dedup/cooldown, config redaction, the fleet bucket
merge), one real gateway driving /debug/timeline + /debug/incidents +
/debug/config (+ the kill-switch contract), and the FleetAdmin fan-in
against stub workers (gap-marked merge, traces fan-in, config-skew
check)."""

import asyncio
import json
import os
import sys

import httpx
import pytest
from aiohttp import web

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))

from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.kvobs import CacheLedger, KvObsConfig
from llm_d_inference_scheduler_tpu.router.slo import SloConfig, SloLedger
from llm_d_inference_scheduler_tpu.router.timeline import (
    RULE_BURN_RATE,
    RULE_SHED_RATE,
    BurnRateMonitor,
    IncidentRecorder,
    TimelineConfig,
    TimelineSampler,
    config_hash,
    merge_timeline,
    redact_config,
)

GW_A, GW_B = 19170, 19171
STUB_A, STUB_B, STUB_ADMIN = 19180, 19181, 19182


def run(coro):
    return asyncio.run(coro)


def _sampler(cfg: TimelineConfig, **kw) -> TimelineSampler:
    return TimelineSampler(cfg, **kw)


# ---- config -------------------------------------------------------------

def test_config_defaults_and_validation():
    cfg = TimelineConfig.from_spec(None)
    assert cfg.enabled and cfg.tick_s == 1.0 and cfg.retention_s == 600.0
    assert cfg.ring_capacity == 600
    cfg = TimelineConfig.from_spec(
        {"tickS": 0.5, "retentionS": 30,
         "burnRate": {"target": 0.99, "fastWindowS": 5, "slowWindowS": 60},
         "rules": {"shedRateMax": 2.5},
         "incidents": {"capacity": 8, "cooldownS": 7}})
    assert cfg.ring_capacity == 60
    assert cfg.burn.target == 0.99
    assert cfg.shed_rate_max == 2.5
    assert cfg.incident_capacity == 8 and cfg.cooldown_s == 7.0
    with pytest.raises(ValueError):
        TimelineConfig.from_spec({"tickS": 0})
    with pytest.raises(ValueError):
        TimelineConfig.from_spec({"burnRate": {"target": 1.5}})
    with pytest.raises(ValueError):
        TimelineConfig.from_spec(
            {"burnRate": {"fastWindowS": 60, "slowWindowS": 5}})


# ---- ring bounds + bucket alignment ------------------------------------

def test_ring_bounds_and_killswitch():
    cfg = TimelineConfig.from_spec({"tickS": 1.0, "retentionS": 5})
    s = _sampler(cfg)
    for i in range(50):
        s.tick(wall=1000.0 + i)
    assert len(s.ring) == 5  # retentionS / tickS, older ticks evicted
    assert [x["t_unix"] for x in s.ring] == [1045.0, 1046.0, 1047.0,
                                             1048.0, 1049.0]
    # Kill-switch: tick() is inert, snapshot still answers.
    off = _sampler(TimelineConfig.from_spec({"enabled": False}))
    assert off.tick(wall=1.0) is None
    assert len(off.ring) == 0
    snap = off.snapshot()
    assert snap["enabled"] is False and snap["ticks"] == 0


def test_grid_alignment_shared_across_processes():
    """Two samplers ticking on the same wall grid land in the same
    merge_timeline bucket — the property that makes the fleet merge a
    pure function of wall time, no cross-process coordination."""
    cfg = TimelineConfig.from_spec({"tickS": 0.5, "retentionS": 10})
    a, b = _sampler(cfg), _sampler(cfg)
    for i in range(4):
        t = 2000.0 + i * 0.5
        a.tick(wall=t)
        b.tick(wall=t + 0.01)  # scheduling jitter inside the same bucket
    merged = merge_timeline(
        [(0, a.snapshot()), (1, b.snapshot())], workers=2)
    assert len(merged["buckets"]) == 4
    assert all(set(r["shards"]) == {"0", "1"} for r in merged["buckets"])
    assert merged["gap_buckets"] == 0


def test_snapshot_window_and_aggregates():
    cfg = TimelineConfig.from_spec({"tickS": 1.0, "retentionS": 60})
    s = _sampler(cfg, inflight_fn=iter(range(100)).__next__)
    for i in range(20):
        s.tick(wall=3000.0 + i)
    snap = s.snapshot(window_s=5.0)
    assert snap["ticks"] == 6  # samples inside the trailing 5 s
    agg = snap["aggregates"]["inflight"]
    assert agg["min"] == 14 and agg["max"] == 19
    assert agg["rate_per_s"] == 1.0  # inflight_fn advances 1/tick
    assert "p99" in agg and "p50" in agg


# ---- burn-rate windows --------------------------------------------------

def test_burn_rate_fast_and_slow_windows():
    cfg = TimelineConfig.from_spec(
        {"tickS": 1.0,
         "burnRate": {"target": 0.9, "fastWindowS": 2, "slowWindowS": 10,
                      "fastBurn": 4.0, "slowBurn": 2.0}})
    mon = BurnRateMonitor(cfg)
    # Healthy traffic: 10 arrivals/tick, 10 met → burn 0 everywhere.
    for _ in range(10):
        mon.add(10, 10)
    assert mon.rates() == (0.0, 0.0)
    # Total outage for 2 ticks: the FAST window sees 100% miss (burn 10 =
    # 1.0/0.1); the slow window still holds 8 healthy ticks so it lags.
    mon.add(10, 0)
    mon.add(10, 0)
    fast, slow = mon.rates()
    assert fast == pytest.approx(10.0)
    assert slow == pytest.approx((20 / 100) / 0.1)  # 2 bad of 10 ticks
    # Trip requires BOTH: a 2-tick blip does not confirm on the slow
    # window (slow 2.0 is exactly at threshold → tripped, so check the
    # one-tick case instead).
    assert mon.tripped(10.0, 1.0) is False
    assert mon.tripped(10.0, 2.0) is True
    # Idle window: no arrivals → burn 0, not NaN/latch.
    empty = BurnRateMonitor(cfg)
    assert empty.rates() == (0.0, 0.0)


def test_burn_counts_sheds_as_budget_burn():
    """Arrival-relative by design: a shed request burns the user-facing
    goodput budget even though /debug/slo's served-relative attainment
    excludes it."""
    cfg = TimelineConfig.from_spec(
        {"tickS": 1.0, "burnRate": {"target": 0.9, "fastWindowS": 1,
                                    "slowWindowS": 1}})
    mon = BurnRateMonitor(cfg)
    mon.add(10, 5)  # 5 met, 5 shed (none "missed" in ledger terms)
    fast, _ = mon.rates()
    assert fast == pytest.approx(5.0)


# ---- incident trigger / dedup / cooldown --------------------------------

def _mk_recorder(cfg, clock):
    return IncidentRecorder(cfg, slo_snapshot_fn=lambda: {"slo": 1},
                            kv_snapshot_fn=lambda: {"kv": 1},
                            decisions_fn=lambda k: [{"d": i}
                                                    for i in range(k)],
                            wall=clock)


def test_incident_trigger_dedup_and_cooldown():
    t = [5000.0]
    cfg = TimelineConfig.from_spec(
        {"incidents": {"capacity": 4, "contextTicks": 2, "cooldownS": 30,
                       "maxDecisions": 3}})
    rec = _mk_recorder(cfg, lambda: t[0])

    def obs(tripped, sample, ctx=()):
        rec.observe(tripped, sample, lambda: list(ctx))
        t[0] += 1.0

    # Trip sustained over 5 ticks → ONE incident with ticks=5 and the
    # context + trigger + post-trigger samples in the window (± N bound).
    ctx = [{"t_unix": 1}, {"t_unix": 2}]
    obs({RULE_BURN_RATE: "hot"}, {"t_unix": 3}, ctx)
    for i in range(4):
        obs({RULE_BURN_RATE: "hot"}, {"t_unix": 4 + i})
    snap = rec.snapshot()
    assert snap["count"] == 1
    inc = snap["incidents"][0]
    assert inc["ticks"] == 5
    assert inc["rule"] == RULE_BURN_RATE
    # window = 2 pre-trigger + trigger + post-trigger ticks, ≤ 2N+1 = 5.
    assert [w["t_unix"] for w in inc["window"]] == [1, 2, 3, 4, 5]
    assert inc["slo"] == {"slo": 1} and inc["kv"] == {"kv": 1}
    assert len(inc["decisions"]) == 3
    # Clear, then re-trip INSIDE the cooldown: same incident, retrip
    # counted, not a new ring entry.
    obs({}, {"t_unix": 9})
    assert "cleared_unix" in rec.snapshot()["incidents"][0]
    obs({RULE_BURN_RATE: "hot again"}, {"t_unix": 10})
    snap = rec.snapshot()
    assert snap["count"] == 1
    assert snap["incidents"][0]["retrips"] == 1
    # Clear, jump PAST the cooldown: a fresh trip mints a new incident.
    obs({}, {"t_unix": 11})
    t[0] += 100.0
    obs({RULE_BURN_RATE: "new episode"}, {"t_unix": 12})
    snap = rec.snapshot()
    assert snap["count"] == 2
    assert snap["incidents"][0]["id"] != snap["incidents"][1]["id"]


def test_incident_rules_independent_and_ring_bounded():
    t = [6000.0]
    cfg = TimelineConfig.from_spec(
        {"incidents": {"capacity": 3, "cooldownS": 0.0}})
    rec = _mk_recorder(cfg, lambda: t[0])
    # Two different rules tripping the same tick → two incidents.
    rec.observe({RULE_BURN_RATE: "a", RULE_SHED_RATE: "b"},
                {"t_unix": 1}, list)
    assert rec.snapshot()["count"] == 2
    # Flapping one rule past the (zero) cooldown floods… into the bounded
    # ring.
    for i in range(10):
        t[0] += 1.0
        rec.observe({}, {"t_unix": 2 + i}, list)
        t[0] += 1.0
        rec.observe({RULE_SHED_RATE: "flap"}, {"t_unix": 2 + i}, list)
    assert rec.snapshot()["count"] == 3  # capacity bound holds


# ---- sampler end-to-end over wired sources ------------------------------

def test_sampler_signals_and_shed_rule():
    ledger = SloLedger(SloConfig())
    ds = Datastore()
    kv = CacheLedger(KvObsConfig(enabled=True), datastore=ds)
    cfg = TimelineConfig.from_spec(
        {"tickS": 1.0, "retentionS": 60,
         "rules": {"shedRateMax": 2.0},
         # Burn thresholds out of reach: this test isolates the shed rule
         # (a shed spike inherently burns arrival-relative budget too).
         "burnRate": {"fastBurn": 1e9, "slowBurn": 1e9},
         "incidents": {"cooldownS": 300}})
    s = _sampler(cfg, slo_ledger=ledger, kv_ledger=kv, datastore=ds,
                 inflight_fn=lambda: 4, drain_rate_fn=lambda: 9.5,
                 degraded_fn=lambda: 2)
    ledger._totals.requests = 10
    ledger._totals.slo_met = 8
    ledger._totals.output_tokens = 100
    ledger._totals.goodput_tokens = 90
    ledger.prompt_tokens_total = 50
    ledger.tokens_by_role = {"decode": (50, 100)}
    sample = s.tick(wall=7000.0)
    assert sample["requests"] == 10 and sample["slo_met"] == 8
    assert sample["attainment"] == 0.8
    assert sample["inflight"] == 4
    assert sample["drain_rate_rps"] == 9.5
    assert sample["degraded"] == 2
    assert sample["token_mix"] == {
        "prefill_tokens": 50, "decode_tokens": 100,
        "prefill_fraction": round(50 / 150, 4),
        "by_role": {"decode": {"prompt": 50, "completion": 100}}}
    assert sample["kv"] == {"stamps": 0, "joins": 0}
    assert sample["process"]["rss_bytes"] > 0
    # Deltas reset: an idle second tick reports zeros, not cumulative.
    sample2 = s.tick(wall=7001.0)
    assert sample2["requests"] == 0 and sample2["token_mix"][
        "prefill_tokens"] == 0
    # Shed-rate excursion trips the rule into an incident.
    ledger._totals.requests = 20
    ledger._totals.shed = 8
    s.tick(wall=7002.0)
    snap = s.incidents.snapshot()
    assert snap["count"] == 1
    assert snap["incidents"][0]["rule"] == RULE_SHED_RATE
    assert snap["incidents"][0]["trigger"]["shed"] == 8


# ---- fleet merge: gaps marked, no interpolation -------------------------

def test_merge_timeline_marks_gaps():
    tick = 1.0

    def doc(ts):
        return {"enabled": True, "tick_s": tick,
                "samples": [{"t_unix": t, "inflight": 1} for t in ts]}

    # Shard 1 missing the middle two buckets (down), and shard 2 never
    # responded at all (not in docs) — every bucket gap-marks it.
    merged = merge_timeline(
        [(0, doc([100.0, 101.0, 102.0, 103.0])),
         (1, doc([100.0, 103.0]))],
        workers=3)
    assert merged["workers"] == 3 and merged["responding"] == [0, 1]
    gaps = {r["t_unix"]: r.get("gaps") for r in merged["buckets"]}
    assert gaps == {100.0: [2], 101.0: [1, 2], 102.0: [1, 2],
                    103.0: [2]}
    assert merged["gap_buckets"] == 4
    # No interpolation: absent means absent.
    mid = [r for r in merged["buckets"] if r["t_unix"] == 101.0][0]
    assert "1" not in mid["shards"]
    # Supervisor series rides beside the worker buckets.
    sup = [{"t_unix": 101.0, "kv_index_divergence_max": 0.4}]
    merged = merge_timeline([(0, doc([100.0]))], workers=1, supervisor=sup)
    assert merged["supervisor"] == sup
    # Bucket collision (a stalled loop's late tick rounding into the next
    # tick's bucket): the closest-to-center sample wins and the displaced
    # one is COUNTED, not silently dropped.
    merged = merge_timeline([(0, doc([100.0, 100.6, 101.0]))], workers=1)
    assert [r["t_unix"] for r in merged["buckets"]] == [100.0, 101.0]
    assert merged["buckets"][1]["shards"]["0"]["t_unix"] == 101.0
    assert merged["collapsed_samples"] == {"0": 1}
    assert merged["gap_buckets"] == 0


# ---- config redaction + hash -------------------------------------------

def test_redact_config_and_hash():
    doc = {
        "tlsClient": {"caCertPath": "/etc/certs/ca.pem",
                      "insecureSkipVerify": False},
        "kube": {"tokenPath": "/var/run/secrets/token"},
        "watchPath": "/opt/router/config.yaml",
        "pool": {"endpoints": [{"address": "10.0.0.1", "port": 8200}]},
        "scheduling": {"pickSeed": 7},
    }
    red = redact_config(doc)
    flat = json.dumps(red)
    assert "/etc/certs" not in flat and "/var/run" not in flat
    assert "/opt/router" not in flat
    assert red["tlsClient"]["caCertPath"] == "***"       # secret fragment
    assert red["watchPath"] == "***/config.yaml"         # path: basename kept
    assert red["scheduling"]["pickSeed"] == 7            # knobs untouched
    assert red["pool"]["endpoints"][0]["address"] == "10.0.0.1"
    # The hash covers the UNREDACTED doc: secret-only differences must
    # change it (fleet skew detection), and it is stable across calls.
    other = json.loads(json.dumps(doc))
    other["kube"]["tokenPath"] = "/var/run/secrets/other"
    assert config_hash(doc) == config_hash(json.loads(json.dumps(doc)))
    assert config_hash(doc) != config_hash(other)
    assert redact_config(red) == red  # idempotent


# ---- gateway e2e: routes + kill-switch ---------------------------------

GW_CFG = """
pool:
  endpoints: []
timeline:
  tickS: 0.05
  retentionS: 10
slo: {defaultTtftMs: 100}
"""

KILL_CFG = """
pool:
  endpoints: []
timeline: {enabled: false}
"""


def test_gateway_timeline_surfaces():
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    async def body():
        gw = build_gateway(GW_CFG, port=GW_A, poll_interval=60.0)
        await gw.start()
        try:
            await asyncio.sleep(0.4)
            async with httpx.AsyncClient(timeout=10) as c:
                base = f"http://127.0.0.1:{GW_A}"
                tl = (await c.get(base + "/debug/timeline")).json()
                assert tl["enabled"] and tl["ticks"] >= 3
                assert tl["tick_s"] == 0.05
                last = tl["samples"][-1]
                assert "process" in last and "burn" in last
                assert "snapshot_epoch" in last
                # Windowed view trims; aggregates render.
                tl2 = (await c.get(
                    base + "/debug/timeline?window_s=0.1")).json()
                assert tl2["ticks"] <= tl["ticks"]
                inc = (await c.get(base + "/debug/incidents")).json()
                assert inc == {"enabled": True, "count": 0,
                               "incidents": []}
                cfgdoc = (await c.get(base + "/debug/config")).json()
                assert cfgdoc["hash"] == gw.config_hash
                assert cfgdoc["config"]["timeline"]["tickS"] == 0.05
                # /debug/profile structured output (the verify-debug probe
                # drives this same real path).
                prof = (await c.get(
                    base + "/debug/profile?seconds=0.05&format=json&n=5"
                )).json()
                assert prof["seconds"] == 0.05
                assert 0 < len(prof["rows"]) <= 5
                assert {"function", "ncalls",
                        "cumtime_s"} <= set(prof["rows"][0])
        finally:
            await gw.stop()

    run(body())


def test_gateway_timeline_killswitch_inert():
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    async def body():
        gw = build_gateway(KILL_CFG, port=GW_B, poll_interval=60.0)
        await gw.start()
        try:
            await asyncio.sleep(0.15)
            # No sampler task, no gc callback installed, empty ring — and
            # the surfaces still answer JSON.
            assert gw.timeline._task is None
            assert not gw.timeline.gc_pause._installed
            async with httpx.AsyncClient(timeout=10) as c:
                base = f"http://127.0.0.1:{GW_B}"
                tl = (await c.get(base + "/debug/timeline")).json()
                assert tl["enabled"] is False and tl["ticks"] == 0
                inc = (await c.get(base + "/debug/incidents")).json()
                assert inc["enabled"] is False and inc["count"] == 0
        finally:
            await gw.stop()

    run(body())


# ---- fleet admin fan-in against stub workers ----------------------------

def _stub(port, *, samples, spans, cfg_hash):
    app = web.Application()

    async def timeline(request):
        return web.json_response({"enabled": True, "tick_s": 1.0,
                                  "samples": samples})

    async def incidents(request):
        return web.json_response(
            {"enabled": True, "count": 1,
             "incidents": [{"id": f"inc-{port}", "rule": "burn_rate",
                            "first_unix": port}]})

    async def config(request):
        return web.json_response({"hash": cfg_hash, "config": {"p": port}})

    async def traces(request):
        return web.json_response({"spans": spans})

    app.add_routes([web.get("/debug/timeline", timeline),
                    web.get("/debug/incidents", incidents),
                    web.get("/debug/config", config),
                    web.get("/debug/traces", traces)])
    return app, port


def test_fleet_admin_timeline_incidents_config_traces():
    from llm_d_inference_scheduler_tpu.router.fleet import FleetAdmin

    async def body():
        shared = {"span_id": "s-shared", "name": "gateway.request"}
        runners = []
        for app, port in (
                _stub(STUB_A, samples=[{"t_unix": 100.0, "inflight": 1},
                                       {"t_unix": 101.0, "inflight": 1}],
                      spans=[shared, {"span_id": "s-a", "name": "a"}],
                      cfg_hash="h1"),
                _stub(STUB_B, samples=[{"t_unix": 100.0, "inflight": 2}],
                      spans=[shared, {"span_id": "s-b", "name": "b"}],
                      cfg_hash="h2")):
            runner = web.AppRunner(app)
            await runner.setup()
            await web.TCPSite(runner, "127.0.0.1", port).start()
            runners.append(runner)
        admin = FleetAdmin([("127.0.0.1", STUB_A), ("127.0.0.1", STUB_B)],
                           host="127.0.0.1", port=STUB_ADMIN)
        await admin.start()
        try:
            async with httpx.AsyncClient(timeout=10) as c:
                base = f"http://127.0.0.1:{STUB_ADMIN}"
                # Merged timeline: bucket 100 has both shards, bucket 101
                # gap-marks shard 1 (down — no interpolation).
                tl = (await c.get(base + "/debug/timeline")).json()
                assert tl["workers"] == 2
                by_t = {r["t_unix"]: r for r in tl["buckets"]}
                assert set(by_t[100.0]["shards"]) == {"0", "1"}
                assert by_t[101.0].get("gaps") == [1]
                assert tl["gap_buckets"] == 1
                # Incidents: shard-annotated union, newest first.
                inc = (await c.get(base + "/debug/incidents")).json()
                assert inc["count"] == 2
                assert {i["shard"] for i in inc["incidents"]} == {0, 1}
                firsts = [i["first_unix"] for i in inc["incidents"]]
                assert firsts == sorted(firsts, reverse=True)
                # Config skew: two hashes → consistent false.
                cfg = (await c.get(base + "/debug/config")).json()
                assert cfg["consistent"] is False
                assert [s["hash"] for s in cfg["shards"]] == ["h1", "h2"]
                assert cfg["config"] == {"p": STUB_A}
                # Traces fan-in: dedup by span_id across shards.
                tr = (await c.get(base + "/debug/traces")).json()
                ids = [s["span_id"] for s in tr["spans"]]
                assert sorted(ids) == ["s-a", "s-b", "s-shared"]
        finally:
            await admin.stop()
            for runner in runners:
                await runner.cleanup()

    run(body())


# ---- CI hook ------------------------------------------------------------

def test_verify_debug_probes_profile_real_path():
    """The satellite contract: verify_debug drives /debug/profile through
    the REAL capture path (?seconds>0&format=json), not the 400 branch."""
    import verify_debug

    q = verify_debug.QUERY_OVERRIDES["/debug/profile"]
    assert "format=json" in q
    assert "seconds=0&" not in q and not q.endswith("seconds=0")
