"""Pipeline-parallel serving: the stage ring (parallel/pp_serve.py) through
the full engine must reproduce the single-device engine's greedy tokens.
Runs on the virtual CPU mesh (conftest pins 8 devices)."""

from __future__ import annotations

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig, EngineRequest
from llm_d_inference_scheduler_tpu.engine.core import TpuEngine
from llm_d_inference_scheduler_tpu.models import llama
from llm_d_inference_scheduler_tpu.models.configs import get_config

PROMPT = [1, 7, 19, 4, 33, 2, 9]


async def _run(cfg, params, n_gen=6):
    eng = TpuEngine(cfg, params=params)
    await eng.start()
    try:
        req = EngineRequest(request_id="pp", prompt_token_ids=list(PROMPT),
                            max_tokens=n_gen, temperature=0.0,
                            ignore_eos=True)
        out = eng.submit(req)
        got = []
        while True:
            ev = await out.get()
            if ev.token_id is not None:
                got.append(ev.token_id)
            if ev.finish_reason is not None:
                break
        return got
    finally:
        await eng.stop()


def test_pp_engine_matches_single_device():
    # f32 keeps greedy argmax robust to the ring's different reduce points.
    params = llama.init_params(get_config("tiny"), jax.random.key(5),
                               dtype=jnp.float32)

    def cfg(pp):
        return EngineConfig(model="tiny", backend="tpu", max_batch=2,
                            max_model_len=64, decode_chunk=4, seed=5,
                            kv_events_port=0, pp_size=pp,
                            enable_prefix_caching=False)

    single = asyncio.run(_run(cfg(1), params))
    piped = asyncio.run(_run(cfg(2), params))
    assert len(single) == 6
    assert piped == single


def test_pp_ring_logits_match_plain_decode():
    """Op-level: one ring decode step vs llama.decode_step on real pages."""
    from llm_d_inference_scheduler_tpu.parallel.pp_serve import (
        alloc_pp_pages,
        make_pp_decode_chunk,
        make_pp_mesh,
        shard_params_pp,
    )

    cfg = get_config("tiny")
    mesh = make_pp_mesh(jax.devices()[:2], 2)
    params = llama.init_params(cfg, jax.random.key(1), dtype=jnp.float32)

    B, n_blocks = 2, 9
    block = cfg.kv_block_size
    maxB = 4
    kshape = (cfg.n_layers, n_blocks, block, cfg.n_kv_heads, cfg.head_dim)
    k_pages = jnp.asarray(
        np.random.default_rng(0).normal(size=kshape), jnp.float32)
    v_pages = jnp.asarray(
        np.random.default_rng(1).normal(size=kshape), jnp.float32)
    tables = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    tokens = jnp.asarray([3, 9], jnp.int32)
    positions = jnp.asarray([17, 22], jnp.int32)

    ref_logits, rk, rv = llama.decode_step(
        params, cfg, tokens, positions, k_pages, v_pages, tables)

    pp_params = shard_params_pp(params, cfg, mesh)
    pk, pv = alloc_pp_pages(cfg, mesh, n_blocks)
    pk = jax.device_put(k_pages, pk.sharding)
    pv = jax.device_put(v_pages, pv.sharding)
    chunk = make_pp_decode_chunk(cfg, mesh, decode_chunk=1)
    toks, pk, pv = chunk(pp_params, tokens, positions, pk, pv, tables,
                         jax.random.key(0),
                         jnp.zeros((B,), jnp.float32),      # temp 0 = greedy
                         jnp.zeros((B,), jnp.int32),
                         jnp.ones((B,), jnp.float32))

    expected = np.argmax(np.asarray(ref_logits), axis=-1)
    np.testing.assert_array_equal(np.asarray(toks)[0], expected)
    # KV writes landed identically in every REAL block. Block 0 is the trash
    # block: the ring's off-turn writes redirect there (plain decode doesn't
    # touch it), so its contents are undefined by design.
    np.testing.assert_allclose(np.asarray(pk)[:, 1:], np.asarray(rk)[:, 1:],
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(pv)[:, 1:], np.asarray(rv)[:, 1:],
                               atol=1e-5)


def test_pp_rejects_bad_geometry():
    with pytest.raises(ValueError, match="does not divide"):
        TpuEngine(EngineConfig(model="tiny", backend="tpu", pp_size=3,
                               kv_events_port=0))


def test_pp_tp_engine_matches_single_device():
    """pp×tp composition: a 2-stage ring with TP-2 slabs through the full
    engine reproduces the single-device greedy tokens."""
    params = llama.init_params(get_config("tiny"), jax.random.key(5),
                               dtype=jnp.float32)

    def cfg(pp, tp):
        return EngineConfig(model="tiny", backend="tpu", max_batch=2,
                            max_model_len=64, decode_chunk=4, seed=5,
                            kv_events_port=0, pp_size=pp, tp_size=tp,
                            enable_prefix_caching=False)

    single = asyncio.run(_run(cfg(1, 1), params))
    composed = asyncio.run(_run(cfg(2, 2), params))
    assert len(single) == 6
    assert composed == single


async def _run_pair(cfg, params, prompts, n_gen=6):
    """Two concurrent requests — fills the B=2 decode bucket so the pp
    engine exercises the lane-group interleave schedule."""
    eng = TpuEngine(cfg, params=params)
    await eng.start()
    try:
        outs = [eng.submit(EngineRequest(request_id=f"pp{i}",
                                         prompt_token_ids=list(p),
                                         max_tokens=n_gen, temperature=0.0,
                                         ignore_eos=True))
                for i, p in enumerate(prompts)]

        async def drain(out):
            got = []
            while True:
                ev = await out.get()
                if ev.token_id is not None:
                    got.append(ev.token_id)
                if ev.finish_reason is not None:
                    return got

        return await asyncio.gather(*(drain(o) for o in outs))
    finally:
        await eng.stop()


def test_pp_interleaved_engine_two_streams_match_single_device():
    params = llama.init_params(get_config("tiny"), jax.random.key(5),
                               dtype=jnp.float32)
    prompts = [PROMPT, [5, 11, 2, 8, 40]]

    def cfg(pp):
        return EngineConfig(model="tiny", backend="tpu", max_batch=2,
                            max_model_len=64, decode_chunk=4, seed=5,
                            kv_events_port=0, pp_size=pp,
                            enable_prefix_caching=False)

    single = asyncio.run(_run_pair(cfg(1), params, prompts))
    piped = asyncio.run(_run_pair(cfg(2), params, prompts))
    assert all(len(s) == 6 for s in single)
    assert piped == single


def test_pp_interleaved_chunk_matches_plain_decode_loop():
    """Op-level: a K-token interleaved chunk (lane groups through the full
    ring pipeline) reproduces a greedy plain-decode loop, tokens AND page
    writes."""
    from llm_d_inference_scheduler_tpu.parallel.pp_serve import (
        alloc_pp_pages,
        make_pp_decode_chunk_interleaved,
        make_pp_mesh,
        shard_params_pp,
    )

    cfg = get_config("tiny")
    mesh = make_pp_mesh(jax.devices()[:2], 2)
    params = llama.init_params(cfg, jax.random.key(1), dtype=jnp.float32)

    B, K, n_blocks = 4, 3, 25
    block = cfg.kv_block_size
    max_blocks = 6
    kshape = (cfg.n_layers, n_blocks, block, cfg.n_kv_heads, cfg.head_dim)
    k_pages = jnp.asarray(
        np.random.default_rng(0).normal(size=kshape), jnp.float32)
    v_pages = jnp.asarray(
        np.random.default_rng(1).normal(size=kshape), jnp.float32)
    tables = jnp.asarray(
        [[1 + b * max_blocks + i for i in range(max_blocks)]
         for b in range(B)], jnp.int32)
    tokens = jnp.asarray([3, 9, 14, 27], jnp.int32)
    positions = jnp.asarray([7, 12, 3, 18], jnp.int32)

    # Reference: greedy plain-decode loop on the same pages.
    rk, rv = k_pages, v_pages
    toks, pos = tokens, positions
    expected = []
    for _ in range(K):
        logits, rk, rv = llama.decode_step(params, cfg, toks, pos, rk, rv,
                                           tables)
        toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        expected.append(np.asarray(toks))
        pos = pos + 1

    pp_params = shard_params_pp(params, cfg, mesh)
    pk, pv = alloc_pp_pages(cfg, mesh, n_blocks)
    pk = jax.device_put(k_pages, pk.sharding)
    pv = jax.device_put(v_pages, pv.sharding)
    chunk = make_pp_decode_chunk_interleaved(cfg, mesh, K)
    got, pk, pv = chunk(pp_params, tokens, positions, pk, pv, tables,
                        jax.random.key(0),
                        jnp.zeros((B,), jnp.float32),   # temp 0 = greedy
                        jnp.zeros((B,), jnp.int32),
                        jnp.ones((B,), jnp.float32))

    np.testing.assert_array_equal(np.asarray(got), np.stack(expected))
    np.testing.assert_allclose(np.asarray(pk)[:, 1:], np.asarray(rk)[:, 1:],
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(pv)[:, 1:], np.asarray(rv)[:, 1:],
                               atol=1e-5)


def test_pp_tp_ring_logits_match_plain_decode():
    """Op-level: one pp×tp ring decode step vs llama.decode_step, including
    the KV writes landing in the (pp, tp)-sharded pages."""
    from llm_d_inference_scheduler_tpu.parallel.pp_serve import (
        alloc_pp_pages,
        make_pp_decode_chunk,
        make_pp_mesh,
        shard_params_pp,
    )

    cfg = get_config("tiny")
    mesh = make_pp_mesh(jax.devices()[:4], 2, tp=2)
    params = llama.init_params(cfg, jax.random.key(1), dtype=jnp.float32)

    B, n_blocks = 2, 9
    block = cfg.kv_block_size
    kshape = (cfg.n_layers, n_blocks, block, cfg.n_kv_heads, cfg.head_dim)
    k_pages = jnp.asarray(
        np.random.default_rng(0).normal(size=kshape), jnp.float32)
    v_pages = jnp.asarray(
        np.random.default_rng(1).normal(size=kshape), jnp.float32)
    tables = jnp.asarray([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    tokens = jnp.asarray([3, 9], jnp.int32)
    positions = jnp.asarray([17, 22], jnp.int32)

    ref_logits, rk, rv = llama.decode_step(
        params, cfg, tokens, positions, k_pages, v_pages, tables)

    pp_params = shard_params_pp(params, cfg, mesh)
    pk, pv = alloc_pp_pages(cfg, mesh, n_blocks)
    pk = jax.device_put(k_pages, pk.sharding)
    pv = jax.device_put(v_pages, pv.sharding)
    chunk = make_pp_decode_chunk(cfg, mesh, decode_chunk=1)
    toks, pk, pv = chunk(pp_params, tokens, positions, pk, pv, tables,
                         jax.random.key(0),
                         jnp.zeros((B,), jnp.float32),
                         jnp.zeros((B,), jnp.int32),
                         jnp.ones((B,), jnp.float32))

    expected = np.argmax(np.asarray(ref_logits), axis=-1)
    np.testing.assert_array_equal(np.asarray(toks)[0], expected)
    np.testing.assert_allclose(np.asarray(pk)[:, 1:], np.asarray(rk)[:, 1:],
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(pv)[:, 1:], np.asarray(rv)[:, 1:],
                               atol=1e-5)


def test_pp_engine_prefix_cache_hit_matches_single_device():
    """pp × prefix caching (VERDICT r2 next #7): the prefix-ring prefill
    (make_pp_prefill_with_prefix) reuses cached blocks under pp — second
    identical prompt reports cached tokens and reproduces the single-device
    cached-path greedy tokens; a different prompt misses."""
    params = llama.init_params(get_config("tiny"), jax.random.key(5),
                               dtype=jnp.float32)
    prompt = [1] + list(range(100, 140))  # 41 tokens: 2 full 16-blocks

    def cfg(pp, tp=1):
        return EngineConfig(model="tiny", backend="tpu", max_batch=2,
                            max_model_len=256, decode_chunk=4, seed=5,
                            kv_events_port=0, pp_size=pp, tp_size=tp,
                            enable_prefix_caching=True)

    async def run_twice(c):
        eng = TpuEngine(c, params=params)
        await eng.start()
        try:
            async def gen(rid, ids):
                out = eng.submit(EngineRequest(
                    request_id=rid, prompt_token_ids=ids, max_tokens=6,
                    temperature=0.0, ignore_eos=True))
                toks, cached = [], 0
                while True:
                    ev = await asyncio.wait_for(out.get(), timeout=120)
                    cached = max(cached, ev.cached_tokens)
                    if ev.token_id is not None:
                        toks.append(ev.token_id)
                    if ev.finish_reason is not None:
                        return toks, cached

            t1, c1 = await gen("first", prompt)
            t2, c2 = await gen("second", prompt)
            t3, c3 = await gen("other", [1] + list(range(500, 540)))
            return t1, c1, t2, c2, c3
        finally:
            await eng.stop()

    s1, sc1, s2, sc2, sc3 = asyncio.run(run_twice(cfg(1)))
    assert sc1 == 0 and sc2 == 32 and sc3 == 0
    assert s2 == s1

    p1, pc1, p2, pc2, pc3 = asyncio.run(run_twice(cfg(2)))
    assert pc1 == 0 and pc2 == 32 and pc3 == 0   # ring hit the cache
    assert p1 == s1 and p2 == s2                 # token parity w/ single dev

    q1, qc1, q2, qc2, _ = asyncio.run(run_twice(cfg(2, tp=2)))
    assert qc2 == 32
    assert q1 == s1 and q2 == s2                 # pp×tp parity too


def test_pp_engine_multimodal_matches_single_device():
    """Multimodal prefill under pp: the encoder-embedding splice rides the
    stage-0 embedding of the prefill ring (make_pp_prefill mm=True) and must
    reproduce the single-device engine's greedy tokens."""
    mcfg = get_config("tiny")
    params = llama.init_params(mcfg, jax.random.key(9), dtype=jnp.float32)
    rng = np.random.default_rng(3)
    mm = rng.normal(size=(2, mcfg.d_model)).astype(np.float32)

    def cfg(pp):
        return EngineConfig(model="tiny", backend="tpu", max_batch=2,
                            max_model_len=64, decode_chunk=4, seed=9,
                            kv_events_port=0, pp_size=pp,
                            enable_prefix_caching=False)

    async def run(c):
        eng = TpuEngine(c, params=params)
        await eng.start()
        try:
            req = EngineRequest(request_id="pp-mm",
                                prompt_token_ids=list(PROMPT),
                                mm_embeds=mm, mm_positions=[1, 2],
                                max_tokens=5, temperature=0.0,
                                ignore_eos=True)
            out = eng.submit(req)
            got = []
            while True:
                ev = await out.get()
                if ev.token_id is not None:
                    got.append(ev.token_id)
                if ev.finish_reason is not None:
                    assert ev.finish_reason.value != "abort"
                    break
            return got
        finally:
            await eng.stop()

    single = asyncio.run(run(cfg(1)))
    piped = asyncio.run(run(cfg(2)))
    assert len(single) == 5
    assert piped == single
    # And the splice changed the output vs the plain-text prompt (the mm
    # vectors are load-bearing, not dropped).
    plain = asyncio.run(_run(cfg(2), params, n_gen=5))
    assert plain != piped


def test_pp_engine_moe_matches_single_device():
    """MoE under pp: with ep=1 the stage slabs run the dense-over-experts
    FFN with full (replicated) experts; with ep>1 each device holds E/ep
    experts and the combine psums over (tp, ep) — both must reproduce the
    single-device engine token-for-token."""
    params = llama.init_params(get_config("tiny-moe"), jax.random.key(4),
                               dtype=jnp.float32)

    def cfg(pp, ep=1):
        return EngineConfig(model="tiny-moe", backend="tpu", max_batch=2,
                            max_model_len=64, decode_chunk=4, seed=4,
                            kv_events_port=0, pp_size=pp, ep_size=ep,
                            enable_prefix_caching=False)

    single = asyncio.run(_run(cfg(1), params))
    piped = asyncio.run(_run(cfg(2), params))
    assert len(single) == 6
    assert piped == single
    # Experts sharded under pp (VERDICT r4 next #4): pp=2 × ep=2.
    pp_ep = asyncio.run(_run(cfg(2, ep=2), params))
    assert pp_ep == single
    # pp × tp × ep together on 8 devices.
    from llm_d_inference_scheduler_tpu.models.configs import get_config as _gc

    if _gc("tiny-moe").n_kv_heads % 2 == 0:
        cfg3 = EngineConfig(model="tiny-moe", backend="tpu", max_batch=2,
                            max_model_len=64, decode_chunk=4, seed=4,
                            kv_events_port=0, pp_size=2, tp_size=2, ep_size=2,
                            enable_prefix_caching=False)
        assert asyncio.run(_run(cfg3, params)) == single
