"""Scalar ↔ vectorized scheduling parity (ISSUE 19).

The columnar hot path (Scheduler._run_batch over EndpointBatch) must be
BIT-identical to the scalar per-endpoint path — picks, DecisionRecord score
tables, sampled router_scorer_score observations, even the exception text
when a filter empties the pool. This suite sweeps random pools across sizes
(including degenerate ones), NaN/missing metrics, tie-heavy score
plateaus, overlay mutations mid-cycle, and an out-of-tree scalar-only
scorer riding the auto-adapter; plus the verify_vectorized coverage-lint
hook.
"""

import pathlib
import random
import sys
import time

import numpy as np
import pytest

import llm_d_inference_scheduler_tpu.router.plugins  # noqa: F401
import llm_d_inference_scheduler_tpu.router.plugins.saturation  # noqa: F401
from llm_d_inference_scheduler_tpu.router.config.loader import (
    Handle,
    load_config,
)
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.decisions import DecisionRecord
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.plugin import (
    PluginBase,
    global_registry,
    register_plugin,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
)
from llm_d_inference_scheduler_tpu.router.metrics import SCORER_SCORE
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    INFLIGHT_ATTRIBUTE_KEY,
    InFlightLoad,
)
from llm_d_inference_scheduler_tpu.router.schedpool import SchedulingConfig
from llm_d_inference_scheduler_tpu.router.snapshot import (
    EndpointBatch,
    PoolSnapshot,
)

# ---- out-of-tree-style test plugins (auto-adapter coverage) --------------
# Scalar-only on purpose: they model an operator extension that predates
# the columnar path. THREAD_SAFE so the schedpool lint stays clean; NOT in
# verify_vectorized's SCALAR_FALLBACK — the lint polices in-tree types only.


class _OutOfTreeQueueScorer(PluginBase):
    THREAD_SAFE = True

    def score(self, ctx, state, request, endpoints):
        return {ep.metadata.address_port:
                1.0 / (1.0 + ep.metrics.waiting_queue_size)
                for ep in endpoints}


class _OverlayLoadProducerFilter(PluginBase):
    """Stages per-request InFlightLoad overlays mid-cycle, the way a
    data producer would — later kernels must read the OVERLAY, not the
    snapshot's base attrs."""

    THREAD_SAFE = True

    def filter(self, ctx, state, request, endpoints):
        for i, ep in enumerate(endpoints):
            ep.attributes.put(INFLIGHT_ATTRIBUTE_KEY,
                              InFlightLoad(requests=(i * 7) % 5, tokens=i))
        return endpoints


def _register_once(type_name, cls):
    try:
        register_plugin(type_name)(cls)
    except ValueError:
        pass  # already registered by a prior import of this module


_register_once("test-oot-queue-scorer", _OutOfTreeQueueScorer)
_register_once("test-overlay-load-filter", _OverlayLoadProducerFilter)


# ---- pool + config helpers ------------------------------------------------


def mk_endpoints(n, seed=0, nan_frac=0.0, stale_frac=0.0, tie_levels=None):
    rng = random.Random(seed)
    now = time.monotonic()
    eps = []
    for i in range(n):
        role = rng.choice(["decode", "prefill", "both", None, "encode"])
        labels = {"llm-d.ai/role": role} if role else {}
        ep = Endpoint(EndpointMetadata(
            name=f"p{i}", address=f"10.0.{i // 256}.{i % 256}", port=8000,
            labels=labels))
        if tie_levels:
            ep.metrics.waiting_queue_size = rng.choice(tie_levels)
            ep.metrics.kv_cache_usage_percent = ep.metrics.waiting_queue_size / 50.0
            ep.metrics.running_requests_size = 1
        else:
            ep.metrics.waiting_queue_size = rng.randrange(0, 50)
            ep.metrics.kv_cache_usage_percent = rng.random()
            ep.metrics.running_requests_size = rng.randrange(0, 30)
        ep.metrics.kv_cache_max_token_capacity = rng.choice([0, 100000])
        if rng.random() < nan_frac:
            ep.metrics.kv_cache_usage_percent = float("nan")
        ep.metrics.update_time = 0.0 if rng.random() < stale_frac else now
        eps.append(ep)
    return eps


def mk_snapshot(eps, epoch=1):
    return PoolSnapshot.from_entries(
        epoch, [(e.metadata, e.metrics, e.attributes._data) for e in eps])


def mk_request(rid, decision=None):
    req = InferenceRequest(
        request_id=rid, target_model="m",
        body=InferenceRequestBody(completions={"model": "m", "prompt": "hi"}))
    if decision is not None:
        req.decision = decision
    return req


YAML = """
scheduling: {pickSeed: 7}
plugins:
  - type: decode-filter
  - type: fresh-metrics-filter
  - type: utilization-detector
  - type: queue-scorer
  - type: kv-cache-utilization-scorer
  - type: load-aware-scorer
  - type: context-length-aware-scorer
  - type: session-affinity-scorer
  - type: max-score-picker
schedulingProfiles:
  - name: default
    plugins:
      - pluginRef: decode-filter
      - pluginRef: fresh-metrics-filter
      - pluginRef: utilization-detector
      - pluginRef: queue-scorer
        weight: 2
      - pluginRef: kv-cache-utilization-scorer
        weight: 2
      - pluginRef: load-aware-scorer
        weight: 1
      - pluginRef: context-length-aware-scorer
        weight: 1
      - pluginRef: session-affinity-scorer
        weight: 1
      - pluginRef: max-score-picker
"""


def fresh_config(yaml_text=YAML):
    return load_config(yaml_text, Handle(datastore=Datastore()))


def _norm(x):
    """NaN-aware structural normalization: nan == nan for parity purposes
    (a NaN total produced identically by both paths IS parity)."""
    if isinstance(x, dict):
        return {k: _norm(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_norm(v) for v in x]
    if isinstance(x, float) and x != x:
        return "NaN"
    return x


def run_one(cfg, request, candidates):
    """Schedule, capturing either the result tuple or the exception text —
    failure parity matters as much as pick parity."""
    try:
        res = cfg.scheduler.schedule(None, request, candidates)
    except Exception as e:
        return ("error", str(e))
    prim = res.primary()
    return _norm(
        ("ok",
         [ep.metadata.address_port for ep in prim.target_endpoints],
         dict(prim.totals),
         {s: dict(t) for s, t in prim.raw_scores.items()}))


def assert_parity(eps, yaml_text=YAML, rids=("r1", "r2", "r3"), record=False):
    snap = mk_snapshot(eps)
    cfg_s = fresh_config(yaml_text)
    cfg_b = fresh_config(yaml_text)
    recs = []
    for rid in rids:
        rec_s = DecisionRecord(rid, "m", top_k=4096) if record else None
        rec_b = DecisionRecord(rid, "m", top_k=4096) if record else None
        out_s = run_one(cfg_s, mk_request(rid, rec_s), snap.view())
        out_b = run_one(cfg_b, mk_request(rid, rec_b), EndpointBatch(snap))
        assert out_s == out_b, (len(eps), rid, out_s[:2], out_b[:2])
        if record:
            recs.append((rec_s, rec_b))
    return recs


# ---- parity sweep ---------------------------------------------------------


@pytest.mark.parametrize("n", [1, 2, 8, 128, 1024])
def test_parity_random_pools(n):
    assert_parity(mk_endpoints(n, seed=n))


@pytest.mark.parametrize("n", [2, 8, 128])
def test_parity_nan_and_stale_metrics(n):
    # NaN kv columns force load-aware/max-score kernels to DECLINE (their
    # array forms can't reproduce Python's order-dependent min/max), so
    # this sweep exercises the decline→scalar-fallback path bit-exactly.
    assert_parity(mk_endpoints(n, seed=100 + n, nan_frac=0.3, stale_frac=0.3))


@pytest.mark.parametrize("n", [8, 128, 1024])
def test_parity_tie_plateaus(n):
    # Few distinct score levels → massive ties → the picker's seeded
    # shuffle/stable-sort tie-break must draw identically in both paths.
    assert_parity(mk_endpoints(n, seed=200 + n, tie_levels=[0, 3]),
                  rids=tuple(f"tie-{i}" for i in range(8)))


def test_parity_all_filtered_out():
    # Every pod prefill-only: decode-filter empties the set; both paths
    # must fail with the identical SchedulingError text.
    eps = mk_endpoints(8, seed=9)
    for ep in eps:
        ep.metadata.labels["llm-d.ai/role"] = "prefill"
    snap = mk_snapshot(eps)
    out_s = run_one(fresh_config(), mk_request("r"), snap.view())
    out_b = run_one(fresh_config(), mk_request("r"), EndpointBatch(snap))
    assert out_s[0] == "error" and out_s == out_b


def test_parity_single_endpoint_decode():
    eps = mk_endpoints(1, seed=3)
    eps[0].metadata.labels["llm-d.ai/role"] = "decode"
    assert_parity(eps)


def test_parity_overlay_mutation_mid_batch():
    # A producer-style filter stages InFlightLoad overlays mid-cycle; the
    # concurrency-detector kernel and active-request scorer read them back
    # through batch.views() — base columns are blind to overlay writes.
    yaml_text = """
scheduling: {pickSeed: 11}
plugins:
  - type: test-overlay-load-filter
  - type: concurrency-detector
    parameters: {capacity: 2, headroom: 0.0}
  - type: active-request-scorer
  - type: queue-scorer
  - type: max-score-picker
schedulingProfiles:
  - name: default
    plugins:
      - pluginRef: test-overlay-load-filter
      - pluginRef: concurrency-detector
      - pluginRef: active-request-scorer
        weight: 2
      - pluginRef: queue-scorer
      - pluginRef: max-score-picker
"""
    for n in (2, 8, 64):
        assert_parity(mk_endpoints(n, seed=300 + n), yaml_text=yaml_text)


def test_out_of_tree_scalar_scorer_through_adapter():
    # THREAD_SAFE scalar-only scorer, no config change, no kernel: the
    # auto-adapter must run it per-endpoint inside the vectorized cycle and
    # keep the cycle's picks bit-identical to the scalar path.
    yaml_text = """
scheduling: {pickSeed: 5}
plugins:
  - type: decode-filter
  - type: test-oot-queue-scorer
  - type: kv-cache-utilization-scorer
  - type: max-score-picker
schedulingProfiles:
  - name: default
    plugins:
      - pluginRef: decode-filter
      - pluginRef: test-oot-queue-scorer
        weight: 3
      - pluginRef: kv-cache-utilization-scorer
      - pluginRef: max-score-picker
"""
    assert not hasattr(_OutOfTreeQueueScorer, "score_batch")
    assert_parity(mk_endpoints(64, seed=77), yaml_text=yaml_text)


# ---- DecisionRecord + sampled metric parity ------------------------------


def _scorer_observations():
    """(label-tuple, sample-kind) → value for the shared SCORER_SCORE
    histogram: counts and per-bucket tallies (exact integers) plus sums."""
    out = {}
    for metric in SCORER_SCORE.collect():
        for s in metric.samples:
            key = (tuple(sorted(s.labels.items())),
                   s.name.rsplit("_", 1)[-1])
            out[key] = s.value
    return out


def test_decision_records_and_sampled_observations_identical():
    eps = mk_endpoints(32, seed=55)
    snap = mk_snapshot(eps)
    cfg_s = fresh_config()
    cfg_b = fresh_config()
    # Interleave runs per path so each config's 1-in-8 sampling counters
    # advance identically; diff the shared histogram between phases.
    rids = [f"rec-{i}" for i in range(10)]
    base = _scorer_observations()
    docs_s = []
    for rid in rids:
        rec = DecisionRecord(rid, "m", top_k=4096)
        run_one(cfg_s, mk_request(rid, rec), snap.view())
        docs_s.append(rec.to_dict())
    after_scalar = _scorer_observations()
    docs_b = []
    for rid in rids:
        rec = DecisionRecord(rid, "m", top_k=4096)
        run_one(cfg_b, mk_request(rid, rec), EndpointBatch(snap))
        docs_b.append(rec.to_dict())
    after_batch = _scorer_observations()

    for ds, db in zip(docs_s, docs_b):
        # Identical score tables, filter drops, picker choice + margin —
        # timestamps differ by construction, so compare the rounds section.
        assert _norm(ds["rounds"]) == _norm(db["rounds"])

    scalar_delta = {k: after_scalar[k] - base.get(k, 0)
                    for k in after_scalar}
    batch_delta = {k: after_batch[k] - after_scalar.get(k, 0)
                   for k in after_batch}
    assert set(scalar_delta) == set(batch_delta)
    for key, sv in scalar_delta.items():
        bv = batch_delta[key]
        if key[1] == "sum":
            # _sum accumulates: subtracting deltas from different float
            # bases rounds differently even for identical observations.
            assert bv == pytest.approx(sv, rel=1e-9, abs=1e-9), key
        else:
            # counts / bucket tallies / created timestamps-as-gauges:
            # bucket membership is exact, so identical observed VALUES
            # are required, not just identical totals.
            assert sv == bv or key[1] == "created", key
    # And the sampling scheme actually sampled something (1-in-8 over 10
    # recorded cycles → 2 observation rounds).
    assert any(v > 0 for (_, kind), v in scalar_delta.items()
               if kind == "count")


# ---- config knob + lint hook ---------------------------------------------


def test_vectorized_kill_switch_parses():
    assert SchedulingConfig.from_spec({}).vectorized is True
    assert SchedulingConfig.from_spec({"vectorized": False}).vectorized is False


def test_verify_vectorized_lint_clean():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                           / "scripts"))
    import verify_vectorized

    assert verify_vectorized.check() == []


def test_verify_vectorized_flags_silent_trampoline():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                           / "scripts"))
    import verify_vectorized

    from llm_d_inference_scheduler_tpu.router.plugins.scorers import (
        QueueScorer,
    )
    kernel = QueueScorer.score_batch
    try:
        del QueueScorer.score_batch
        errors = verify_vectorized.check()
    finally:
        QueueScorer.score_batch = kernel
    assert any("queue-scorer" in e and "score_batch" in e for e in errors)
