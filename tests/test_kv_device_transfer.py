"""Device-to-device KV handoff (VERDICT r1 item 6): jax.experimental.transfer
pull replaces the host-staged copy for P/D pairs; HTTP stays as fallback."""

import asyncio
import json

import httpx
import pytest

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer


def _cfg(port, role="both", **kw):
    return EngineConfig(backend="tpu", model="tiny", port=port, max_batch=4,
                        max_model_len=256, role=role, kv_events_port=0, **kw)


def _device_transfer_available() -> bool:
    """True when jax.experimental.transfer can actually start a transfer
    server on this backend. On CPU images the module is absent (or the
    server refuses to start), so the device-pull tests below cannot
    exercise their subject — skip them cleanly instead of failing (the
    same precedent as test_tls's ``importorskip("cryptography")``)."""
    try:
        from llm_d_inference_scheduler_tpu.engine.core import (
            _get_transfer_server,
        )

        _get_transfer_server()
        return True
    except Exception:
        return False


requires_device_transfer = pytest.mark.skipif(
    not _device_transfer_available(),
    reason="jax.experimental.transfer server unavailable on this backend "
           "(CPU image): device-to-device KV pull cannot run")


PROMPT = [1] + [(i * 11) % 400 + 3 for i in range(40)]


async def _pd_pair(pre_port, dec_port, **kw):
    pre = EngineServer(_cfg(pre_port, role="prefill", **kw))
    dec = EngineServer(_cfg(dec_port, role="decode", **kw))
    await pre.start()
    await dec.start()
    return pre, dec


async def _run_pd(pre_port, dec_port, mutate_ktp=None):
    async with httpx.AsyncClient(timeout=60) as c:
        r1 = await c.post(f"http://127.0.0.1:{pre_port}/v1/completions", json={
            "prompt": PROMPT, "max_tokens": 1, "stream": False,
            "temperature": 0,
            "kv_transfer_params": {"do_remote_decode": True}})
        assert r1.status_code == 200
        ktp = r1.json()["kv_transfer_params"]
        if mutate_ktp:
            ktp = mutate_ktp(ktp)
        r2 = await c.post(f"http://127.0.0.1:{dec_port}/v1/completions", json={
            "prompt": PROMPT, "max_tokens": 6, "temperature": 0,
            "ignore_eos": True, "kv_transfer_params": ktp})
        assert r2.status_code == 200
        return ktp, r2.json()


@requires_device_transfer
def test_device_path_used_and_matches_monolithic():
    async def body():
        mono = EngineServer(_cfg(18731))
        await mono.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post("http://127.0.0.1:18731/v1/completions",
                                 json={"prompt": PROMPT, "max_tokens": 6,
                                       "temperature": 0, "ignore_eos": True})
                mono_text = r.json()["choices"][0]["text"]
        finally:
            await mono.stop()

        pre, dec = await _pd_pair(18732, 18733)
        try:
            ktp, doc = await _run_pd(18732, 18733)
            # The prefiller advertised the device pull route...
            assert "transfer_address" in ktp and "transfer_uuid" in ktp
            assert ktp["kv_shape"][2] == 16  # block size sanity
            # ...and the decode engine actually pulled device-to-device.
            assert dec.engine.kv_import_device_count == 1
            assert dec.engine.kv_import_host_count == 0
            assert doc["choices"][0]["text"] == mono_text
        finally:
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_host_path_when_transfer_disabled():
    async def body():
        pre, dec = await _pd_pair(18734, 18735, kv_transfer="host")
        try:
            ktp, doc = await _run_pd(18734, 18735)
            assert "transfer_address" not in ktp
            assert dec.engine.kv_import_host_count == 1
            assert dec.engine.kv_import_device_count == 0
            assert len(doc["choices"][0]["text"]) > 0
        finally:
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_device_pull_failure_falls_back_to_http():
    async def body():
        pre, dec = await _pd_pair(18736, 18737)
        try:
            def poison(ktp):
                # Unreachable transfer address: the pull must fail fast and
                # the decode engine degrade to the host-staged HTTP path.
                return {**ktp, "transfer_address": "127.0.0.1:1"}

            ktp, doc = await _run_pd(18736, 18737, mutate_ktp=poison)
            assert dec.engine.kv_import_device_count == 0
            assert dec.engine.kv_import_host_count == 1
            assert len(doc["choices"][0]["text"]) > 0
        finally:
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


@requires_device_transfer
def test_sharded_pull_tp_pair_matches_monolithic():
    """tp-sharded P/D pair (VERDICT r2 missing #6, single-process half):
    the prefiller registers one descriptor per unique page shard
    (kv_shards.py) and the tp decode engine pulls + assembles them under
    its own page sharding — device path, token parity with monolithic."""
    async def body():
        mono = EngineServer(_cfg(18741, tp_size=2))
        await mono.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post("http://127.0.0.1:18741/v1/completions",
                                 json={"prompt": PROMPT, "max_tokens": 6,
                                       "temperature": 0, "ignore_eos": True})
                mono_text = r.json()["choices"][0]["text"]
        finally:
            await mono.stop()

        pre, dec = await _pd_pair(18742, 18743, tp_size=2)
        try:
            ktp, doc = await _run_pd(18742, 18743)
            assert "transfer_shards" in ktp and "kv_mesh" in ktp
            assert ktp["kv_mesh"]["n_procs"] == 1
            assert dec.engine.kv_import_device_count == 1
            assert dec.engine.kv_import_host_count == 0
            assert doc["choices"][0]["text"] == mono_text
        finally:
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


@requires_device_transfer
def test_sharded_pull_pp_pair_matches_monolithic():
    """pp-sharded P/D pair: pages shard the LAYER axis over pp stages
    (pp_serve.PAGE_SPEC); the prefiller stages one descriptor per unique
    page shard and the pp decode engine pulls + scatters under its own
    stage layout — device path, token parity with a monolithic pp engine.
    (Round-5 follow-on to the tp pair: proves the kv_shards staging is
    mesh-shape-agnostic, the precondition for disagg under the host-
    spanning pp ring.)"""
    async def body():
        mono = EngineServer(_cfg(18761, pp_size=2))
        await mono.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post("http://127.0.0.1:18761/v1/completions",
                                 json={"prompt": PROMPT, "max_tokens": 6,
                                       "temperature": 0, "ignore_eos": True})
                mono_text = r.json()["choices"][0]["text"]
        finally:
            await mono.stop()

        pre, dec = await _pd_pair(18762, 18763, pp_size=2)
        try:
            ktp, doc = await _run_pd(18762, 18763)
            assert "transfer_shards" in ktp and "kv_mesh" in ktp
            assert dec.engine.kv_import_device_count == 1
            assert dec.engine.kv_import_host_count == 0
            assert doc["choices"][0]["text"] == mono_text
        finally:
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


@requires_device_transfer
def test_sharded_geometry_mismatch_falls_back_to_host():
    """tp=2 exporter, unsharded importer: geometry mismatch must degrade to
    the host-staged path (numpy resharding), not fail the request."""
    async def body():
        pre = EngineServer(_cfg(18744, role="prefill", tp_size=2))
        dec = EngineServer(_cfg(18745, role="decode"))
        await pre.start()
        await dec.start()
        try:
            ktp, doc = await _run_pd(18744, 18745)
            assert "transfer_shards" in ktp
            assert dec.engine.kv_import_device_count == 0
            assert dec.engine.kv_import_host_count == 1
            assert len(doc["choices"][0]["text"]) > 0
        finally:
            await pre.stop()
            await dec.stop()

    asyncio.run(body())
