"""Session-aware prefill classifier (router/plugins/disagg.py):
verdict matrix, config plumbing, DecisionRecord block, the CacheLedger's
post-hoc judge, the ?profile= decision filter, and the live skip-the-hop
e2e through gateway → sidecar → P/D sim engines.

PPD (arXiv:2603.13358): multi-turn traffic splits into cache-hit prefills
(cheap, decode-adjacent) and cold prefills (expensive, prefill-pool work).
The classifier estimates the chosen decode pod's expected prefix-hit depth
from the CacheLedger's schedule-time signals, discounts it by the pod's
measured KvHitTable signed-error EWMA, and skips the P/D hop when the
confidence-adjusted cold-token count falls under the threshold.
"""

import asyncio

import httpx

from llm_d_inference_scheduler_tpu.router.config.loader import Handle, load_config
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.decisions import (
    DecisionConfig,
    DecisionRecorder,
    record_matches,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
)
from llm_d_inference_scheduler_tpu.router.kvobs import CacheLedger, KvObsConfig
from llm_d_inference_scheduler_tpu.router.plugins.attributes import (
    PREFIX_ATTRIBUTE_KEY,
    PrefixCacheMatchInfo,
)
from llm_d_inference_scheduler_tpu.router.plugins.disagg import (
    DisaggProfileHandler,
    PdClassifierConfig,
)

import llm_d_inference_scheduler_tpu.router.plugins  # noqa: F401  (register)


def _ep(port: int, role: str) -> Endpoint:
    return Endpoint(EndpointMetadata(
        name=f"ep-{port}", address="127.0.0.1", port=port,
        labels={"llm-d.ai/role": role}))


def _req(prompt: str = "hello " * 200, rid: str = "r1") -> InferenceRequest:
    return InferenceRequest(
        request_id=rid, target_model="tiny",
        body=InferenceRequestBody(completions={"prompt": prompt}))


def _handler(cfg: PdClassifierConfig | None,
             datastore: Datastore | None = None) -> DisaggProfileHandler:
    h = DisaggProfileHandler("h")
    h.configure({"pdDecider": {"type": "always-disagg-pd-decider"}},
                Handle(datastore=datastore))
    if cfg is not None:
        h.set_classifier(cfg)
    return h


class TestClassifierVerdicts:
    def test_disabled_returns_none(self):
        h = _handler(PdClassifierConfig(enabled=False))
        assert h._classify(_req(), _ep(9000, "decode"), None) is None
        assert _handler(None)._classify(_req(), _ep(9000, "decode"),
                                        None) is None

    def test_no_reuse_signal_keeps(self):
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.0))
        block = h._classify(_req(), _ep(9000, "decode"), None)
        assert block["verdict"] == "keep"
        assert block["predicted_ratio"] == 0.0
        assert block["predicted_source"] == "none"

    def test_warm_pod_zero_gate_skips(self):
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.0,
                                        cold_token_threshold=64))
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(15, 16, 16))
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "skip"
        assert block["predicted_source"] == "approx"
        # ~300 estimated tokens * (1 - 15/16) < 64
        assert block["expected_cold_tokens"] < 64

    def test_low_confidence_blocks_skip(self):
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.5),
                     datastore=Datastore())
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(15, 16, 16))
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "low_confidence"
        assert block["trust"]["confidence"] == 0.0

    def test_pool_joins_build_confidence(self):
        ds = Datastore()
        # Joins land on OTHER pods (the always-disagg bootstrap shape: a
        # decode pod's actual is confirmed on the prefill pod) — pool-wide
        # confidence must still open the gate.
        for _ in range(8):
            ds.kv_obs.record("127.0.0.1:7000", hit_ratio=0.9,
                             signed_error=0.0)
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.5),
                     datastore=ds)
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(15, 16, 16))
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "skip"
        assert block["trust"]["scope"] == "pool"
        assert block["trust"]["pool_n"] == 8

    def test_signed_error_discount_flips_to_keep(self):
        ds = Datastore()
        # This pod's scorers over-promise badly: predicted − actual EWMA
        # near 1 ⇒ the adjusted ratio collapses and the hop stays.
        for _ in range(8):
            ds.kv_obs.record("127.0.0.1:9000", hit_ratio=0.0,
                             signed_error=0.95)
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.5),
                     datastore=ds)
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(15, 16, 16))
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "keep"
        assert block["trust"]["scope"] == "pod"
        assert block["adjusted_ratio"] < block["predicted_ratio"]

    def test_under_promise_not_inflated(self):
        ds = Datastore()
        for _ in range(8):
            ds.kv_obs.record("127.0.0.1:9000", hit_ratio=0.9,
                             signed_error=-0.5)  # engine finds MORE reuse
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.5),
                     datastore=ds)
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(8, 16, 16))
        block = h._classify(_req(), ep, None)
        # Negative signed error must not raise the ratio above predicted.
        assert block["adjusted_ratio"] == block["predicted_ratio"]

    def test_precise_score_wins_when_higher(self):
        from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
            ProfileRunResult,
        )

        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.0,
                                        cold_token_threshold=64))
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(1, 16, 16))
        res = ProfileRunResult(
            target_endpoints=[ep],
            raw_scores={"precise-prefix-scorer/precise-prefix-scorer":
                        {"127.0.0.1:9000": 0.95}})
        block = h._classify(_req(), ep, res)
        assert block["predicted_source"] == "precise"
        assert block["predicted_ratio"] == 0.95
        assert block["verdict"] == "skip"


class TestPairCostMargin:
    """Measured-pair-cost coupling (ROADMAP item 1's noted extension): the
    cheapest measured KV-pull EWMA into the chosen decode pod scales the
    skip threshold — cheap pull → keep the hop more often, expensive pull
    → skip more eagerly; no measured pair → bit-identical neutrality."""

    def _warm_handler(self, ref_ms: float, ds: Datastore | None = None):
        ds = ds or Datastore()
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.0,
                                        cold_token_threshold=64,
                                        pair_cost_ref_ms=ref_ms),
                     datastore=ds)
        # Borderline pod: expected_cold lands between threshold/2 and
        # threshold, so the margin direction decides the verdict.
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY,
                          PrefixCacheMatchInfo(13, 16, 16))
        return h, ds, ep

    def _expected_cold(self, h, ep) -> float:
        return h._classify(_req(), ep, None)["expected_cold_tokens"]

    def test_cheap_pull_weakens_the_skip(self):
        h, ds, ep = self._warm_handler(25.0)
        cold = self._expected_cold(h, ep)
        assert 32 < cold < 64  # borderline by construction
        # No measured pair: neutral margin → skip at the base threshold.
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "skip"
        assert "pair_cost" not in block
        # A CHEAP measured pull into this decode pod halves the bar: the
        # hop costs little, so the same borderline prefill keeps it.
        ds.transfers.record("127.0.0.1:7000", "127.0.0.1:9000", pull_ms=1.0)
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "keep"
        pc = block["pair_cost"]
        assert pc["min_ewma_pull_ms"] == 1.0
        assert pc["margin"] == 0.5  # clamped floor
        assert pc["effective_threshold"] == 32.0

    def test_expensive_pull_strengthens_the_skip(self):
        h, ds, ep = self._warm_handler(25.0)
        # Push the pod colder so the base threshold would KEEP …
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY,
                          PrefixCacheMatchInfo(10, 16, 16))
        assert h._classify(_req(), ep, None)["verdict"] == "keep"
        # … but an expensive measured pull doubles the bar → skip.
        ds.transfers.record("127.0.0.1:7000", "127.0.0.1:9000",
                            pull_ms=500.0)
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "skip"
        assert block["pair_cost"]["margin"] == 2.0  # clamped ceiling
        assert block["pair_cost"]["effective_threshold"] == 128.0

    def test_cheapest_pair_wins(self):
        h, ds, ep = self._warm_handler(25.0)
        ds.transfers.record("127.0.0.1:7000", "127.0.0.1:9000",
                            pull_ms=100.0)
        ds.transfers.record("127.0.0.1:7001", "127.0.0.1:9000",
                            pull_ms=12.5)
        block = h._classify(_req(), ep, None)
        # min over measured pairs INTO the pod; 12.5/25 → margin 0.5.
        assert block["pair_cost"]["min_ewma_pull_ms"] == 12.5
        assert block["pair_cost"]["margin"] == 0.5
        # Pairs into OTHER decode pods don't count.
        assert ds.transfers.cheapest_pull_ms("127.0.0.1:9999") is None

    def test_coupling_disabled_is_bit_identical(self):
        h, ds, ep = self._warm_handler(0.0)
        ds.transfers.record("127.0.0.1:7000", "127.0.0.1:9000", pull_ms=1.0)
        block = h._classify(_req(), ep, None)
        assert block["verdict"] == "skip"
        assert "pair_cost" not in block

    def test_loader_threads_pair_cost_ref(self):
        cfg_text = TestLoaderPlumbing.CFG.replace(
            "minConfidence: 0.25", "minConfidence: 0.25, pairCostRefMs: 40")
        cfg = load_config(cfg_text, Handle(datastore=Datastore()))
        h = cfg.plugins_by_name["disagg-profile-handler"]
        assert h.classifier_cfg.pair_cost_ref_ms == 40.0


class TestPickProfilesIntegration:
    """The classifier stage inside pick_profiles: skip suppresses the
    prefill profile; keep falls through to the decider; the verdict is
    stamped on the request and the DecisionRecord."""

    def _run(self, cfg: PdClassifierConfig | None, warm: bool = True):
        h = _handler(cfg)
        dec = _ep(9000, "decode")
        if warm:
            dec.attributes.put(PREFIX_ATTRIBUTE_KEY,
                               PrefixCacheMatchInfo(15, 16, 16))
        from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
            ProfileRunResult,
        )

        req = _req()
        req.decision = DecisionRecorder(DecisionConfig()).start("r1", "tiny")
        profiles = {"decode": object(), "prefill": object()}
        results = {"decode": ProfileRunResult(target_endpoints=[dec])}
        to_run = h.pick_profiles(None, req, profiles, results)
        return req, to_run

    def test_skip_suppresses_prefill_profile(self):
        req, to_run = self._run(PdClassifierConfig(enabled=True,
                                                   min_confidence=0.0))
        assert "prefill" not in to_run
        assert req.classifier["verdict"] == "skip"
        assert req.decision.to_dict()["classifier"]["verdict"] == "skip"

    def test_keep_runs_decider(self):
        req, to_run = self._run(PdClassifierConfig(enabled=True,
                                                   min_confidence=0.0),
                                warm=False)
        # always-disagg decider ⇒ the hop runs on a keep verdict.
        assert "prefill" in to_run
        assert req.classifier["verdict"] == "keep"

    def test_disabled_is_bit_identical(self):
        req, to_run = self._run(None)
        assert "prefill" in to_run          # decider path, unchanged
        assert req.classifier is None       # no stamp
        assert "classifier" not in req.decision.to_dict()

    def test_reclassify_updates_in_place(self):
        """A failover re-classification must update the SAME dict the
        DecisionRecord references (the record follows the verdict that
        actually served) — unless the response already judged it."""
        req, _ = self._run(PdClassifierConfig(enabled=True,
                                              min_confidence=0.0))
        first = req.classifier
        assert first["verdict"] == "skip"
        h = _handler(PdClassifierConfig(enabled=True, min_confidence=0.0))
        cold = _ep(9100, "decode")  # fresh pod, no reuse
        from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
            ProfileRunResult,
        )

        h.pick_profiles(None, req, {"decode": object(), "prefill": object()},
                        {"decode": ProfileRunResult(target_endpoints=[cold])})
        assert req.classifier is first          # same dict object
        assert first["verdict"] == "keep"       # updated in place
        # Once judged, history is immutable.
        first["judged"] = {"correct": True}
        h.pick_profiles(None, req, {"decode": object(), "prefill": object()},
                        {"decode": ProfileRunResult(target_endpoints=[cold])})
        assert "judged" in req.classifier


class TestLoaderPlumbing:
    CFG = """
disagg:
  classifier: {enabled: true, coldTokenThreshold: 128, minConfidence: 0.25}
plugins:
  - {type: decode-filter}
  - {type: prefill-filter}
  - {type: queue-scorer}
  - type: disagg-profile-handler
    parameters:
      pdDecider: {type: always-disagg-pd-decider}
schedulingProfiles:
  - name: decode
    plugins: [{pluginRef: decode-filter}, {pluginRef: queue-scorer}]
  - name: prefill
    plugins: [{pluginRef: prefill-filter}, {pluginRef: queue-scorer}]
"""

    def test_loader_applies_classifier_section(self):
        ds = Datastore()
        cfg = load_config(self.CFG, Handle(datastore=ds))
        h = cfg.plugins_by_name["disagg-profile-handler"]
        assert h.classifier_cfg == PdClassifierConfig(
            enabled=True, cold_token_threshold=128, min_confidence=0.25)
        assert h._datastore is ds

    def test_default_is_off(self):
        cfg = load_config(self.CFG.replace("enabled: true", "enabled: false"),
                          Handle(datastore=Datastore()))
        h = cfg.plugins_by_name["disagg-profile-handler"]
        assert h.classifier_cfg.enabled is False
        # And with no disagg section at all, no config object is injected.
        import re

        bare = re.sub(r"disagg:\n(  .*\n)+", "", self.CFG)
        cfg2 = load_config(bare, Handle(datastore=Datastore()))
        assert cfg2.plugins_by_name["disagg-profile-handler"] \
            .classifier_cfg is None


class TestJudge:
    def _joined(self, verdict: str, actual_hit_tokens: int,
                prompt_tokens: int = 1200,
                input_tokens: int = 300, threshold: int = 64):
        ds = Datastore()
        ledger = CacheLedger(KvObsConfig(), datastore=ds)
        ep = _ep(9000, "decode")
        ep.attributes.put(PREFIX_ATTRIBUTE_KEY, PrefixCacheMatchInfo(15, 16, 16))
        from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
            ProfileRunResult,
            SchedulingResult,
        )

        req = _req()
        req.classifier = {"verdict": verdict, "pod": "127.0.0.1:9000",
                          "input_tokens": input_tokens,
                          "threshold": threshold}
        result = SchedulingResult(
            profile_results={"decode": ProfileRunResult(target_endpoints=[ep])},
            primary_profile_name="decode")
        ledger.record_scheduled(req, result)
        ledger.observe_response(
            req, ep, {"x-kv-hit-tokens": str(actual_hit_tokens)},
            {"prompt_tokens": prompt_tokens})
        return req.classifier["judged"], ledger

    def test_skip_correct(self):
        judged, ledger = self._joined("skip", actual_hit_tokens=1150)
        # actual ratio ≈ 0.958 applied to the router-side 300-token
        # estimate → ~12.5 cold tokens < 64 → the skip was right.
        assert judged["should_skip"] is True and judged["correct"] is True
        snap = ledger.snapshot()["classifier"]
        assert snap["counts"]["skip_correct"] == 1
        assert snap["precision"] == 1.0 and snap["recall"] == 1.0

    def test_skip_wrong_counts_fp(self):
        judged, ledger = self._joined("skip", actual_hit_tokens=0)
        assert judged["should_skip"] is False and judged["correct"] is False
        snap = ledger.snapshot()["classifier"]
        assert snap["counts"]["skip_wrong"] == 1
        assert snap["precision"] == 0.0

    def test_keep_missed_skip_counts_fn(self):
        judged, ledger = self._joined("keep", actual_hit_tokens=1150)
        assert judged["should_skip"] is True and judged["correct"] is False
        assert ledger.snapshot()["classifier"]["counts"][
            "keep_missed_skip"] == 1

    def test_keep_necessary_counts_tn(self):
        judged, ledger = self._joined("low_confidence", actual_hit_tokens=0)
        assert judged["should_skip"] is False and judged["correct"] is True
        assert ledger.snapshot()["classifier"]["counts"][
            "keep_necessary"] == 1

    def test_units_scale_through_actual_ratio(self):
        """Engine counts raw tokens (4× the chars/4 router estimate here);
        the judge must apply the actual RATIO to the router-side estimate,
        not compare engine tokens against a router-unit threshold."""
        # 50% actual hit on 1200 engine tokens = 150 cold router-tokens
        # against a 64-token threshold ⇒ should_skip False.
        judged, _ = self._joined("skip", actual_hit_tokens=600)
        assert judged["actual_cold_tokens"] == 150.0
        assert judged["should_skip"] is False

    def test_judge_is_once_per_request(self):
        judged, ledger = self._joined("skip", actual_hit_tokens=1150)
        snap1 = ledger.snapshot()["classifier"]["judged"]
        assert snap1 == 1  # the second observe_response call was a no-op
        # (obs.done short-circuits; and the judged marker guards the block)


class TestFleetMerge:
    def test_merge_kv_sums_classifier_counts(self):
        from llm_d_inference_scheduler_tpu.router.fleet import merge_kv

        def shard_doc(tp, fp, fn, tn):
            return {"enabled": True, "predicted_stamps": 1,
                    "confirmed_joins": 1, "prediction": {"n": 0},
                    "prediction_ratio": {"n": 0}, "pods": {},
                    "classifier": {
                        "judged": tp + fp + fn + tn,
                        "counts": {"skip_correct": tp, "skip_wrong": fp,
                                   "keep_missed_skip": fn,
                                   "keep_necessary": tn}}}

        merged = merge_kv([(0, shard_doc(8, 1, 1, 2)),
                           (1, shard_doc(4, 0, 3, 5))])
        cls = merged["classifier"]
        assert cls["counts"] == {"skip_correct": 12, "skip_wrong": 1,
                                 "keep_missed_skip": 4,
                                 "keep_necessary": 7}
        assert cls["judged"] == 24
        # Recomputed from the summed counts, never averaged.
        assert cls["precision"] == round(12 / 13, 4)
        assert cls["recall"] == 0.75

    def test_merge_kv_without_classifier_sections(self):
        from llm_d_inference_scheduler_tpu.router.fleet import merge_kv

        merged = merge_kv([(0, {"enabled": True, "pods": {}})])
        assert merged["classifier"]["judged"] == 0
        assert "precision" not in merged["classifier"]


def test_example_disagg_config_loads():
    """examples/disagg.yaml (the documented knobs) must load: classifier
    section applied at its kill-switch default, session-affinity-scorer
    wired into the decode profile."""
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples", "disagg.yaml")
    with open(path) as f:
        cfg = load_config(f.read(), Handle(datastore=Datastore()))
    h = cfg.plugins_by_name["disagg-profile-handler"]
    assert h.classifier_cfg is not None
    assert h.classifier_cfg.enabled is False  # kill-switch default
    assert "session-affinity-scorer" in cfg.plugins_by_name


class TestProfileFilter:
    def _doc(self, *, classifier_verdict=None, prefill_picked=False,
             decode_picked=True):
        rounds = [{"reason": "schedule", "candidates_in": 3, "profiles": {}}]
        if decode_picked:
            rounds[0]["profiles"]["decode"] = {"outcome": "picked"}
        if prefill_picked:
            rounds[0]["profiles"]["prefill"] = {"outcome": "picked"}
        doc = {"rounds": rounds, "outcome": {}, "final": {}}
        if classifier_verdict:
            doc["classifier"] = {"verdict": classifier_verdict}
        return doc

    def test_prefill_filter(self):
        assert record_matches(self._doc(prefill_picked=True),
                              profile="prefill")
        assert not record_matches(self._doc(), profile="prefill")

    def test_decode_filter_is_decode_only(self):
        assert record_matches(self._doc(), profile="decode")
        assert not record_matches(self._doc(prefill_picked=True),
                                  profile="decode")

    def test_skip_hop_requires_skip_verdict(self):
        assert record_matches(self._doc(classifier_verdict="skip"),
                              profile="skip-hop")
        assert not record_matches(self._doc(classifier_verdict="keep"),
                                  profile="skip-hop")
        assert not record_matches(self._doc(), profile="skip-hop")

    def test_unknown_value_matches_nothing(self):
        assert not record_matches(self._doc(), profile="bogus")


GW, SC, DEC, PRE = 18990, 18991, 18992, 18993

E2E_CFG = f"""
disagg:
  classifier: {{enabled: true, coldTokenThreshold: 64, minConfidence: 0.0}}
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {SC}, labels: {{llm-d.ai/role: decode}}}}
    - {{address: 127.0.0.1, port: {PRE}, labels: {{llm-d.ai/role: prefill}}}}
plugins:
  - {{type: decode-filter}}
  - {{type: prefill-filter}}
  - {{type: queue-scorer}}
  - {{type: approx-prefix-cache-producer}}
  - {{type: prefix-cache-scorer}}
  - type: disagg-profile-handler
    parameters:
      pdDecider: {{type: always-disagg-pd-decider}}
schedulingProfiles:
  - name: decode
    plugins:
      - {{pluginRef: decode-filter}}
      - {{pluginRef: prefix-cache-scorer, weight: 3}}
      - {{pluginRef: queue-scorer}}
  - name: prefill
    plugins:
      - {{pluginRef: prefill-filter}}
      - {{pluginRef: queue-scorer}}
"""

LONG_PROMPT = "summarise this very important support conversation: " * 8


def _metric_value(text: str, family: str) -> float:
    for line in text.splitlines():
        if line.startswith(family + " "):
            return float(line.split()[-1])
    return 0.0


def test_classifier_skips_hop_live():
    """Live skip-the-hop e2e: cold turn rides the P/D hop (always-disagg
    decider), the warm repeat is classified skip — served by the decode
    pod with NO prefill-pod growth — and the whole decision is explainable:
    classifier block with judged sub-block at /debug/decisions/<id>,
    ?profile=skip-hop finds it, /debug/kv reports per-pod precision, and
    the metric families moved."""
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
    from llm_d_inference_scheduler_tpu.router.sidecar import (
        Sidecar,
        SidecarConfig,
    )

    async def body():
        def sim(port, role):
            return EngineServer(EngineConfig(
                backend="sim", model="tiny", port=port, role=role,
                max_batch=4, max_model_len=2048))

        dec, pre = sim(DEC, "decode"), sim(PRE, "prefill")
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC}"))
        await sc.start()
        gw = build_gateway(E2E_CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                # Delta-based metric asserts: the prometheus registry is
                # process-global and earlier unit tests incremented it.
                m_start = (await c.get(
                    f"http://127.0.0.1:{GW}/metrics")).text
                skips_start = _metric_value(m_start,
                                            "router_pd_hop_skipped_total")

                def pre_prompt_tokens():
                    text = pre.engine.telemetry.render().decode()
                    for line in text.splitlines():
                        if line.startswith("jetstream:prompt_tokens_total "):
                            return float(line.split()[-1])
                    return 0.0

                # Turn 1: cold → keep → the P/D hop runs.
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": LONG_PROMPT,
                                       "max_tokens": 4},
                                 headers={"x-request-id": "clf-cold-1"})
                assert r.status_code == 200
                assert pre_prompt_tokens() > 0
                d = (await c.get(f"http://127.0.0.1:{GW}"
                                 "/debug/decisions/clf-cold-1")).json()
                assert d["classifier"]["verdict"] == "keep"
                assert d["classifier"]["judged"]["correct"] is True

                # Turn 2 (warm repeat): classified skip — decode pod
                # serves it, the prefill pod sees nothing.
                pre_before = pre_prompt_tokens()
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": LONG_PROMPT,
                                       "max_tokens": 4},
                                 headers={"x-request-id": "clf-warm-2"})
                assert r.status_code == 200
                assert r.headers["x-gateway-destination-endpoint-served"] == \
                    f"127.0.0.1:{SC}"
                assert pre_prompt_tokens() == pre_before
                d = (await c.get(f"http://127.0.0.1:{GW}"
                                 "/debug/decisions/clf-warm-2")).json()
                cls = d["classifier"]
                assert cls["verdict"] == "skip"
                # Fully explained: prediction, trust, threshold, verdict,
                # and the engine-confirmed judgement.
                for k in ("predicted_ratio", "adjusted_ratio", "trust",
                          "expected_cold_tokens", "threshold", "judged"):
                    assert k in cls, k
                assert cls["judged"]["correct"] is True
                # The prefill profile never ran on the skip.
                assert all("prefill" not in rnd["profiles"]
                           for rnd in d["rounds"])

                # ?profile= filters: skip-hop finds exactly the skip;
                # prefill finds exactly the hop.
                lst = (await c.get(f"http://127.0.0.1:{GW}"
                                   "/debug/decisions?profile=skip-hop")
                       ).json()["decisions"]
                assert [x["request_id"] for x in lst] == ["clf-warm-2"]
                lst = (await c.get(f"http://127.0.0.1:{GW}"
                                   "/debug/decisions?profile=prefill")
                       ).json()["decisions"]
                assert [x["request_id"] for x in lst] == ["clf-cold-1"]

                # /debug/kv: per-pod precision over the judged verdicts.
                kv = (await c.get(f"http://127.0.0.1:{GW}/debug/kv")).json()
                assert kv["classifier"]["judged"] >= 2
                assert kv["classifier"]["precision"] == 1.0
                pod = kv["pods"][f"127.0.0.1:{SC}"]["classifier"]
                assert pod["counts"]["skip_correct"] >= 1

                # Metric families.
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert 'router_pd_classifier_decisions_total{' \
                    'verdict="skip"}' in m
                assert _metric_value(
                    m, "router_pd_hop_skipped_total") == skips_start + 1
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())


def test_classifier_killswitch_always_disaggs():
    """classifier.enabled: false ⇒ bit-identical always-disagg behavior:
    the warm repeat still rides the hop, no verdicts, no skip metric."""
    from llm_d_inference_scheduler_tpu.engine import EngineConfig
    from llm_d_inference_scheduler_tpu.engine.server import EngineServer
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
    from llm_d_inference_scheduler_tpu.router.sidecar import (
        Sidecar,
        SidecarConfig,
    )

    async def body():
        def sim(port, role):
            return EngineServer(EngineConfig(
                backend="sim", model="tiny", port=port, role=role,
                max_batch=4, max_model_len=2048))

        dec, pre = sim(DEC, "decode"), sim(PRE, "prefill")
        await dec.start()
        await pre.start()
        sc = Sidecar(SidecarConfig(port=SC,
                                   decoder_url=f"http://127.0.0.1:{DEC}"))
        await sc.start()
        gw = build_gateway(E2E_CFG.replace("enabled: true", "enabled: false"),
                           port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                m_start = (await c.get(
                    f"http://127.0.0.1:{GW}/metrics")).text
                skips_start = _metric_value(m_start,
                                            "router_pd_hop_skipped_total")
                for rid in ("ks-1", "ks-2"):
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": LONG_PROMPT,
                              "max_tokens": 4},
                        headers={"x-request-id": rid})
                    assert r.status_code == 200
                    d = (await c.get(f"http://127.0.0.1:{GW}"
                                     f"/debug/decisions/{rid}")).json()
                    assert "classifier" not in d
                    # always-disagg: every turn ran the prefill profile.
                    assert any("prefill" in rnd["profiles"]
                               for rnd in d["rounds"])
                m = (await c.get(f"http://127.0.0.1:{GW}/metrics")).text
                assert _metric_value(
                    m, "router_pd_hop_skipped_total") == skips_start
                kv = (await c.get(f"http://127.0.0.1:{GW}/debug/kv")).json()
                assert kv["classifier"]["judged"] == 0
        finally:
            await gw.stop()
            await sc.stop()
            await pre.stop()
            await dec.stop()

    asyncio.run(body())
