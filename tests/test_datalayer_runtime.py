"""Data layer against a live engine: collectors scrape real /metrics."""

import asyncio

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.datalayer.extractor import CoreMetricsExtractor
from llm_d_inference_scheduler_tpu.router.datalayer.metrics_source import MetricsDataSource
from llm_d_inference_scheduler_tpu.router.datalayer.runtime import DataLayerRuntime
from llm_d_inference_scheduler_tpu.router.framework.datalayer import EndpointMetadata


def test_collector_scrapes_live_engine():
    async def body():
        server = EngineServer(EngineConfig(backend="sim", model="tiny", port=18331,
                                           max_batch=2))
        await server.start()
        ds = Datastore()
        runtime = DataLayerRuntime(ds, poll_interval=0.02)
        src = MetricsDataSource("metrics-data-source")
        src.add_extractor(CoreMetricsExtractor("core-metrics-extractor"))
        runtime.register_source(src)
        await runtime.start()
        try:
            ep = ds.endpoint_add_or_update(EndpointMetadata(
                name="e1", address="127.0.0.1", port=18331))
            # Generate load so the gauges move.
            import httpx
            async with httpx.AsyncClient(timeout=30) as c:
                tasks = [c.post("http://127.0.0.1:18331/v1/completions",
                                json={"prompt": "x" * 50, "max_tokens": 20})
                         for _ in range(4)]
                done = asyncio.gather(*tasks)
                seen_running = False
                for _ in range(60):
                    await asyncio.sleep(0.02)
                    if ep.metrics.running_requests_size > 0:
                        seen_running = True
                        break
                await done
            assert seen_running, "collector never observed running requests"
            assert ep.metrics.fresh
            assert ep.metrics.cache_block_size == 16
            # Endpoint removal stops its collector.
            ds.endpoint_delete("127.0.0.1:18331")
            assert not runtime._collectors
        finally:
            await runtime.stop()
            await server.stop()

    asyncio.run(body())
