"""Concurrent scheduling engine (ISSUE 5): copy-on-write pool snapshots,
off-loop scheduler workers, loop trampolines for undeclared plugins,
batched flow-control dispatch, scrape-parse offload, and the
verify-threadsafe lint hook."""

from __future__ import annotations

import asyncio
import concurrent.futures
import pathlib
import sys
import threading
import time

import pytest

from llm_d_inference_scheduler_tpu.router.datalayer.datastore import Datastore
from llm_d_inference_scheduler_tpu.router.datalayer.runtime import (
    DataLayerRuntime,
    _Collector,
)
from llm_d_inference_scheduler_tpu.router.flowcontrol import (
    FlowControlConfig,
    FlowController,
)
from llm_d_inference_scheduler_tpu.router.flowcontrol.types import (
    FlowControlRequest,
    FlowKey,
    QueueOutcome,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.plugin import TypedName
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
)
from llm_d_inference_scheduler_tpu.router.plugins.pickers import MaxScorePicker
from llm_d_inference_scheduler_tpu.router.plugins.profile_handlers import (
    SingleProfileHandler,
)
from llm_d_inference_scheduler_tpu.router.plugins.scorers import (
    KvCacheUtilizationScorer,
    QueueScorer,
)
from llm_d_inference_scheduler_tpu.router.schedpool import (
    SchedulerPool,
    SchedulingConfig,
    trampoline_scheduler,
)
from llm_d_inference_scheduler_tpu.router.scheduling.scheduler import (
    Scheduler,
    SchedulerProfile,
    WeightedScorer,
)


def _datastore(n: int = 8) -> Datastore:
    ds = Datastore()
    for i in range(n):
        ep = ds.endpoint_add_or_update(EndpointMetadata(
            name=f"ep{i}", address=f"10.0.0.{i}", port=8000))
        # Distinct queue depths -> distinct scores -> deterministic picks.
        ep.metrics.waiting_queue_size = i
        ep.metrics.kv_cache_usage_percent = 0.05 * i
        ep.metrics.update_time = time.monotonic()
    return ds


def _scheduler() -> Scheduler:
    profile = SchedulerProfile(
        "default", [],
        [WeightedScorer(QueueScorer("queue-scorer"), 2.0),
         WeightedScorer(KvCacheUtilizationScorer("kv-scorer"), 2.0)],
        MaxScorePicker("max-score-picker"))
    return Scheduler({"default": profile}, SingleProfileHandler())


def _request(i: int) -> InferenceRequest:
    return InferenceRequest(
        request_id=f"sp-{i}", target_model="tiny",
        body=InferenceRequestBody(completions={"prompt": f"p{i}"}))


# ---- snapshot semantics --------------------------------------------------


def test_snapshot_is_cached_until_dirty():
    ds = _datastore()
    s1 = ds.snapshot()
    assert ds.snapshot() is s1  # copy-on-write: same epoch until dirty
    # Scrape landings are SOFT dirty: within the refresh floor the epoch is
    # intentionally reused (bounds rebuild CPU under steady scraping and
    # keeps one epoch per co-dispatched batch).
    ds.mark_snapshot_dirty()
    assert ds.snapshot() is s1
    # Once the floor passes, the next snapshot() publishes a fresh epoch.
    ds.SNAPSHOT_MIN_REFRESH_S = 0.0
    s2 = ds.snapshot()
    assert s2 is not s1 and s2.epoch == s1.epoch + 1


def test_snapshot_isolates_metrics_and_attributes():
    ds = _datastore()
    ds.SNAPSHOT_MIN_REFRESH_S = 0.0  # scrape-dirty rebuilds immediately
    snap = ds.snapshot()
    views = snap.view()
    # Live scrape write after the snapshot: the view keeps the old value.
    ds.endpoint_get("10.0.0.3:8000").metrics.waiting_queue_size = 999
    assert views[3].metrics.waiting_queue_size == 3
    # Per-request attribute overlays are private to each view() call.
    views[0].attributes.put("attr", {"x": 1})
    assert snap.view()[0].attributes.get("attr") is None
    # Base attributes captured at build fall through to overlay readers.
    ds.mark_snapshot_dirty()
    ds.endpoint_get("10.0.0.0:8000").attributes.put("base-key", "v")
    assert ds.snapshot().view()[0].attributes.get("base-key") == "v"


def test_endpoint_churn_bumps_epoch():
    ds = _datastore(3)
    e1 = ds.snapshot().epoch
    ds.endpoint_delete("10.0.0.2:8000")
    assert ds.snapshot().epoch == e1 + 1
    assert len(ds.snapshot()) == 2
    ds.resync([EndpointMetadata(name="n", address="10.1.0.1", port=9000)])
    snap = ds.snapshot()
    assert snap.epoch > e1 + 1
    assert [v.metadata.address_port for v in snap.view()] == ["10.1.0.1:9000"]


def test_delete_mid_cycle_schedules_old_epoch_next_batch_sees_new():
    """An endpoint deleted while an off-loop cycle is in flight: the cycle
    finishes against its (old-epoch) views without KeyError; the next
    dispatch batch observes the new epoch without the endpoint."""
    ds = _datastore(4)

    class SlowScorer:
        THREAD_SAFE = True

        def typed_name(self):
            return TypedName("slow-scorer", "slow")

        def score(self, ctx, state, request, endpoints):
            time.sleep(0.05)  # hold the cycle open across the deletion
            return {ep.metadata.address_port: 0.0 for ep in endpoints}

    profile = SchedulerProfile(
        "default", [],
        [WeightedScorer(SlowScorer(), 1.0),
         WeightedScorer(QueueScorer("queue-scorer"), 2.0)],
        MaxScorePicker("max-score-picker"))
    sched = Scheduler({"default": profile}, SingleProfileHandler())
    pool = SchedulerPool(sched, SchedulingConfig(workers=2))

    async def run():
        old_epoch = ds.snapshot().epoch
        views = ds.snapshot().view()
        task = asyncio.ensure_future(pool.schedule(None, _request(0), views))
        await asyncio.sleep(0.01)      # cycle is now inside the slow scorer
        ds.endpoint_delete("10.0.0.0:8000")  # the would-be winner
        result = await task            # finishes against the old epoch
        picked = result.primary().target_endpoints[0]
        assert picked.metadata.address_port == "10.0.0.0:8000"
        assert picked.snapshot_epoch == old_epoch
        # The next batch resolves a fresh epoch without the dead endpoint.
        fresh = ds.snapshot()
        assert fresh.epoch > old_epoch
        assert "10.0.0.0:8000" not in [
            v.metadata.address_port for v in fresh.view()]
        return True

    try:
        assert asyncio.run(run())
    finally:
        pool.shutdown()


# ---- kill-switch parity and trampolines ---------------------------------


def test_workers0_and_workersN_produce_identical_picks():
    """`scheduling: {workers: 0}` (inline kill-switch) and workers: N must
    pick identically for a fixed scrape state."""
    ds = _datastore(8)

    def picks(workers: int) -> list[str]:
        pool = SchedulerPool(_scheduler(), SchedulingConfig(workers=workers))

        async def run():
            out = []
            for i in range(16):
                cands = (ds.snapshot().view() if pool.offloaded
                         else ds.endpoint_list())
                res = await pool.schedule(None, _request(i), cands)
                out.append(res.primary().target_endpoints[0]
                           .metadata.address_port)
            return out

        try:
            return asyncio.run(run())
        finally:
            pool.shutdown()

    inline, offloaded = picks(0), picks(4)
    assert inline == offloaded
    assert inline[0] == "10.0.0.0:8000"  # lowest queue + kv wins


def test_threadsafe_plugin_runs_on_worker_undeclared_on_loop():
    threads: dict[str, int] = {}

    class SafeScorer:
        THREAD_SAFE = True

        def typed_name(self):
            return TypedName("safe-scorer", "safe")

        def score(self, ctx, state, request, endpoints):
            threads["safe"] = threading.get_ident()
            return {ep.metadata.address_port: 0.1 for ep in endpoints}

    class UndeclaredScorer:
        def typed_name(self):
            return TypedName("undeclared-scorer", "undeclared")

        def score(self, ctx, state, request, endpoints):
            threads["undeclared"] = threading.get_ident()
            return {ep.metadata.address_port: 0.2 for ep in endpoints}

    ds = _datastore(3)
    profile = SchedulerProfile(
        "default", [],
        [WeightedScorer(SafeScorer(), 1.0),
         WeightedScorer(UndeclaredScorer(), 1.0)],
        MaxScorePicker("max-score-picker"))
    sched = Scheduler({"default": profile}, SingleProfileHandler())
    pool = SchedulerPool(sched, SchedulingConfig(workers=1))

    async def run():
        await pool.schedule(None, _request(0), ds.snapshot().view())
        return threading.get_ident()

    try:
        loop_thread = asyncio.run(run())
    finally:
        pool.shutdown()
    # The undeclared scorer was trampolined back onto the loop thread; the
    # audited one ran off-loop on a worker.
    assert threads["undeclared"] == loop_thread
    assert threads["safe"] != loop_thread


def test_trampoline_scheduler_noop_when_all_safe():
    sched = _scheduler()
    loop = asyncio.new_event_loop()
    try:
        assert trampoline_scheduler(sched, loop) is sched
    finally:
        loop.close()


def test_unsafe_decider_trampolines_whole_handler():
    """Deciders run INSIDE the handler's pick_profiles, so a decider that
    declares THREAD_SAFE = False must drag the whole handler back onto the
    loop — the handler's own True declaration is not enough."""
    from llm_d_inference_scheduler_tpu.router.plugins.disagg import (
        DisaggProfileHandler,
    )

    class UnsafeDecider:
        THREAD_SAFE = False

        def typed_name(self):
            return TypedName("unsafe-decider", "unsafe")

        def disaggregate(self, ctx, request, decode_endpoint):
            return True

    handler = DisaggProfileHandler()
    handler.pd_decider = UnsafeDecider()
    profile = SchedulerProfile(
        "decode", [],
        [WeightedScorer(QueueScorer("queue-scorer"), 1.0)],
        MaxScorePicker("max-score-picker"))
    sched = Scheduler({"decode": profile}, handler)
    loop = asyncio.new_event_loop()
    try:
        wrapped = trampoline_scheduler(sched, loop)
        assert wrapped is not sched
        assert wrapped.profile_handler.wrapped is handler

        # Swap in a safe decider: nothing to wrap, scheduler passes through.
        handler.pd_decider.THREAD_SAFE = True
        assert trampoline_scheduler(sched, loop) is sched
    finally:
        loop.close()


def test_switch_interval_refcounted_across_pools():
    """The GIL switch interval is process-global: the first offloaded pool
    to shut down must not revert it while a second pool still runs."""
    prev = sys.getswitchinterval()
    assert prev > 0.001  # interpreter default (5 ms) — nothing else holds it
    a = SchedulerPool(_scheduler(), SchedulingConfig(workers=1))
    b = SchedulerPool(_scheduler(), SchedulingConfig(workers=1))
    try:
        assert sys.getswitchinterval() == pytest.approx(0.001)
        a.shutdown()
        assert sys.getswitchinterval() == pytest.approx(0.001)
    finally:
        a.shutdown()
        b.shutdown()
    assert sys.getswitchinterval() == pytest.approx(prev)


# ---- batched flow-control dispatch --------------------------------------


def test_batched_dispatch_preserves_fairness_and_batches():
    """dispatch_batch=4: one wake drains multiple flows in fairness order,
    and everything queued is dispatched."""
    cfg = FlowControlConfig(shards=1, dispatch_batch=4)
    order: list[str] = []

    async def run():
        fc = FlowController(cfg, saturation_fn=lambda: 0.0)
        await fc.start()
        try:
            async def submit(i, flow):
                item = FlowControlRequest(
                    request_id=f"b{i}", flow_key=FlowKey(flow, 0),
                    size_bytes=1)
                out = await fc.enqueue_and_wait(item)
                order.append(item.request_id)
                return out
            outs = await asyncio.gather(*[
                submit(i, f"flow-{i % 2}") for i in range(8)])
            assert all(o == QueueOutcome.DISPATCHED for o in outs)
            assert len(order) == 8
        finally:
            await fc.stop()

    asyncio.run(run())


def test_dispatch_batch_default_is_one():
    assert FlowControlConfig.from_spec({}).dispatch_batch == 1
    assert FlowControlConfig.from_spec({"dispatchBatch": 6}).dispatch_batch == 6


# ---- scrape-parse offload + collector jitter -----------------------------


class _FakeSource:
    def __init__(self):
        self.extracted_on: list[int] = []
        outer = self

        class _Ex:
            def typed_name(self):
                return TypedName("fake-extractor", "fake")

            def extract(self, raw, endpoint):
                outer.extracted_on.append(threading.get_ident())
                endpoint.metrics.waiting_queue_size = int(raw)

        self._ex = _Ex()

    def typed_name(self):
        return TypedName("fake-source", "fake")

    async def collect(self, endpoint):
        return "7"

    def extractors(self):
        return [self._ex]

    def add_extractor(self, ex):
        pass


def test_collector_extracts_on_offload_executor_and_marks_snapshot():
    ds = Datastore()
    ds.SNAPSHOT_MIN_REFRESH_S = 0.0  # scrape-dirty rebuilds immediately
    rt = DataLayerRuntime(ds, poll_interval=0.01)
    src = _FakeSource()
    rt.register_source(src)
    pool = concurrent.futures.ThreadPoolExecutor(1)
    rt.offload = pool

    async def run():
        ep = ds.endpoint_add_or_update(EndpointMetadata(
            name="e", address="10.2.0.1", port=8000))
        before = ds.snapshot().epoch
        await rt.start()
        for _ in range(100):
            if src.extracted_on:
                break
            await asyncio.sleep(0.01)
        await rt.stop()
        assert src.extracted_on, "extractor never ran"
        # Parse CPU left the loop...
        assert src.extracted_on[0] != threading.get_ident()
        # ...the metrics landed...
        assert ep.metrics.waiting_queue_size == 7
        # ...and the scrape published a fresh snapshot epoch.
        assert ds.snapshot().epoch > before
        assert ds.snapshot().view()[0].metrics.waiting_queue_size == 7

    try:
        asyncio.run(run())
    finally:
        pool.shutdown(wait=False)


def test_collector_first_collect_is_immediate_despite_jitter():
    """Anti-thundering-herd jitter must not delay the FIRST scrape (pool
    readiness rides on it) — the phase offset applies after it."""
    ds = Datastore()
    ep = Endpoint(EndpointMetadata(name="e", address="10.3.0.1", port=8000))
    src = _FakeSource()

    async def run():
        c = _Collector(ep, [src], interval=5.0, jitter_s=4.0,
                       on_scrape=ds.mark_snapshot_dirty)
        c.start()
        t0 = time.monotonic()
        while not src.extracted_on and time.monotonic() - t0 < 1.0:
            await asyncio.sleep(0.005)
        c.stop()
        assert src.extracted_on, "first collect delayed by jitter"
        assert time.monotonic() - t0 < 1.0

    asyncio.run(run())


# ---- gateway / config wiring --------------------------------------------


def test_gateway_wires_scheduling_config():
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    gw = build_gateway("""
featureGates: {flowControl: true}
scheduling: {workers: 2, maxBatch: 5}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 19999}
""")
    try:
        assert gw.sched_pool.offloaded
        assert gw.sched_pool.cfg.workers == 2
        assert gw.sched_pool.cfg.max_batch == 5
        assert gw.director.sched_pool is gw.sched_pool
        # Batched dispatch follows scheduling.maxBatch when offloaded.
        assert gw.flow_controller.cfg.dispatch_batch == 5
        # The scrape-parse offload shares the pool's workers.
        assert gw.dl_runtime.offload is gw.sched_pool.executor
    finally:
        gw.sched_pool.shutdown()


def test_gateway_default_is_inline_killswitch():
    from llm_d_inference_scheduler_tpu.router.gateway import build_gateway

    gw = build_gateway("""
featureGates: {flowControl: true}
pool:
  endpoints:
    - {address: 127.0.0.1, port: 19998}
""")
    assert not gw.sched_pool.offloaded
    assert gw.sched_pool.executor is None
    assert gw.flow_controller.cfg.dispatch_batch == 1  # one-pop-one-yield
    assert gw.dl_runtime.offload is None


# ---- lint hook -----------------------------------------------------------


def test_verify_threadsafe_lint_clean():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]
                           / "scripts"))
    import verify_threadsafe

    assert verify_threadsafe.check() == []
