"""OTLP/HTTP exporter: wire-format round-trip against a local collector.

The emitted bytes are decoded with the vllmgrpc parser's independent
protobuf reader (different code path from the writer), asserting genuine
OTLP proto layout: resource_spans → scope_spans → spans with ids, names,
times, attributes, status.
"""

from __future__ import annotations

import http.server
import threading
import time

from llm_d_inference_scheduler_tpu.router.handlers.vllmgrpc import _fields
from llm_d_inference_scheduler_tpu.router.otlp import OtlpHttpExporter
from llm_d_inference_scheduler_tpu.router.tracing import Tracer


class _Collector(http.server.BaseHTTPRequestHandler):
    received: list[tuple[str, bytes]] = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        _Collector.received.append((self.path, body))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *a):
        pass


def _decode_spans(payload: bytes) -> list[dict]:
    spans = []
    for f1, w1, rs in _fields(payload):
        assert f1 == 1 and w1 == 2          # resource_spans
        resource = scope = None
        for f2, w2, v2 in _fields(rs):
            if f2 == 1:
                resource = v2
            elif f2 == 2:                   # scope_spans
                for f3, w3, sp in _fields(v2):
                    if f3 != 2:
                        continue
                    span = {"attributes": {}}
                    for f4, w4, v4 in _fields(sp):
                        if f4 == 1:
                            span["trace_id"] = v4.hex()
                        elif f4 == 2:
                            span["span_id"] = v4.hex()
                        elif f4 == 4:
                            span["parent_id"] = v4.hex()
                        elif f4 == 5:
                            span["name"] = v4.decode()
                        elif f4 == 7:
                            span["start"] = int.from_bytes(v4, "little")
                        elif f4 == 8:
                            span["end"] = int.from_bytes(v4, "little")
                        elif f4 == 9:
                            key = val = None
                            for f5, w5, v5 in _fields(v4):
                                if f5 == 1:
                                    key = v5.decode()
                                elif f5 == 2:
                                    for f6, w6, v6 in _fields(v5):
                                        if f6 == 1:
                                            val = v6.decode()
                                        elif f6 == 3:
                                            val = int(v6)
                            span["attributes"][key] = val
                        elif f4 == 15:
                            for f5, w5, v5 in _fields(v4):
                                if f5 == 3:
                                    span["status_code"] = int(v5)
                    spans.append(span)
        assert resource is not None
    return spans


def test_otlp_export_roundtrip():
    _Collector.received.clear()
    srv = http.server.HTTPServer(("127.0.0.1", 0), _Collector)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        exp = OtlpHttpExporter(f"http://127.0.0.1:{port}",
                               service_name="router-test",
                               flush_interval=30.0)
        tracer = Tracer(enabled=True, sample_ratio=1.0)
        tracer.add_exporter(exp)
        with tracer.span("gateway.request", model="m1") as root:
            root.set_attribute("tokens", 42)
            with tracer.span("gateway.request_orchestration"):
                pass
        exp.flush()

        assert len(_Collector.received) == 1
        path, body = _Collector.received[0]
        assert path == "/v1/traces"
        spans = _decode_spans(body)
        assert {s["name"] for s in spans} == {
            "gateway.request", "gateway.request_orchestration"}
        root_s = next(s for s in spans if s["name"] == "gateway.request")
        child = next(s for s in spans
                     if s["name"] == "gateway.request_orchestration")
        assert child["parent_id"] == root_s["span_id"]
        assert child["trace_id"] == root_s["trace_id"]
        assert root_s["attributes"]["model"] == "m1"
        assert root_s["attributes"]["tokens"] == 42
        assert root_s["status_code"] == 1      # STATUS_CODE_OK
        assert root_s["end"] >= root_s["start"] > 0
        # Per-span wall-clock anchors: the child started at/after its parent,
        # not at flush time (spans carry their own start_unix_ns).
        assert child["start"] >= root_s["start"]
        assert abs(root_s["start"] - time.time_ns()) < 60e9
        exp.shutdown()
    finally:
        srv.shutdown()


def test_otlp_env_activation(monkeypatch):
    monkeypatch.setenv("OTEL_EXPORTER_OTLP_ENDPOINT", "http://127.0.0.1:9")
    monkeypatch.setenv("TRACING_ENABLED", "1")
    tr = Tracer(enabled=True, sample_ratio=1.0)
    # Exporter registered; a failing endpoint must not break span finish.
    assert len(tr._exporters) == 1
    with tr.span("s"):
        pass
    assert tr.snapshot()[0]["name"] == "s"


def test_otlp_span_events_exported_both_encodings():
    """Span events (the decision flight recorder's phase summaries) must
    survive BOTH OTLP encodings — silently dropping them from the sinks
    would make the recorder look like it never fired in a collector."""
    from llm_d_inference_scheduler_tpu.router.otlp import (
        encode_span,
        span_to_otlp_json,
    )

    span = {"trace_id": "ab" * 16, "span_id": "cd" * 8, "name": "s",
            "duration_ms": 1.0, "start_unix_ns": 1000,
            "attributes": {"a": 1},
            "events": [{"name": "decision.admission", "time_unix_ns": 1500,
                        "attributes": {"outcome": "dispatched", "n": 2}}]}
    wire = encode_span(span, 0)
    assert b"decision.admission" in wire and b"dispatched" in wire

    doc = span_to_otlp_json(span, "svc")
    ev = doc["resourceSpans"][0]["scopeSpans"][0]["spans"][0]["events"][0]
    assert ev["name"] == "decision.admission"
    assert ev["timeUnixNano"] == "1500"
    assert {"key": "outcome", "value": {"stringValue": "dispatched"}} in \
        ev["attributes"]
