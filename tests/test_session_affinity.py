"""session-affinity-scorer e2e: x-session-token issuance and round-trip
through the live gateway, sticky picks across a multi-turn conversation,
and token invalidation when the pod leaves the pool.

The scorer (router/plugins/scorers.py SessionAffinityScorer) stamps an
encoded pod identity after scheduling; the gateway echoes it to the client
as the x-session-token response header; a client presenting it on a later
request scores its previous endpoint 1.0. The sticky session path is what
keeps multi-turn conversations landing where their KV cache lives — the
prefill classifier's skip-the-hop verdict (ISSUE 11) rides on it.
"""

import asyncio
import base64

import httpx

from llm_d_inference_scheduler_tpu.engine import EngineConfig
from llm_d_inference_scheduler_tpu.engine.server import EngineServer
from llm_d_inference_scheduler_tpu.router.gateway import build_gateway
from llm_d_inference_scheduler_tpu.router.plugins.scorers import (
    SessionAffinityScorer,
)

GW, E0, E1 = 18970, 18971, 18972

CFG = f"""
pool:
  endpoints:
    - {{address: 127.0.0.1, port: {E0}}}
    - {{address: 127.0.0.1, port: {E1}}}
plugins:
  - {{type: session-affinity-scorer}}
  - {{type: queue-scorer}}
schedulingProfiles:
  - name: default
    plugins:
      - {{pluginRef: session-affinity-scorer, weight: 4}}
      - {{pluginRef: queue-scorer, weight: 1}}
"""


def _decode_token(token: str) -> str:
    return base64.standard_b64decode(token.encode()).decode()


def test_session_token_roundtrip_and_sticky_conversation():
    """Issuance: the first response carries x-session-token naming the
    served pod. Round-trip: presenting it keeps a 3-turn conversation on
    that pod even when the prompt grows every turn."""

    async def body():
        engines = [EngineServer(EngineConfig(backend="sim", model="tiny",
                                             port=p)) for p in (E0, E1)]
        for e in engines:
            await e.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                history = "user: hello, I have a billing question."
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": history,
                                       "max_tokens": 2})
                assert r.status_code == 200
                token = r.headers.get("x-session-token")
                assert token, "first response must issue x-session-token"
                served = r.headers["x-gateway-destination-endpoint-served"]
                # The token IS the encoded pod identity (reference
                # session_affinity.go base64 contract).
                assert _decode_token(token) == served

                for turn in range(2, 5):
                    history += f"\nassistant: ok.\nuser: follow-up {turn}."
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": history,
                              "max_tokens": 2},
                        headers={"x-session-token": token})
                    assert r.status_code == 200
                    assert r.headers[
                        "x-gateway-destination-endpoint-served"] == served
                    # Re-issued every turn (still the same pod).
                    token = r.headers["x-session-token"]
                    assert _decode_token(token) == served
        finally:
            await gw.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())


def test_session_token_invalidated_when_pod_leaves():
    """A token naming a pod that left the pool scores nothing: the request
    is placed fresh on a live pod and the response issues a NEW token for
    it (clients recover by simply following the header)."""

    async def body():
        engines = [EngineServer(EngineConfig(backend="sim", model="tiny",
                                             port=p)) for p in (E0, E1)]
        for e in engines:
            await e.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "hi",
                                       "max_tokens": 2})
                token = r.headers["x-session-token"]
                served = _decode_token(token)

                # The pod leaves the pool (scrape loss / scale-down).
                gw.datastore.endpoint_delete(served)
                assert len(gw.datastore.endpoint_list()) == 1
                survivor = gw.datastore.endpoint_list()[0] \
                    .metadata.address_port

                r = await c.post(f"http://127.0.0.1:{GW}/v1/completions",
                                 json={"model": "tiny", "prompt": "hi again",
                                       "max_tokens": 2},
                                 headers={"x-session-token": token})
                assert r.status_code == 200
                assert r.headers[
                    "x-gateway-destination-endpoint-served"] == survivor
                new_token = r.headers["x-session-token"]
                assert _decode_token(new_token) == survivor
                assert new_token != token
        finally:
            await gw.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())


def test_garbage_token_scores_nothing():
    """Tokens that don't decode (or decode to nonsense) neither crash nor
    pin placement — fresh placement, fresh token."""

    async def body():
        engines = [EngineServer(EngineConfig(backend="sim", model="tiny",
                                             port=p)) for p in (E0, E1)]
        for e in engines:
            await e.start()
        gw = build_gateway(CFG, port=GW, poll_interval=0.02)
        await gw.start()
        try:
            async with httpx.AsyncClient(timeout=60) as c:
                for bad in ("!!!not-base64!!!",
                            base64.standard_b64encode(
                                b"10.0.0.9:1").decode()):
                    r = await c.post(
                        f"http://127.0.0.1:{GW}/v1/completions",
                        json={"model": "tiny", "prompt": "x",
                              "max_tokens": 2},
                        headers={"x-session-token": bad})
                    assert r.status_code == 200
                    fresh = r.headers["x-session-token"]
                    assert _decode_token(fresh) in (
                        f"127.0.0.1:{E0}", f"127.0.0.1:{E1}")
        finally:
            await gw.stop()
            for e in engines:
                await e.stop()

    asyncio.run(body())


def test_scorer_unit_scores():
    """Unit matrix: matching endpoint 1.0, everyone else 0.0; absent or
    undecodable header scores all 0.0 (fresh placement)."""
    from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
        Endpoint,
        EndpointMetadata,
    )
    from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
        InferenceRequest,
        InferenceRequestBody,
    )

    s = SessionAffinityScorer("s")
    eps = [Endpoint(EndpointMetadata(name=f"e{p}", address="10.0.0.1",
                                     port=p)) for p in (1, 2)]

    def req(headers):
        return InferenceRequest(request_id="r", target_model="m",
                                body=InferenceRequestBody(
                                    completions={"prompt": "x"}),
                                headers=headers)

    tok = SessionAffinityScorer._encode("10.0.0.1:2")
    assert s.score(None, None, req({"x-session-token": tok}), eps) == \
        {"10.0.0.1:1": 0.0, "10.0.0.1:2": 1.0}
    assert s.score(None, None, req({}), eps) == \
        {"10.0.0.1:1": 0.0, "10.0.0.1:2": 0.0}
    assert s.score(None, None, req({"x-session-token": "###"}), eps) == \
        {"10.0.0.1:1": 0.0, "10.0.0.1:2": 0.0}
