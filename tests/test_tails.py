"""Tail-latency attribution observatory (router/tails.py): waterfall
assembly on every terminal shape, the decode residual clamp, body-vs-tail
cohort split + dominant-stage attribution, exemplar bounds, the
kill-switch, fleet fan-in weighting, the ?stage= list filter, and the
engine-side first-pop-wins queue-wait measurement."""

import time
from types import SimpleNamespace

from llm_d_inference_scheduler_tpu.router.decisions import (
    DecisionRecord,
    record_matches,
)
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    Objectives,
)
from llm_d_inference_scheduler_tpu.router.tails import (
    STAGES,
    TailsConfig,
    TailsObservatory,
    merge_tails,
)
from llm_d_inference_scheduler_tpu.router.timeline import (
    TimelineConfig,
    TimelineSampler,
)


def _req(rid="r1", model="m", priority=0) -> InferenceRequest:
    return InferenceRequest(
        request_id=rid, target_model=model,
        body=InferenceRequestBody(completions={"prompt": "x"}),
        headers={}, objectives=Objectives(priority=priority))


def _ep(port=9001) -> Endpoint:
    return Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1",
                                     port=port, labels={}))


def _obs(t0, ttft_ms=None, last_ms=None, streamed=False, queue_ms=0.0):
    """Duck-typed slo.py RequestObservation — only the fields tails reads."""
    first = t0 + ttft_ms / 1e3 if ttft_ms is not None else None
    last = t0 + last_ms / 1e3 if last_ms is not None else None
    return SimpleNamespace(first_token_at=first, last_token_at=last,
                           streamed=streamed, abort_reason=None,
                           queue_ms=queue_ms)


def _close_served(obs_ry, rid, ttft_ms, stages=None, pair=None,
                  model="m", priority=0, endpoint=None):
    """Open + stamp + complete one served (verdict ok) request."""
    req = _req(rid, model=model, priority=priority)
    req.decision = DecisionRecord(rid, model)
    t0 = time.monotonic()
    wf = obs_ry.start(req, t0)
    for name, v in (stages or {}).items():
        setattr(wf, f"{name}_ms", v)
    wf.pair = pair
    req.outcome = _obs(t0, ttft_ms=ttft_ms)
    obs_ry.complete(req, status=200, endpoint=endpoint or _ep())
    return req


# ---- config / kill-switch ----------------------------------------------


def test_from_spec_defaults_and_clamps():
    cfg = TailsConfig.from_spec(None)
    assert cfg.enabled and cfg.capacity == 512
    assert cfg.tail_quantile == 0.95 and cfg.exemplars == 8
    cfg = TailsConfig.from_spec({"capacity": 2, "tailQuantile": 2.0,
                                 "exemplars": -1})
    assert cfg.capacity == 16          # floor
    assert cfg.tail_quantile == 0.999  # clamp
    assert cfg.exemplars == 0


def test_killswitch_is_inert():
    obs_ry = TailsObservatory(TailsConfig.from_spec({"enabled": False}))
    req = _req()
    assert obs_ry.start(req, time.monotonic()) is None
    # No waterfall attribute ever rides the request (bit-identical).
    assert getattr(req, "waterfall", None) is None
    obs_ry.complete(req, status=200)  # no-op, not a crash
    snap = obs_ry.snapshot()
    assert snap["enabled"] is False
    assert snap["closed"] == 0 and snap["cohorts"] == {}


# ---- waterfall assembly -------------------------------------------------


def test_waterfall_block_and_decode_residual():
    obs_ry = TailsObservatory()
    req = _close_served(obs_ry, "w1", ttft_ms=100.0,
                        stages={"queue": 10.0, "sched": 5.0,
                                "prefill": 30.0, "kv_transfer": 20.0},
                        pair="127.0.0.1:1→127.0.0.1:2")
    block = req.decision.waterfall
    assert block["verdict"] == "ok"
    assert block["cohort"] == "m|b0|unary"
    assert abs(block["ttft_ms"] - 100.0) < 1.0
    st = block["stages"]
    assert st["queue"] == 10.0 and st["prefill"] == 30.0
    assert st["kv_transfer"] == 20.0
    # Residual: TTFT minus every accounted stage.
    assert abs(st["decode"] - 35.0) < 1.0
    assert block["pair"] == "127.0.0.1:1→127.0.0.1:2"
    # Sums: stages (incl. residual) reassemble the TTFT.
    assert abs(sum(st.values()) - block["ttft_ms"]) < 1.0
    # Summary echo names the waterfall.
    assert "ttft=" in req.decision.summary_line()


def test_residual_never_negative_under_clock_skew():
    obs_ry = TailsObservatory()
    # Engine-stamped stages exceed the observed TTFT (cross-host clock
    # skew): the residual clamps at zero instead of minting negative time.
    req = _close_served(obs_ry, "w2", ttft_ms=50.0,
                        stages={"prefill": 200.0})
    st = req.decision.waterfall["stages"]
    assert "decode" not in st  # clamped to 0 → not emitted
    assert all(v >= 0 for v in st.values())


def test_streamed_shape_gets_stream_stage():
    obs_ry = TailsObservatory()
    req = _req("w3")
    req.decision = DecisionRecord("w3", "m")
    t0 = time.monotonic()
    obs_ry.start(req, t0)
    req.outcome = _obs(t0, ttft_ms=40.0, last_ms=90.0, streamed=True)
    obs_ry.complete(req, status=200, endpoint=_ep())
    block = req.decision.waterfall
    assert block["cohort"] == "m|b0|stream"
    assert abs(block["stages"]["stream"] - 50.0) < 1.0


def test_queue_backfills_from_slo_observation():
    obs_ry = TailsObservatory()
    req = _req("w4")
    t0 = time.monotonic()
    obs_ry.start(req, t0)
    req.outcome = _obs(t0, ttft_ms=30.0, queue_ms=12.0)
    req.decision = DecisionRecord("w4", "m")
    obs_ry.complete(req, status=200, endpoint=_ep())
    assert req.decision.waterfall["stages"]["queue"] == 12.0


# ---- terminal shapes ----------------------------------------------------


def test_error_shed_abort_verdicts_skip_cohorts():
    obs_ry = TailsObservatory()
    # Error status.
    req = _req("e1")
    req.decision = DecisionRecord("e1", "m")
    obs_ry.start(req, time.monotonic())
    obs_ry.complete(req, status=500)
    assert req.decision.waterfall["verdict"] == "error"
    # Shed.
    req = _req("e2")
    req.decision = DecisionRecord("e2", "m")
    obs_ry.start(req, time.monotonic())
    obs_ry.complete(req, status=429, reason="shed under saturation",
                    shed=True)
    assert req.decision.waterfall["verdict"] == "shed"
    # Mid-stream abort (status 200 but the observation says aborted).
    req = _req("e3")
    req.decision = DecisionRecord("e3", "m")
    t0 = time.monotonic()
    obs_ry.start(req, t0)
    o = _obs(t0, ttft_ms=10.0, streamed=True)
    o.abort_reason = "client-disconnect"
    req.outcome = o
    obs_ry.complete(req, status=200)
    assert req.decision.waterfall["verdict"] == "error"
    # All three closed, none fed a cohort ring (served-only).
    snap = obs_ry.snapshot()
    assert snap["closed"] == 3 and snap["cohorts"] == {}


def test_complete_is_first_call_wins():
    obs_ry = TailsObservatory()
    req = _close_served(obs_ry, "d1", ttft_ms=20.0)
    obs_ry.complete(req, status=500)  # duplicate close must be a no-op
    assert obs_ry.closed_total == 1
    assert req.decision.waterfall["verdict"] == "ok"


def test_shed_rung_culprit_read_from_decision_record():
    obs_ry = TailsObservatory()
    req = _req("s1")
    rec = DecisionRecord("s1", "m")
    rec.record_shed({"action": "drop-context", "reason": "overload"})
    req.decision = rec
    t0 = time.monotonic()
    obs_ry.start(req, t0)
    req.outcome = _obs(t0, ttft_ms=15.0)
    obs_ry.complete(req, status=200, endpoint=_ep())
    assert rec.waterfall["rung"] == "drop-context"


# ---- cohort split + attribution -----------------------------------------


def _skewed_observatory(n_body=96, n_tail=4, exemplars=8):
    # Tail fraction stays under (1 - tailQuantile) so the rolling p95
    # threshold sits inside the body band, not on the slow value.
    obs_ry = TailsObservatory(TailsConfig(capacity=256,
                                          exemplars=exemplars))
    for i in range(n_body):
        _close_served(obs_ry, f"b{i}", ttft_ms=50.0,
                      stages={"queue": 2.0, "prefill": 10.0,
                              "kv_transfer": 5.0})
    for i in range(n_tail):
        _close_served(obs_ry, f"t{i}", ttft_ms=260.0,
                      stages={"queue": 2.0, "prefill": 10.0,
                              "kv_transfer": 215.0},
                      pair="127.0.0.1:9100→127.0.0.1:9001",
                      endpoint=_ep(9001))
    return obs_ry


def test_cohort_split_and_dominant_stage_attribution():
    obs_ry = _skewed_observatory()
    snap = obs_ry.snapshot()
    cohort = snap["cohorts"]["m|b0|unary"]
    assert cohort["window_n"] == 100
    assert cohort["body_n"] + cohort["tail_n"] == 100
    assert cohort["tail_n"] >= 1
    # The tail cohort's excess time is overwhelmingly the injected stage.
    attr = cohort["attribution"]
    assert attr["dominant"] == "kv_transfer"
    assert attr["dominant_share"] >= 0.6
    assert "kv_transfer" in attr["statement"]
    # Culprit drill-down names the skewed transfer pair.
    assert attr["culprits"]["pair"]["value"] == \
        "127.0.0.1:9100→127.0.0.1:9001"
    assert attr["culprits"]["endpoint"]["value"] == "127.0.0.1:9001"
    # Online classification fed the flat counters + the metric family.
    assert obs_ry.tail_total > 0
    assert obs_ry.dominant_total.get("kv_transfer", 0) > 0
    # Body cohort is unattributed: its stages sit at their own means.
    assert cohort["stages"]["kv_transfer"]["body_mean_ms"] < 10


def test_tail_classified_records_page_via_stage_filter():
    obs_ry = _skewed_observatory()
    ex = obs_ry.snapshot()["cohorts"]["m|b0|unary"]["exemplars"]
    assert ex, "tail exemplars expected"
    # Exemplar rows carry the drill-down identity.
    assert all(e["dominant"] == "kv_transfer" for e in ex)
    assert all("request_id" in e and e["ttft_ms"] > 0 for e in ex)


def test_exemplar_ring_is_bounded():
    obs_ry = _skewed_observatory(n_body=60, n_tail=40, exemplars=4)
    ex = obs_ry.snapshot()["cohorts"]["m|b0|unary"]["exemplars"]
    assert len(ex) <= 4


def test_cohort_table_is_lru_capped():
    obs_ry = TailsObservatory()
    for i in range(TailsObservatory.MAX_COHORTS + 10):
        _close_served(obs_ry, f"c{i}", ttft_ms=10.0, model=f"m{i}")
    assert len(obs_ry.snapshot()["cohorts"]) == TailsObservatory.MAX_COHORTS


# ---- decisions ?stage= filter -------------------------------------------


def test_record_matches_stage_filter():
    doc = {"waterfall": {"dominant": "kv_transfer", "tail": True}}
    assert record_matches(doc, stage="kv_transfer")
    assert not record_matches(doc, stage="decode")
    # Records without a tail verdict (or any waterfall) match nothing.
    assert not record_matches({"waterfall": {"stages": {}}}, stage="decode")
    assert not record_matches({}, stage="decode")


# ---- timeline row -------------------------------------------------------


def test_timeline_tick_embeds_tails_deltas():
    obs_ry = _skewed_observatory()
    sampler = TimelineSampler(TimelineConfig.from_spec({"tickS": 1.0}),
                              tails=obs_ry)
    row = sampler.tick(wall=1000.0)["tails"]
    assert row["closed"] == obs_ry.closed_total
    assert row["tail"] == obs_ry.tail_total
    assert row["dominant"].get("kv_transfer", 0) > 0
    # Deltas, not totals: a quiet tick reads zero.
    row = sampler.tick(wall=1001.0)["tails"]
    assert row == {"closed": 0, "tail": 0}


# ---- fleet fan-in -------------------------------------------------------


def test_merge_tails_weights_by_n_and_annotates_shards():
    heavy = _skewed_observatory()
    light = TailsObservatory()
    for i in range(30):
        _close_served(light, f"l{i}", ttft_ms=20.0,
                      stages={"prefill": 8.0})
    merged = merge_tails([(0, heavy.snapshot()), (1, light.snapshot())])
    assert merged["shards"] == 2 and merged["enabled"]
    assert merged["closed"] == heavy.closed_total + light.closed_total
    cohort = merged["cohorts"]["m|b0|unary"]
    assert cohort["window_n"] == 130
    # Digest-merged stage quantiles carry the combined population.
    assert cohort["stages"]["prefill"]["n"] == 130
    assert cohort["ttft_ms"]["n"] == 130
    assert cohort["ttft_ms"]["p99_ms"] > cohort["ttft_ms"]["p50_ms"]
    # Attribution comes from the (only) shard with tail excess; its
    # culprits speak for the merged cohort, tagged with the shard.
    attr = cohort["attribution"]
    assert attr["dominant"] == "kv_transfer"
    assert attr["culprit_shard"] == 0
    assert attr["culprits"]["pair"]["value"] == \
        "127.0.0.1:9100→127.0.0.1:9001"
    # Exemplars are shard-annotated and bounded.
    ex = cohort["exemplars"]
    assert ex and len(ex) <= 8
    assert all(e["shard"] == 0 for e in ex)


def test_merge_tails_empty_and_disabled_shards():
    merged = merge_tails([])
    assert merged["shards"] == 0 and merged["cohorts"] == {}
    off = TailsObservatory(TailsConfig(enabled=False))
    merged = merge_tails([(0, off.snapshot())])
    assert merged["enabled"] is False and merged["closed"] == 0


# ---- engine queue-wait measurement --------------------------------------


def test_engine_queue_wait_is_first_pop_wins_and_bounded():
    from llm_d_inference_scheduler_tpu.engine.core import TpuEngine

    stub = SimpleNamespace(_queue_submit={}, queue_waits={},
                           _queue_wait_order=__import__("collections").deque())
    stub._queue_submit["r1"] = time.monotonic() - 0.05
    TpuEngine._record_queue_wait(stub, "r1")
    first = stub.queue_waits["r1"]
    assert first >= 50.0
    # A KV-fetch re-insert pops again: the stamp is consumed, so the wait
    # is NOT re-measured (first-pop-wins keeps it disjoint from the
    # transfer stage).
    TpuEngine._record_queue_wait(stub, "r1")
    assert stub.queue_waits["r1"] == first
    # Bounded ring: 512 entries max.
    for i in range(600):
        stub._queue_submit[f"x{i}"] = time.monotonic()
        TpuEngine._record_queue_wait(stub, f"x{i}")
    assert len(stub.queue_waits) <= 512
