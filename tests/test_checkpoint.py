"""Orbax checkpoint save/restore roundtrip for engine params."""

import tempfile

import jax
import numpy as np

from llm_d_inference_scheduler_tpu.engine.checkpoint import load_params, save_params
from llm_d_inference_scheduler_tpu.models import TINY, llama


def test_checkpoint_roundtrip():
    params = llama.init_params(TINY, jax.random.key(42))
    path = tempfile.mkdtemp() + "/ckpt"
    save_params(path, params)
    restored = load_params(path, TINY)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
