"""SLO & goodput ledger: target resolution, verdicts on every terminal
shape, predictor calibration rollup, the per-pair KV-transfer EWMA table,
and the verify-slo terminal-path check."""

import time

from llm_d_inference_scheduler_tpu.router.datalayer.transfers import (
    TransferTable,
)
from llm_d_inference_scheduler_tpu.router.decisions import DecisionRecord
from llm_d_inference_scheduler_tpu.router.framework.datalayer import (
    Endpoint,
    EndpointMetadata,
)
from llm_d_inference_scheduler_tpu.router.framework.scheduling import (
    InferenceRequest,
    InferenceRequestBody,
    Objectives,
)
from llm_d_inference_scheduler_tpu.router.slo import (
    SloConfig,
    SloLedger,
    H_SLO_TPOT,
    H_SLO_TTFT,
)


def _req(rid="r1", model="m", priority=0, headers=None) -> InferenceRequest:
    return InferenceRequest(
        request_id=rid, target_model=model,
        body=InferenceRequestBody(completions={"prompt": "x"}),
        headers=headers or {}, objectives=Objectives(priority=priority))


def _ep(port=9001, role=None) -> Endpoint:
    labels = {"llm-d.ai/role": role} if role else {}
    return Endpoint(EndpointMetadata(name=f"e{port}", address="127.0.0.1",
                                     port=port, labels=labels))


def _ledger(**spec) -> SloLedger:
    return SloLedger(SloConfig.from_spec(spec))


# ---- config / targets ---------------------------------------------------


def test_targets_headers_beat_model_defaults_beat_global():
    led = _ledger(defaultTtftMs=500, defaultTpotMs=20,
                  perModel={"m": {"ttftMs": 300, "tpotMs": 10}})
    # Headers win.
    assert led.resolve_targets("m", {H_SLO_TTFT: "100", H_SLO_TPOT: "5"}) \
        == (100, 5)
    # Per-model defaults fill absent headers.
    assert led.resolve_targets("m", {}) == (300, 10)
    # Global defaults for unknown models.
    assert led.resolve_targets("other", {}) == (500, 20)
    # Garbage header falls through to config.
    assert led.resolve_targets("m", {H_SLO_TTFT: "nan-ish?"}) == (300, 10)


def test_killswitch_returns_none_observation():
    led = _ledger(enabled=False)
    req = _req()
    assert led.start(req, time.monotonic()) is None
    assert req.outcome is None
    led.complete(req, status=200)  # must be a no-op, not a crash
    assert led.snapshot()["totals"]["requests"] == 0


# ---- verdicts -----------------------------------------------------------


def test_streamed_request_meets_slo():
    led = _ledger()
    req = _req(headers={H_SLO_TTFT: "1000", H_SLO_TPOT: "1000"})
    t0 = time.monotonic()
    obs = led.start(req, t0)
    obs.first_token(t0 + 0.010)
    obs.last_token_at = t0 + 0.020
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 6})
    snap = led.snapshot()
    assert snap["totals"] == {**snap["totals"], "requests": 1, "slo_met": 1,
                              "goodput_tokens": 6, "output_tokens": 6}
    assert snap["totals"]["attainment"] == 1.0


def test_ttft_miss_records_reason_and_drops_goodput():
    led = _ledger()
    req = _req(headers={H_SLO_TTFT: "5"})
    t0 = time.monotonic() - 1.0  # opened 1s ago
    obs = led.start(req, t0)
    obs.t_start = t0
    obs.first_token(t0 + 0.5)  # 500ms TTFT >> 5ms SLO
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 8})
    snap = led.snapshot()
    assert snap["totals"]["slo_met"] == 0
    assert snap["totals"]["output_tokens"] == 8
    assert snap["totals"]["goodput_tokens"] == 0
    assert any(k.startswith("ttft") for k in snap["miss_reasons"])


def test_non_streaming_uses_e2e_as_ttft_and_whole_response_tpot():
    led = _ledger()
    req = _req(headers={H_SLO_TTFT: "60000", H_SLO_TPOT: "60000"})
    rec = DecisionRecord(req.request_id, "m")
    req.decision = rec
    led.start(req, time.monotonic() - 0.2)  # 200ms e2e, no stream events
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 10})
    out = rec.outcome
    assert out["slo_met"] is True and out["streamed"] is False
    # e2e-as-TTFT ≈ 200ms; whole-response TPOT = e2e / tokens.
    assert 150 < out["actual"]["ttft_ms"] < 2000
    assert abs(out["actual"]["tpot_ms"] - out["actual"]["ttft_ms"] / 10) < 0.01


def test_error_and_abort_are_slo_met_false_with_reason():
    led = _ledger()
    # Explicit error reason (shed / retry-exhausted / deadline shapes).
    req = _req(rid="err")
    led.start(req, time.monotonic())
    led.complete(req, status=429, reason="shed under saturation")
    # Mid-stream abort.
    req2 = _req(rid="abort")
    rec = DecisionRecord("abort", "m")
    req2.decision = rec
    obs = led.start(req2, time.monotonic())
    obs.first_token(time.monotonic())
    obs.abort_reason = "client-disconnect"
    led.complete(req2, status=200, endpoint=_ep())
    snap = led.snapshot()
    assert snap["totals"]["requests"] == 2 and snap["totals"]["slo_met"] == 0
    assert snap["miss_reasons"].get("shed") == 1
    assert snap["miss_reasons"].get("client-disconnect") == 1
    assert rec.outcome["slo_met"] is False
    assert rec.outcome["reason"] == "client-disconnect"


def test_complete_is_idempotent_first_wins():
    led = _ledger()
    req = _req()
    led.start(req, time.monotonic())
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 3})
    led.complete(req, status=502, reason="late duplicate")
    snap = led.snapshot()
    assert snap["totals"]["requests"] == 1
    assert snap["totals"]["slo_met"] == 1


# ---- predictor calibration ---------------------------------------------


def test_predictor_error_rollup_signed_and_mae():
    led = _ledger()
    for rid, predicted, actual_s in (("a", 100.0, 0.150), ("b", 100.0, 0.050)):
        req = _req(rid=rid)
        t0 = time.monotonic() - actual_s
        obs = led.start(req, t0)
        obs.t_start = t0
        obs.predicted_ttft_ms = predicted
        obs.first_token(t0 + actual_s)
        led.complete(req, status=200, endpoint=_ep(role="decode"),
                     usage={"completion_tokens": 1})
    ttft = led.snapshot()["totals"]["predictor"]["ttft"]
    assert ttft["n"] == 2
    # errors: +50ms and -50ms → MAE ≈ 50, signed mean ≈ 0.
    assert 45 < ttft["mae_ms"] < 55
    assert abs(ttft["mean_signed_ms"]) < 10


def test_predictor_ttft_calibration_subtracts_queue_time():
    # The TTFT ridge is dispatch-relative; the client-observed TTFT also
    # contains the flow-control queue wait. Calibration must compare like
    # with like or under load the MAE reports queue time, not model error.
    led = _ledger()
    req = _req()
    t0 = time.monotonic() - 0.150
    obs = led.start(req, t0)
    obs.t_start = t0
    obs.predicted_ttft_ms = 100.0
    obs.queue_ms = 50.0
    obs.first_token(t0 + 0.150)  # client-observed TTFT ≈ 150ms
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 1})
    ttft = led.snapshot()["totals"]["predictor"]["ttft"]
    # dispatch-relative actual ≈ 100ms → error ≈ 0, not 50.
    assert ttft["mae_ms"] < 10


def test_non_streamed_tpot_judges_slo_but_skips_calibration():
    # The TPOT ridge trains only on streamed inter-token cadence; the
    # non-streamed whole-response average (queue+prefill folded in) still
    # drives the SLO verdict but must not feed kind=tpot calibration.
    led = _ledger()
    req = _req(headers={H_SLO_TPOT: "0.001"})
    t0 = time.monotonic() - 0.100
    obs = led.start(req, t0)
    obs.t_start = t0
    obs.predicted_tpot_ms = 4.0
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 10})
    snap = led.snapshot()
    assert snap["totals"]["slo_met"] == 0          # verdict still judged
    assert snap["totals"]["predictor"]["tpot"]["n"] == 0  # no calibration


def test_transfer_header_guard_rejects_nonfinite():
    # A malformed x-kv-transfer-ms must not seed NaN into the per-pair
    # EWMAs (0.8*NaN + 0.2*x stays NaN forever) or the histogram sums —
    # shared guard for the gateway landing and the sidecar relay.
    from llm_d_inference_scheduler_tpu.router.slo import finite_float_or_none
    assert finite_float_or_none("nan") is None
    assert finite_float_or_none("inf") is None
    assert finite_float_or_none("3.5") == 3.5
    assert finite_float_or_none("") is None
    assert finite_float_or_none(None) is None


def test_token_bearing_chunk_classification():
    # Framing chunks (keep-alives, blank heartbeats, [DONE]) must not
    # advance the TPOT clock, but a token event split across reads —
    # arriving with the previous event's trailing separator — must.
    from llm_d_inference_scheduler_tpu.router.gateway import _token_bearing
    assert _token_bearing(b'data: {"choices": []}\n\n')
    assert _token_bearing(b'\ndata: {"choices": []}\n\n')   # split separator
    assert _token_bearing(b'\r\n\r\ndata: {"x": 1}\n\n')
    assert not _token_bearing(b": keep-alive\n\n")
    assert not _token_bearing(b"\n\n")
    assert not _token_bearing(b"\r\n")
    assert not _token_bearing(b"data: [DONE]\n\n")
    assert not _token_bearing(b"\n\ndata: [DONE]\n\n")


def test_nonfinite_slo_headers_fall_back_to_defaults():
    led = _ledger(defaultTtftMs=500)
    for bad in ("nan", "inf", "-inf"):
        req = _req(rid=f"r-{bad}", headers={H_SLO_TTFT: bad})
        obs = led.start(req, time.monotonic())
        assert obs.slo_ttft_ms == 500.0


def test_model_rewrite_relabels_tokens_and_redoes_per_model_defaults():
    # The director's weighted rewrite lands AFTER the ledger opens; token
    # counters and perModel defaults must follow the serving name.
    led = _ledger(perModel={"served-v2": {"tpotMs": 7}})
    req = _req(model="client-name")
    t0 = time.monotonic() - 0.010
    obs = led.start(req, t0)
    obs.t_start = t0
    req.target_model = "served-v2"  # director rewrite mid-flight
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 3})
    assert obs.model == "served-v2"
    assert obs.slo_tpot_ms == 7.0


def test_band_reread_at_completion_after_director_classifies():
    # The director resolves the x-objective header AFTER the ledger opens;
    # the band must reflect the classified priority, not the open-time 0.
    led = _ledger()
    req = _req()
    led.start(req, time.monotonic())
    req.objectives.priority = -1  # director classifies mid-flight
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 1})
    assert set(led.snapshot()["bands"]) == {"-1"}


def test_candidate_walk_failover_drops_stale_prediction():
    # Pre-stream failover walks ranked candidates without re-running
    # PreRequest: rank-1's prediction must not calibrate against rank-2's
    # serving latency.
    led = _ledger()
    req = _req()
    t0 = time.monotonic() - 0.100
    obs = led.start(req, t0)
    obs.t_start = t0
    obs.endpoint = "127.0.0.1:9001"      # PreRequest stamped rank-1
    obs.role = "decode"
    obs.predicted_ttft_ms = 5.0
    obs.first_token(t0 + 0.100)
    led.complete(req, status=200, endpoint=_ep(9002),  # rank-2 served
                 usage={"completion_tokens": 1})
    snap = led.snapshot()
    assert snap["totals"]["predictor"]["ttft"]["n"] == 0
    assert "127.0.0.1:9002" in snap["endpoints"]


def test_band_and_endpoint_rollup():
    led = _ledger()
    for rid, prio, port in (("a", 0, 9001), ("b", -1, 9002)):
        req = _req(rid=rid, priority=prio)
        led.start(req, time.monotonic())
        led.complete(req, status=200, endpoint=_ep(port),
                     usage={"completion_tokens": 2})
    snap = led.snapshot()
    assert set(snap["bands"]) == {"0", "-1"}
    assert set(snap["endpoints"]) == {"127.0.0.1:9001", "127.0.0.1:9002"}
    assert snap["endpoints"]["127.0.0.1:9001"]["attainment"] == 1.0


def test_endpoint_rollup_lru_bound_under_pod_churn():
    # Rescheduled pods arrive under fresh ip:ports forever; the per-endpoint
    # table (and its attainment gauge children) must stay bounded.
    led = _ledger()
    for i in range(SloLedger.MAX_ENDPOINTS + 10):
        req = _req(rid=f"r{i}")
        led.start(req, time.monotonic())
        led.complete(req, status=200, endpoint=_ep(10000 + i),
                     usage={"completion_tokens": 1})
    eps = led.snapshot()["endpoints"]
    assert len(eps) == SloLedger.MAX_ENDPOINTS
    assert "127.0.0.1:10000" not in eps          # oldest evicted
    assert f"127.0.0.1:{10000 + SloLedger.MAX_ENDPOINTS + 9}" in eps
    # Totals keep the full history even though the per-endpoint rows rotate.
    assert led.snapshot()["totals"]["requests"] == SloLedger.MAX_ENDPOINTS + 10


# ---- inter-arrival capture ---------------------------------------------


def test_on_chunk_gap_buckets_and_max():
    led = _ledger()
    req = _req()
    obs = led.start(req, time.monotonic())
    obs.first_token(time.monotonic())
    obs.last_token_at = time.monotonic() - 0.020  # 20ms gap → third bucket
    obs.on_chunk()
    obs.last_token_at = time.monotonic() - 0.300  # 300ms gap → overflow
    obs.on_chunk()
    assert obs.gap_buckets[2] == 1
    assert obs.gap_buckets[4] == 1
    assert obs.gap_max_ms >= 300
    assert obs.token_events == 3
    # The outcome block renders the mean inter-arrival gap beside max.
    req.decision = rec = DecisionRecord(req.request_id, "m")
    led.complete(req, status=200, endpoint=_ep(),
                 usage={"completion_tokens": 3})
    mean = rec.outcome["actual"]["gap_mean_ms"]
    assert 150 <= mean <= obs.gap_max_ms


# ---- transfer table -----------------------------------------------------


def test_transfer_table_ewma_and_snapshot():
    t = TransferTable()
    t.record("p:1", "d:1", pull_ms=10.0, nbytes=1000, prefill_ms=30.0)
    t.record("p:1", "d:1", pull_ms=20.0, nbytes=2000, prefill_ms=50.0)
    s = t.pair("p:1", "d:1")
    assert s.pulls == 2 and s.bytes_total == 3000
    # EWMA(0.2): 10 → 0.8*10 + 0.2*20 = 12.
    assert abs(s.ewma_pull_ms - 12.0) < 1e-9
    snap = t.snapshot()["pairs"]
    assert snap[0]["prefill"] == "p:1" and snap[0]["decode"] == "d:1"
    assert "ewma_mb_per_s" in snap[0]


def test_transfer_table_lru_bound():
    t = TransferTable()
    t.MAX_PAIRS = 4
    for i in range(8):
        t.record(f"p:{i}", "d:1", pull_ms=1.0)
    assert len(t) == 4
    assert t.pair("p:0", "d:1") is None
    assert t.pair("p:7", "d:1") is not None


def test_partial_rows_prefill_only():
    # Streamed disagg responses carry no engine pull stats — the pair row
    # still lands with the prefill-leg duration.
    t = TransferTable()
    t.record("p:1", "d:1", prefill_ms=42.0)
    s = t.pair("p:1", "d:1")
    assert s.ewma_pull_ms is None and s.ewma_prefill_ms == 42.0
    assert "ewma_pull_ms" not in s.render()


# ---- terminal-path verification hook ------------------------------------


def test_verify_slo_terminal_paths_clean():
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "scripts"))
    import verify_slo

    assert verify_slo.check() == []
