"""Off-loop scheduling: worker threads run ``Scheduler.schedule`` cycles so
routing-decision CPU stops head-of-line-blocking token streaming.

Every scheduling cycle used to execute synchronously on the gateway's
single asyncio event loop, interleaved with every live SSE token relay:
one 2 ms cycle (128-endpoint pool, benchmarks/SCHED_HOTPATH.json) stalled
every in-flight stream by 2 ms, and concurrent arrivals serialized.
``SchedulerPool`` moves the cycle into a small thread pool over the
copy-on-write pool snapshot (router/snapshot.py):

- config ``scheduling: {workers, maxBatch}``; ``workers: 0`` (the default)
  is the kill-switch — today's inline path, bit-identical behavior;
- the cycle's shared state is thread-safe by audit, not assumption:
  xxhash memoization (router/hashmemo.py) and the batched
  ``KvBlockIndex.match_prefix`` walk hold their own locks, and every
  in-tree filter/scorer/picker declares ``THREAD_SAFE`` (audited —
  ``scripts/verify_threadsafe.py`` lints the registry). Plugins that do
  NOT declare ``THREAD_SAFE = True`` are transparently trampolined back
  onto the event loop (correct, just not off-loop) so third-party plugins
  can't corrupt state;
- workers keep the GIL while scoring (Python threads don't parallelize
  the arithmetic — offload buys loop *responsiveness*, not cycle
  throughput), so the pool drops the interpreter switch interval to 1 ms
  once: a CPU-bound worker then yields the GIL to the loop within ~1 ms
  instead of the 5 ms default, bounding the residual stall.

``bench.py --sched-offload`` measures the event-loop stall (p50/p99
heartbeat lag) and streamed-token inter-arrival gap with offload on vs
off → benchmarks/SCHED_OFFLOAD.json.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import dataclasses
import logging
import sys
import threading
import time
from typing import Any

from .metrics import LOOP_LAG_SECONDS, SCHED_OFFLOAD_QUEUE_SECONDS
from .scheduling.scheduler import Scheduler, SchedulerProfile, WeightedScorer

log = logging.getLogger("router.schedpool")

# GIL switch interval while scheduler workers churn: a worker holding the
# GIL for the default 5 ms would re-introduce most of the stall the offload
# removes. 1 ms bounds the loop's wait without measurable throughput cost
# at router scale (the cycles are ~2 ms total CPU).
WORKER_SWITCH_INTERVAL_S = 0.001

# The switch interval is PROCESS-global, so pools must refcount it: with two
# offloaded pools alive (an in-process multi-gateway test, a prefill+decode
# router pair), the first shutdown() must not revert the second pool's 1 ms
# responsiveness bound back to the 5 ms default.
_switch_lock = threading.Lock()
_switch_holders = 0
_switch_prev: float | None = None


def _switch_interval_acquire() -> None:
    global _switch_holders, _switch_prev
    with _switch_lock:
        _switch_holders += 1
        if _switch_holders == 1 and sys.getswitchinterval() > WORKER_SWITCH_INTERVAL_S:
            # Never raise an operator's already-lower setting.
            _switch_prev = sys.getswitchinterval()
            sys.setswitchinterval(WORKER_SWITCH_INTERVAL_S)


def _switch_interval_release() -> None:
    global _switch_holders, _switch_prev
    with _switch_lock:
        _switch_holders -= 1
        if _switch_holders == 0 and _switch_prev is not None:
            # Restore the interval we lowered (but leave it alone if someone
            # else changed it since).
            if sys.getswitchinterval() == WORKER_SWITCH_INTERVAL_S:
                sys.setswitchinterval(_switch_prev)
            _switch_prev = None


@dataclasses.dataclass
class SchedulingConfig:
    """The YAML ``scheduling:`` section. ``workers: 0`` = inline (today's
    path); ``maxBatch`` bounds how many flow-control items one shard wake
    dispatches into the pool (they share one snapshot epoch)."""

    workers: int = 0
    max_batch: int = 8
    # Columnar scheduling (router/snapshot.py PoolColumns): when True the
    # director hands the scheduler an EndpointBatch and plugins with batch
    # kernels run vectorized; scalar-only plugins fall back transparently
    # through the scheduler's auto-adapter. `vectorized: false` is the
    # kill-switch back to the pure scalar cycle.
    vectorized: bool = True

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "SchedulingConfig":
        spec = spec or {}
        return cls(workers=max(0, int(spec.get("workers", 0))),
                   max_batch=max(1, int(spec.get("maxBatch", 8))),
                   vectorized=bool(spec.get("vectorized", True)))


def _is_threadsafe(plugin: Any) -> bool:
    return getattr(plugin, "THREAD_SAFE", False) is True


def _handler_threadsafe(handler: Any) -> bool:
    """A profile handler is only as safe as the PD/encode deciders it
    delegates to: ``disaggregate()`` runs INSIDE ``pick_profiles`` (not at
    a call site the pool can wrap individually), so a decider declaring
    ``THREAD_SAFE = False`` drags the whole handler back onto the loop."""
    if not _is_threadsafe(handler):
        return False
    try:
        members = list(vars(handler).values())
    except TypeError:  # __slots__ handler: no instance dict to scan
        members = []
    return all(_is_threadsafe(d) for d in members
               if d is not None and hasattr(d, "disaggregate"))


class _LoopTrampoline:
    """Wraps a plugin that did not declare ``THREAD_SAFE = True``: calls
    from scheduler worker threads hop back onto the event loop (the
    plugin's single-writer world is preserved; the worker blocks on the
    result). On-loop calls — inline cycles, or the loop not running (unit
    tests driving the scheduler directly) — go straight through."""

    __slots__ = ("_plugin", "_loop")

    def __init__(self, plugin: Any, loop: asyncio.AbstractEventLoop):
        self._plugin = plugin
        self._loop = loop

    def typed_name(self):
        return self._plugin.typed_name()

    @property
    def wrapped(self) -> Any:
        return self._plugin

    def _call(self, fn, *args):
        loop = self._loop
        if loop is None or not loop.is_running():
            return fn(*args)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            return fn(*args)
        cf: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                cf.set_result(fn(*args))
            except BaseException as e:  # relayed to the waiting worker
                cf.set_exception(e)

        loop.call_soon_threadsafe(run)
        # Poll instead of blocking forever: if the loop stops before our
        # callback drains (gateway shutdown mid-cycle), the result never
        # arrives and an unbounded wait would wedge the worker thread —
        # and, through concurrent.futures' atexit join, the whole process.
        while True:
            try:
                return cf.result(timeout=1.0)
            except concurrent.futures.TimeoutError:
                if not loop.is_running():
                    raise RuntimeError(
                        "event loop stopped while waiting for trampolined "
                        f"plugin call to {self._plugin!r}") from None


class _FilterTrampoline(_LoopTrampoline):
    def filter(self, ctx, state, request, endpoints):
        return self._call(self._plugin.filter, ctx, state, request, endpoints)


class _ScorerTrampoline(_LoopTrampoline):
    def score(self, ctx, state, request, endpoints):
        return self._call(self._plugin.score, ctx, state, request, endpoints)


class _PickerTrampoline(_LoopTrampoline):
    def pick(self, ctx, state, request, scored):
        return self._call(self._plugin.pick, ctx, state, request, scored)


class _HandlerTrampoline(_LoopTrampoline):
    """Profile handlers run inside Scheduler.schedule too: pick_profiles /
    process_results execute off-loop every cycle (pre_request stays on the
    loop — the director calls it directly on the unwrapped plugin list)."""

    def pick_profiles(self, ctx, request, profiles, results):
        return self._call(self._plugin.pick_profiles, ctx, request,
                          profiles, results)

    def process_results(self, ctx, request, results):
        return self._call(self._plugin.process_results, ctx, request, results)


def trampoline_scheduler(scheduler: Scheduler,
                         loop: asyncio.AbstractEventLoop) -> Scheduler:
    """Clone the scheduler's profiles with every non-THREAD_SAFE
    filter/scorer/picker wrapped in a loop trampoline. Returns the original
    scheduler when nothing needed wrapping (the common all-in-tree case)."""
    profiles: dict[str, SchedulerProfile] = {}
    wrapped_any = False
    for name, prof in scheduler.profiles.items():
        fs = [f if _is_threadsafe(f) else _FilterTrampoline(f, loop)
              for f in prof.filters]
        ss = [ws if _is_threadsafe(ws.scorer)
              else WeightedScorer(_ScorerTrampoline(ws.scorer, loop), ws.weight)
              for ws in prof.scorers]
        pk = (prof.picker if _is_threadsafe(prof.picker)
              else _PickerTrampoline(prof.picker, loop))
        changed = (any(f is not o for f, o in zip(fs, prof.filters))
                   or any(s is not o for s, o in zip(ss, prof.scorers))
                   or pk is not prof.picker)
        if changed:
            wrapped_any = True
            wrapped = [w.typed_name() for w in
                       [f for f in fs if isinstance(f, _LoopTrampoline)]
                       + [s.scorer for s in ss
                          if isinstance(s.scorer, _LoopTrampoline)]
                       + ([pk] if isinstance(pk, _LoopTrampoline) else [])]
            log.info("profile %s: trampolining %s back onto the loop "
                     "(no THREAD_SAFE declaration)", name,
                     [str(w) for w in wrapped])
            profiles[name] = SchedulerProfile(prof.name, fs, ss, pk)
        else:
            profiles[name] = prof
    handler = scheduler.profile_handler
    if not _handler_threadsafe(handler):
        log.info("profile handler %s: trampolining pick_profiles/"
                 "process_results back onto the loop (handler or one of "
                 "its deciders lacks THREAD_SAFE = True)",
                 handler.typed_name())
        handler = _HandlerTrampoline(handler, loop)
        wrapped_any = True
    if not wrapped_any:
        return scheduler
    return Scheduler(profiles, handler)


class SchedulerPool:
    """Runs scheduling cycles inline (``workers: 0``) or on worker threads
    over snapshot views. One pool per gateway; its executor doubles as the
    CPU-offload pool for scrape-text parsing and large-body request
    parsing (the satellite offloads share the same threads — all three are
    pure-Python parse/score CPU that otherwise rides the event loop)."""

    def __init__(self, scheduler: Scheduler, cfg: SchedulingConfig | None = None):
        self.scheduler = scheduler
        self.cfg = cfg or SchedulingConfig()
        self._executor: concurrent.futures.ThreadPoolExecutor | None = None
        self._holds_switch_interval = False
        if self.cfg.workers > 0:
            self._executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.cfg.workers, thread_name_prefix="sched-worker")
            # Bound the loop's GIL wait behind CPU-bound workers (see module
            # docstring). Refcounted: the interval is process-global.
            _switch_interval_acquire()
            self._holds_switch_interval = True
        self._loop: asyncio.AbstractEventLoop | None = None
        self._offload_scheduler: Scheduler | None = None

    @property
    def offloaded(self) -> bool:
        return self._executor is not None

    @property
    def vectorized(self) -> bool:
        return self.cfg.vectorized

    @property
    def executor(self) -> concurrent.futures.ThreadPoolExecutor | None:
        """Shared CPU-offload executor (None when ``workers: 0``)."""
        return self._executor

    def _bind(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop
        self._offload_scheduler = trampoline_scheduler(self.scheduler, loop)

    async def schedule(self, ctx: Any, request: Any,
                       candidates: list) -> Any:
        if self._executor is None:
            return self.scheduler.schedule(ctx, request, candidates)
        loop = asyncio.get_running_loop()
        if self._loop is not loop or self._offload_scheduler is None:
            self._bind(loop)
        sched = self._offload_scheduler
        t_submit = time.monotonic()

        def cycle():
            SCHED_OFFLOAD_QUEUE_SECONDS.observe(time.monotonic() - t_submit)
            return sched.schedule(ctx, request, candidates)

        return await loop.run_in_executor(self._executor, cycle)

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
        if self._holds_switch_interval:
            self._holds_switch_interval = False
            _switch_interval_release()


class LoopLagMonitor:
    """Event-loop stall heartbeat: sleeps ``interval_s`` and records the
    overshoot into ``router_loop_lag_seconds``. The production twin of the
    bench's stall probe — the number the offload exists to shrink, live on
    /metrics so a regression (a new on-loop CPU hog) is graphable."""

    def __init__(self, interval_s: float = 0.1):
        self.interval_s = interval_s
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _run(self):
        loop = asyncio.get_running_loop()
        interval = self.interval_s
        try:
            while True:
                t0 = loop.time()
                await asyncio.sleep(interval)
                LOOP_LAG_SECONDS.observe(max(loop.time() - t0 - interval, 0.0))
        except asyncio.CancelledError:
            pass
