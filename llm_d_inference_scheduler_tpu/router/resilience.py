"""Shared resilience layer: retry budgets, circuit breaking, deadlines, chaos.

The router exists to keep traffic flowing through engine churn (P/D-Serve,
arXiv:2408.08147: fast failover between disaggregated instances + fallback to
aggregated serving is what keeps P99s flat; RTP-LLM makes the same case for
deadline-bounded, retry-budgeted dispatch). This module holds the mechanisms
both data planes share:

- ``RetryBudget``: a token bucket that bounds how many *retries* the fleet
  may issue relative to first-attempt traffic, so failover cannot amplify an
  outage into a retry storm (Finagle/Envoy retry-budget semantics: a deposit
  per admitted request plus a small time-based trickle, spent 1 token per
  retry).
- ``CircuitBreaker`` / ``BreakerRegistry``: passive consecutive-failure
  ejection per endpoint with half-open probes. The registry lives on the
  Datastore so the gateway's per-request checks and the
  ``circuit-breaker-filter`` scheduling plugin share one view — a broken pod
  is excluded fleet-wide, not just per request.
- ``Deadline``: end-to-end request timeout carried in the
  ``x-request-timeout`` header (float seconds), decremented across hops
  (gateway → sidecar → engine) so every leg inherits the *remaining* budget.
- ``FaultInjector``: deterministic, env/config-gated chaos rules (connection
  reset, injected 503, fixed latency, mid-stream stall) decided by
  request-id hash — every failover behavior above is testable hermetically
  and reproducibly.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Any, Callable

from .metrics import (
    BREAKER_STATE,
    BREAKER_TRANSITIONS_TOTAL,
)

# End-to-end deadline wire header: float seconds of REMAINING budget. Each
# hop re-stamps it with its own remaining time before dialing downstream.
H_REQUEST_TIMEOUT = "x-request-timeout"

DEADLINE_EXCEEDED_REASON = "deadline-exceeded"
RETRY_BUDGET_REASON = "retry-budget-exhausted"


class UpstreamFailure(Exception):
    """A pre-stream upstream failure the caller may retry or surface.

    ``kind``: "connect" (dial/transport error before a response),
    "read" (body read failed before anything was relayed to the client),
    "status" (a retryable 502/503 response), or "deadline".
    """

    def __init__(self, kind: str, status: int, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.kind = kind
        self.status = status
        self.reason = reason
        self.detail = detail


# ---- configuration ------------------------------------------------------


@dataclasses.dataclass
class ResilienceConfig:
    """The YAML ``resilience:`` section (camelCase keys, like the rest of
    the EndpointPickerConfig surface)."""

    # Per-request attempt cap (first attempt + retries/failovers).
    max_attempts: int = 3
    # Retry budget: tokens deposited per admitted request / per second /
    # bucket cap. A retry spends 1 token; an empty bucket fails fast.
    retry_budget_ratio: float = 0.1
    retry_budget_min_per_sec: float = 1.0
    retry_budget_burst: float = 10.0
    # Passive endpoint circuit breaking.
    breaker_failure_threshold: int = 5
    breaker_open_s: float = 30.0
    breaker_half_open_successes: int = 1
    # End-to-end deadlines: default when the client sends no
    # x-request-timeout (0 = no default), and a cap on what clients may ask.
    default_timeout_s: float = 0.0
    max_timeout_s: float = 600.0

    @classmethod
    def from_spec(cls, spec: dict[str, Any] | None) -> "ResilienceConfig":
        spec = spec or {}
        return cls(
            max_attempts=max(1, int(spec.get("maxAttempts", 3))),
            retry_budget_ratio=float(spec.get("retryBudgetRatio", 0.1)),
            retry_budget_min_per_sec=float(spec.get("retryBudgetMinPerSec", 1.0)),
            retry_budget_burst=float(spec.get("retryBudgetBurst", 10.0)),
            breaker_failure_threshold=max(
                1, int(spec.get("breakerFailureThreshold", 5))),
            breaker_open_s=float(spec.get("breakerOpenS", 30.0)),
            breaker_half_open_successes=max(
                1, int(spec.get("breakerHalfOpenSuccesses", 1))),
            default_timeout_s=float(spec.get("defaultTimeoutS", 0.0)),
            max_timeout_s=float(spec.get("maxTimeoutS", 600.0)),
        )


# ---- retry budget -------------------------------------------------------


class RetryBudget:
    """Token bucket bounding fleet-wide retry amplification.

    Deposits: ``ratio`` tokens per admitted request (call ``deposit()`` once
    per request) plus a lazy ``min_per_sec`` time trickle so a quiet router
    can still probe a recovering pool. Spends: 1 token per retry. The bucket
    starts full (``burst``) so a cold router can absorb a small burst.
    """

    def __init__(self, ratio: float = 0.1, min_per_sec: float = 1.0,
                 burst: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.ratio = max(ratio, 0.0)
        self.min_per_sec = max(min_per_sec, 0.0)
        self.burst = max(burst, 0.0)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.min_per_sec)
        self._last = now

    @property
    def tokens(self) -> float:
        self._refill()
        return self._tokens

    def deposit(self) -> None:
        """One admitted request arrived: grow the budget by ``ratio``."""
        self._refill()
        self._tokens = min(self.burst, self._tokens + self.ratio)

    def try_spend(self, n: float = 1.0) -> bool:
        """Reserve budget for one retry; False = fail fast, don't retry."""
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


# ---- circuit breaker ----------------------------------------------------

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Passive per-endpoint breaker: consecutive failures open it; after
    ``open_s`` it half-opens and admits ONE in-flight probe at a time;
    ``half_open_successes`` successful probes close it, any probe failure
    re-opens it."""

    def __init__(self, failure_threshold: int = 5, open_s: float = 30.0,
                 half_open_successes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.open_s = open_s
        self.half_open_successes = max(1, half_open_successes)
        self._clock = clock
        self.state = CLOSED
        self._failures = 0
        self._successes = 0
        self._opened_at = 0.0
        self._probe_inflight = False

    def _maybe_half_open(self) -> None:
        if self.state == OPEN and self._clock() - self._opened_at >= self.open_s:
            self.state = HALF_OPEN
            self._successes = 0
            self._probe_inflight = False

    def allow(self) -> bool:
        """Consume an attempt slot. Half-open admits a single in-flight
        probe; callers MUST follow up with record_success/record_failure."""
        self._maybe_half_open()
        if self.state == OPEN:
            return False
        if self.state == HALF_OPEN:
            if self._probe_inflight:
                return False
            self._probe_inflight = True
        return True

    def would_allow(self) -> bool:
        """Non-consuming view for scheduling filters: only hard-open
        endpoints are excluded (half-open stays schedulable so probes
        flow)."""
        self._maybe_half_open()
        return self.state != OPEN

    def record_success(self) -> None:
        if self.state == HALF_OPEN:
            self._probe_inflight = False
            self._successes += 1
            if self._successes >= self.half_open_successes:
                self.state = CLOSED
                self._failures = 0
        else:
            self._failures = 0

    def record_failure(self) -> None:
        if self.state == HALF_OPEN:
            self._probe_inflight = False
            self._open()
        elif self.state == CLOSED:
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._open()

    def release(self) -> None:
        """An allow()ed attempt was abandoned before any outcome (budget
        fast-fail, caller cancelled): free the half-open probe slot without
        counting a success or failure, so the endpoint doesn't stay
        unprobeable forever."""
        if self.state == HALF_OPEN:
            self._probe_inflight = False

    def _open(self) -> None:
        self.state = OPEN
        self._opened_at = self._clock()
        self._failures = 0


class BreakerRegistry:
    """Per-endpoint breakers keyed by address_port, with the state mirrored
    to the ``router_endpoint_circuit_breaker_state`` gauge (0 closed,
    1 half-open, 2 open — label cardinality bounded by pool size, same
    contract as the scrape-error counter)."""

    def __init__(self, failure_threshold: int = 5, open_s: float = 30.0,
                 half_open_successes: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self._kw = dict(failure_threshold=failure_threshold, open_s=open_s,
                        half_open_successes=half_open_successes)
        self._clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}

    def configure(self, cfg: ResilienceConfig) -> None:
        """Apply the loaded resilience config (gateway startup — before any
        traffic, so existing breakers needn't be rebuilt)."""
        self._kw = dict(failure_threshold=cfg.breaker_failure_threshold,
                        open_s=cfg.breaker_open_s,
                        half_open_successes=cfg.breaker_half_open_successes)
        self._breakers.clear()

    def _get(self, key: str) -> CircuitBreaker:
        b = self._breakers.get(key)
        if b is None:
            b = CircuitBreaker(clock=self._clock, **self._kw)
            self._breakers[key] = b
            BREAKER_STATE.labels(key).set(0)
        return b

    def _tracked(self, key: str, fn: Callable[[CircuitBreaker], Any]) -> Any:
        b = self._get(key)
        before = b.state
        out = fn(b)
        if b.state != before:
            BREAKER_STATE.labels(key).set(_STATE_VALUE[b.state])
            BREAKER_TRANSITIONS_TOTAL.labels(key, b.state).inc()
        return out

    def allow(self, key: str) -> bool:
        return self._tracked(key, lambda b: b.allow())

    def would_allow(self, key: str) -> bool:
        return self._tracked(key, lambda b: b.would_allow())

    def record_success(self, key: str) -> None:
        self._tracked(key, lambda b: b.record_success())

    def record_failure(self, key: str) -> None:
        self._tracked(key, lambda b: b.record_failure())

    def release_probe(self, key: str) -> None:
        self._tracked(key, lambda b: b.release())

    def state(self, key: str) -> str:
        b = self._breakers.get(key)
        if b is None:
            return CLOSED
        b._maybe_half_open()
        return b.state

    def remove(self, key: str) -> None:
        """Endpoint left the pool: drop its breaker and gauge label."""
        if self._breakers.pop(key, None) is not None:
            try:
                BREAKER_STATE.remove(key)
            except KeyError:
                pass

    def states(self) -> dict[str, str]:
        return {k: self.state(k) for k in list(self._breakers)}


# ---- end-to-end deadlines -----------------------------------------------


class Deadline:
    """Remaining end-to-end budget for one request, decremented implicitly
    as time passes; every hop re-stamps ``x-request-timeout`` with
    ``header_value()`` so downstream legs inherit what's left."""

    __slots__ = ("_deadline", "_clock")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._deadline = clock() + max(budget_s, 0.0)

    @property
    def remaining_s(self) -> float:
        return max(self._deadline - self._clock(), 0.0)

    @property
    def expired(self) -> bool:
        return self._clock() >= self._deadline

    def header_value(self) -> str:
        return f"{self.remaining_s:.3f}"

    @classmethod
    def from_headers(cls, headers: Any, *, default_s: float = 0.0,
                     max_s: float = 600.0,
                     clock: Callable[[], float] = time.monotonic
                     ) -> "Deadline | None":
        """Parse ``x-request-timeout`` (float seconds). An explicit
        non-positive value means "already expired" (a hop forwarded an
        exhausted budget); an absent/invalid header falls back to
        ``default_s`` (0 = no deadline)."""
        raw = headers.get(H_REQUEST_TIMEOUT) if headers is not None else None
        budget = None
        if raw is not None:
            try:
                budget = float(raw)
            except (TypeError, ValueError):
                budget = None
        if budget is None:
            if default_s <= 0:
                return None
            budget = default_s
        return cls(min(budget, max_s), clock)


# ---- deterministic fault injection --------------------------------------


@dataclasses.dataclass
class FaultRule:
    kind: str          # see FaultInjector.KINDS
    pct: float         # 0..100 of request-id hash space
    arg: float = 0.0   # delay/stall: ms; slow_start: ms; stall_drain: count


class FaultInjector:
    """Config/env-gated chaos shim. Rules are decided by a stable hash of
    (seed, rule kind, request id): the same request id always takes the same
    fault, so chaos tests are hermetic and re-runnable. Spec grammar:
    comma-separated ``kind:pct[:arg]`` — e.g.
    ``"reset:50,http503:25,delay:100:250,stall:25:10"``. First matching rule
    wins. ``triggered`` counts firings per kind (test observability).

    The request-plane kinds (reset/http503/delay/stall) decide per
    request id. The LIFECYCLE kinds drill the elastic-fleet actuator
    (ISSUE 17) and decide per pod identity instead — ``spawn_fail``
    makes the engine's listener raise at startup, ``slow_start`` holds
    /health at 503 for ``arg`` ms after boot (spawn-watchdog food), and
    ``stall_drain`` pins ``arg`` phantom running requests in the metrics
    exposition so a drain never observes empty (stuck-drain watchdog
    food). Same stable-hash determinism: one (seed, kind, pod) always
    decides the same way."""

    KINDS = ("reset", "http503", "delay", "stall",
             "spawn_fail", "slow_start", "stall_drain")
    LIFECYCLE_KINDS = ("spawn_fail", "slow_start", "stall_drain")

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = rules
        self.seed = seed
        self.enabled = True
        self.triggered: dict[str, int] = {k: 0 for k in self.KINDS}

    @classmethod
    def from_spec(cls, spec: str | None, seed: int = 0) -> "FaultInjector | None":
        spec = (spec or "").strip()
        if not spec:
            return None
        rules = []
        for part in spec.split(","):
            fields = [f.strip() for f in part.strip().split(":")]
            if not fields or not fields[0]:
                continue
            kind = fields[0]
            if kind not in cls.KINDS:
                raise ValueError(f"unknown fault kind {kind!r}; "
                                 f"known: {cls.KINDS}")
            pct = float(fields[1]) if len(fields) > 1 else 100.0
            arg = float(fields[2]) if len(fields) > 2 else 0.0
            rules.append(FaultRule(kind, pct, arg))
        return cls(rules, seed) if rules else None

    def decide(self, request_id: str) -> FaultRule | None:
        if not self.enabled:
            return None
        for rule in self.rules:
            if rule.kind in self.LIFECYCLE_KINDS:
                # Lifecycle rules key on pod identity, not request ids —
                # a spawn_fail rule must not also eat request traffic.
                continue
            h = zlib.crc32(f"{self.seed}:{rule.kind}:{request_id}".encode()) % 10000
            if h < rule.pct * 100:
                self.triggered[rule.kind] += 1
                return rule
        return None

    def decide_lifecycle(self, kind: str, pod_id: str) -> FaultRule | None:
        """Per-pod decision for the lifecycle kinds: same stable hash,
        keyed on the pod's identity (its address:port) so a chaos run
        fails the SAME spawns every time under a fixed seed."""
        if not self.enabled:
            return None
        for rule in self.rules:
            if rule.kind != kind:
                continue
            h = zlib.crc32(
                f"{self.seed}:{rule.kind}:{pod_id}".encode()) % 10000
            if h < rule.pct * 100:
                self.triggered[rule.kind] += 1
                return rule
        return None
