"""Kubernetes API binding: list+watch informers driving the datastore.

The client-go analogue for the reference's controller layer
(/root/reference/pkg/epp/controller/{pod,pool,objective,modelrewrite}_reconciler.go,
wired by cmd/epp/runner/runner.go + server/controller_manager.go's
namespace-scoped caches). The reference leans on controller-runtime:
informer caches fed by the API server's list+watch protocol, reconcilers
converging the EPP datastore. Python has no client-go, so this module
implements the same protocol directly against the REST API:

- ``KubeApiClient``: GET list (items + resourceVersion) and GET
  ``watch=true`` streaming newline-delimited JSON watch events, with
  in-cluster auth convention (bearer token file) or explicit base URL.
- ``Informer``: the list→watch→relist loop. A watch picks up from the
  list's resourceVersion; disconnects resume from the last seen version;
  ``410 Gone`` (version too old) forces a fresh list — exactly client-go's
  Reflector behavior. BOOKMARK events advance the version without data.
- ``KubeBinding``: four informers converging the datastore the same way
  the reference's four reconcilers do — InferencePool (selector + target
  port), Pods (filtered by the pool selector → endpoint resync),
  InferenceObjective and InferenceModelRewrite custom resources
  (group ``llm-d.ai/v1alpha2``, mirroring apix/v1alpha2).

Standalone mode (static endpoints / ConfigReconciler file watching,
router/controlplane.py) remains the default; this binding activates with
``--kube-api-url`` on the gateway CLI.
"""

from __future__ import annotations

import asyncio
import dataclasses
import datetime
import json
import logging
import os
import random
import time
import uuid
from typing import Any, Callable

log = logging.getLogger("router.kube")

DEFAULT_TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
DEFAULT_CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"
CRD_GROUP = "llm-d.ai"
CRD_VERSION = "v1alpha2"


class WatchRelist(Exception):
    """Watch stream invalidated (410 Gone / decode error) — relist needed."""


class KubeApiClient:
    """Minimal k8s REST client: list + watch with bearer-token auth."""

    def __init__(self, base_url: str, token: str | None = None,
                 token_path: str | None = None, ca_path: str | None = None):
        self.base_url = base_url.rstrip("/")
        if token is None and token_path:
            try:
                with open(token_path) as f:
                    token = f.read().strip()
            except OSError:
                token = None
        self._token = token
        # In-cluster API servers present a cert signed by the cluster CA,
        # which is NOT in the system trust store — it is mounted beside the
        # service-account token. Without loading it every https request
        # fails certificate verification.
        if ca_path is None and os.path.exists(DEFAULT_CA_PATH):
            ca_path = DEFAULT_CA_PATH
        self._ca_path = ca_path
        self._session = None

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            headers = {}
            if self._token:
                headers["Authorization"] = f"Bearer {self._token}"
            connector = None
            if self.base_url.startswith("https") and self._ca_path:
                import ssl

                connector = aiohttp.TCPConnector(
                    ssl=ssl.create_default_context(cafile=self._ca_path))
            # Watch frames for real pods (managedFields etc.) routinely
            # exceed aiohttp's default 64 KiB line buffer; a small buffer
            # turns every large event into a permanent relist loop.
            self._session = aiohttp.ClientSession(headers=headers,
                                                  connector=connector,
                                                  read_bufsize=2 ** 22)
        return self._session

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None

    # ---- object verbs (lease election + future writes) ------------------

    async def get(self, path: str) -> tuple[int, dict | None]:
        """GET a single object; returns (status, body-or-None)."""
        session = await self._ensure_session()
        async with session.get(self.base_url + path) as resp:
            if resp.status == 404:
                return 404, None
            resp.raise_for_status()
            return resp.status, await resp.json()

    async def create(self, path: str, obj: dict) -> tuple[int, dict | None]:
        """POST to a collection; 409 means the object already exists."""
        session = await self._ensure_session()
        async with session.post(self.base_url + path, json=obj) as resp:
            if resp.status == 409:
                return 409, None
            resp.raise_for_status()
            return resp.status, await resp.json()

    async def replace(self, path: str, obj: dict) -> tuple[int, dict | None]:
        """PUT an object; 409 means the resourceVersion precondition failed
        (another writer won — k8s optimistic concurrency)."""
        session = await self._ensure_session()
        async with session.put(self.base_url + path, json=obj) as resp:
            if resp.status in (404, 409):
                return resp.status, None
            resp.raise_for_status()
            return resp.status, await resp.json()

    async def list(self, path: str,
                   label_selector: str | None = None) -> tuple[list[dict], str]:
        """GET a collection; returns (items, list resourceVersion)."""
        session = await self._ensure_session()
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        async with session.get(self.base_url + path, params=params) as resp:
            resp.raise_for_status()
            body = await resp.json()
        rv = str((body.get("metadata") or {}).get("resourceVersion") or "")
        return list(body.get("items") or []), rv

    async def watch(self, path: str, resource_version: str,
                    label_selector: str | None = None,
                    on_event: Callable[[str, dict], None] | None = None,
                    timeout_s: float = 300.0) -> str:
        """Stream watch events, invoking ``on_event(type, object)``.

        Returns the last seen resourceVersion on clean stream end; raises
        WatchRelist when the server reports 410 Gone or the stream is
        undecodable (client-go Reflector semantics).
        """
        import aiohttp

        session = await self._ensure_session()
        params = {"watch": "true", "resourceVersion": resource_version,
                  "allowWatchBookmarks": "true"}
        if label_selector:
            params["labelSelector"] = label_selector
        rv = resource_version
        # Connection/auth failures (refused, 401/403/5xx) must PROPAGATE so
        # the informer's outer loop backs off and logs — only mid-stream
        # disconnects after a successful open are swallowed (resume from the
        # last seen version, client-go Reflector semantics).
        async with session.get(
                self.base_url + path, params=params,
                timeout=aiohttp.ClientTimeout(total=None,
                                              sock_read=timeout_s)) as resp:
            if resp.status == 410:
                raise WatchRelist("HTTP 410 Gone")
            resp.raise_for_status()
            it = resp.content.__aiter__()
            while True:
                # The stream read gets its own narrow exception scope: only
                # transport errors map to resume/relist — a ValueError
                # raised by an on_event callback (bad CR field) must surface
                # as the data error it is, not as a frame problem.
                try:
                    raw = await it.__anext__()
                except StopAsyncIteration:
                    break
                except ValueError as e:
                    # aiohttp raises ValueError ("Chunk too big") when a
                    # frame exceeds read_bufsize: the stream is no longer
                    # line-aligned, so relist instead of looping forever.
                    raise WatchRelist(f"oversize watch frame: {e}")
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    break  # mid-stream hiccup: resume from rv
                line = raw.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError as e:
                    raise WatchRelist(f"undecodable watch frame: {e}")
                etype = event.get("type", "")
                obj = event.get("object") or {}
                if etype == "ERROR":
                    code = (obj.get("code") or 0)
                    if code == 410:
                        raise WatchRelist("ERROR event 410 Gone")
                    raise WatchRelist(f"watch ERROR event: {obj}")
                new_rv = ((obj.get("metadata") or {})
                          .get("resourceVersion"))
                if new_rv:
                    rv = str(new_rv)
                if etype == "BOOKMARK":
                    continue
                if on_event is not None:
                    on_event(etype, obj)
        return rv


def _key(obj: dict) -> str:
    meta = obj.get("metadata") or {}
    return f"{meta.get('namespace', '')}/{meta.get('name', '')}"


class Informer:
    """client-go Reflector analogue: list, sync the cache, then watch;
    resume on disconnect, relist on 410."""

    def __init__(self, client: KubeApiClient, path: str,
                 on_sync: Callable[[dict[str, dict]], None],
                 on_change: Callable[[dict[str, dict]], None],
                 label_selector: str | None = None,
                 relist_backoff_s: float = 1.0):
        self.client = client
        self.path = path
        self.label_selector = label_selector
        self.on_sync = on_sync          # full cache after (re)list
        self.on_change = on_change      # full cache after each watch event
        self.relist_backoff_s = relist_backoff_s
        self.cache: dict[str, dict] = {}
        self.synced = asyncio.Event()
        self._task: asyncio.Task | None = None

    def _apply_event(self, etype: str, obj: dict) -> None:
        key = _key(obj)
        if etype == "DELETED":
            self.cache.pop(key, None)
        elif etype in ("ADDED", "MODIFIED"):
            self.cache[key] = obj
        else:
            return
        self.on_change(dict(self.cache))

    async def _run(self):
        backoff = self.relist_backoff_s
        while True:
            try:
                items, rv = await self.client.list(self.path,
                                                   self.label_selector)
                self.cache = {_key(o): o for o in items}
                self.on_sync(dict(self.cache))
                self.synced.set()
                backoff = self.relist_backoff_s
                while True:
                    rv = await self.client.watch(
                        self.path, rv, self.label_selector,
                        on_event=self._apply_event)
            except WatchRelist as e:
                log.info("informer %s: relist (%s)", self.path, e)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("informer %s: list/watch failed; retrying",
                              self.path)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    async def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self):
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


@dataclasses.dataclass
class PoolSpec:
    """InferencePool essentials (selector + ports), from the CR or flags."""

    selector: dict[str, str] = dataclasses.field(default_factory=dict)
    target_port: int = 8000
    metrics_port: int | None = None


class KubeBinding:
    """Converges the datastore from the k8s API — the reference's
    reconciler set, standalone-binding edition.

    Pods are watched namespace-wide and filtered client-side against the
    pool selector (so a pool selector change re-filters the existing cache
    without restarting the watch — the reference achieves the same with a
    pool-scoped informer restart, pool_reconciler.go)."""

    def __init__(self, datastore: Any, client: KubeApiClient, namespace: str,
                 pool_name: str | None = None,
                 pool: PoolSpec | None = None):
        self.datastore = datastore
        self.client = client
        self.namespace = namespace
        self.pool_name = pool_name
        self.pool = pool or PoolSpec()
        # With a named pool, endpoint resync is gated until the pool CR has
        # been observed: the zero-value selector matches EVERY pod in the
        # namespace, which would route inference traffic to arbitrary
        # workloads during startup (or forever, if the name is wrong).
        self._pool_seen = pool_name is None
        ns = namespace
        self._informers: list[Informer] = []
        if pool_name:
            self._informers.append(Informer(
                client, f"/apis/{CRD_GROUP}/{CRD_VERSION}/namespaces/{ns}/"
                        "inferencepools",
                self._pools_changed, self._pools_changed))
        self._pod_informer = Informer(
            client, f"/api/v1/namespaces/{ns}/pods",
            self._pods_changed, self._pods_changed)
        self._informers.append(self._pod_informer)
        self._informers.append(Informer(
            client, f"/apis/{CRD_GROUP}/{CRD_VERSION}/namespaces/{ns}/"
                    "inferenceobjectives",
            self._objectives_changed, self._objectives_changed))
        self._informers.append(Informer(
            client, f"/apis/{CRD_GROUP}/{CRD_VERSION}/namespaces/{ns}/"
                    "inferencemodelrewrites",
            self._rewrites_changed, self._rewrites_changed))

    # ---- reconcile callbacks (run on the event loop) --------------------

    def _pools_changed(self, cache: dict[str, dict]) -> None:
        obj = cache.get(f"{self.namespace}/{self.pool_name}")
        if obj is None:
            return
        self._pool_seen = True
        spec = obj.get("spec") or {}
        sel = (spec.get("selector") or {}).get("matchLabels") or {}
        self.pool = PoolSpec(
            selector=dict(sel),
            target_port=int(spec.get("targetPort")
                            or spec.get("targetPortNumber") or 8000),
            metrics_port=(int(spec["metricsPort"])
                          if spec.get("metricsPort") else None))
        # Re-filter the current pod cache under the new selector.
        self._pods_changed(dict(self._pod_informer.cache))

    def _pod_matches(self, pod: dict) -> bool:
        labels = (pod.get("metadata") or {}).get("labels") or {}
        return all(labels.get(k) == v for k, v in self.pool.selector.items())

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        """PodReady condition True — a Running pod still loading weights or
        failing its readiness probe must not receive inference traffic
        (reference pod_reconciler.go:92 → util/pod.go IsPodReady)."""
        conditions = (pod.get("status") or {}).get("conditions") or []
        return any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in conditions)

    def _pods_changed(self, cache: dict[str, dict]) -> None:
        from .framework.datalayer import EndpointMetadata

        if not self._pool_seen:
            return
        metas = []
        for pod in cache.values():
            meta = pod.get("metadata") or {}
            status = pod.get("status") or {}
            ip = status.get("podIP")
            if not ip or status.get("phase") not in (None, "Running"):
                continue  # pending/terminated pods carry no routable address
            if meta.get("deletionTimestamp"):
                continue
            if not self._pod_ready(pod):
                continue
            if not self._pod_matches(pod):
                continue
            metas.append(EndpointMetadata(
                name=meta.get("name") or ip,
                address=ip,
                port=self.pool.target_port,
                metrics_port=self.pool.metrics_port,
                labels=dict(meta.get("labels") or {})))
        self.datastore.resync(metas)

    def _objectives_changed(self, cache: dict[str, dict]) -> None:
        from .datalayer.datastore import InferenceObjective

        declared = set()
        for obj in cache.values():
            name = (obj.get("metadata") or {}).get("name")
            if not name:
                continue
            declared.add(name)
            spec = obj.get("spec") or {}
            self.datastore.objective_set(InferenceObjective(
                name=name, priority=int(spec.get("priority", 0))))
        for name in [n for n in self.datastore.objective_names()
                     if n not in declared]:
            self.datastore.objective_delete(name)

    def _rewrites_changed(self, cache: dict[str, dict]) -> None:
        from .datalayer.datastore import (
            InferenceModelRewrite,
            ModelRewriteTarget,
        )

        declared = set()
        for obj in cache.values():
            meta = obj.get("metadata") or {}
            spec = obj.get("spec") or {}
            source = spec.get("sourceModel") or spec.get("source")
            if not source:
                continue
            declared.add(source)
            self.datastore.rewrite_set(InferenceModelRewrite(
                name=meta.get("name") or source,
                source_model=source,
                targets=[ModelRewriteTarget(model=t["model"],
                                            weight=int(t.get("weight", 1)))
                         for t in spec.get("targets") or []]))
        for source in [s for s in self.datastore.rewrite_sources()
                       if s not in declared]:
            self.datastore.rewrite_delete(source)

    # ---- lifecycle ------------------------------------------------------

    async def start(self):
        # Mirror the --watch-config warning: once the binding is active it
        # owns endpoints/objectives/rewrites — statically-configured entries
        # (--config-file / --endpoints) are replaced on the first sync.
        log.warning(
            "kube binding active: endpoints, objectives and model rewrites "
            "are now owned by the cluster API — entries from --config-file/"
            "--endpoints will be overwritten on sync")
        for inf in self._informers:
            await inf.start()

    async def wait_synced(self, timeout_s: float = 30.0):
        await asyncio.wait_for(
            asyncio.gather(*(inf.synced.wait() for inf in self._informers)),
            timeout_s)

    async def stop(self):
        for inf in self._informers:
            await inf.stop()
        await self.client.close()


# ---- coordination.k8s.io/v1 Lease leader election -----------------------


def _micro_time(ts: float) -> str:
    """k8s MicroTime format (RFC3339 with microseconds, UTC)."""
    return (datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)
            .strftime("%Y-%m-%dT%H:%M:%S.%fZ"))


class KubeLeaseElector:
    """Leader election over a coordination.k8s.io/v1 Lease object — the
    reference's election backend (controller_manager.go:84-91: lease id
    ``epp-<ns>-<name>.llm-d.ai``, leader-elect resource lock). Replaces the
    file-based LeaseElector when a kube API is available, removing the
    RWX-volume deployment constraint.

    client-go LeaderElector semantics (leaderelection.go): acquire creates
    the Lease (POST; 409 → someone else won); renew PUTs renewTime
    periodically; takeover rewrites holderIdentity + bumps leaseTransitions
    once ``renewTime + leaseDurationSeconds`` has passed; every write is
    guarded by the object's resourceVersion so concurrent claimants race
    safely; graceful release shortens the lease so followers take over
    immediately.
    """

    def __init__(self, client: KubeApiClient, namespace: str, name: str,
                 holder_id: str | None = None,
                 lease_duration_s: float = 5.0,
                 renew_interval_s: float = 1.0,
                 renew_deadline_s: float | None = None,
                 on_started_leading: Callable[[], None] | None = None,
                 on_stopped_leading: Callable[[], None] | None = None):
        self.client = client
        self.namespace = namespace
        self.name = name
        self.holder_id = holder_id or os.environ.get(
            "POD_NAME") or f"epp-{uuid.uuid4().hex[:8]}"
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        # How long a leader keeps leading through failed renews before
        # demoting (client-go RenewDeadline, default 2/3 of the lease): one
        # transient apiserver error must not flip the whole pair unready.
        self.renew_deadline_s = (renew_deadline_s
                                 if renew_deadline_s is not None
                                 else lease_duration_s * 2 / 3)
        self.is_leader = False
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._task: asyncio.Task | None = None
        self._rng = random.Random()
        # Local observation clock for foreign leases (client-go
        # observedTime): expiry is timed from when WE last saw the lease
        # record change, never by comparing the remote renewTime timestamp
        # against the local wall clock — node clock skew larger than the
        # lease duration would otherwise cause spurious takeover.
        self._observed_record: tuple | None = None
        self._observed_at: float = 0.0
        self._last_renew_ok: float = 0.0
        self._path = (f"/apis/coordination.k8s.io/v1/namespaces/"
                      f"{namespace}/leases/{name}")
        self._collection = (f"/apis/coordination.k8s.io/v1/namespaces/"
                            f"{namespace}/leases")

    def _spec(self, *, acquire: bool, transitions: int,
              now: float) -> dict[str, Any]:
        spec = {"holderIdentity": self.holder_id,
                "leaseDurationSeconds": int(self.lease_duration_s),
                "renewTime": _micro_time(now),
                "leaseTransitions": transitions}
        if acquire:
            spec["acquireTime"] = _micro_time(now)
        return spec

    async def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        status, lease = await self.client.get(self._path)
        if lease is None:
            status, created = await self.client.create(self._collection, {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.namespace},
                "spec": self._spec(acquire=True, transitions=0, now=now)})
            return created is not None  # 409 → lost the creation race

        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        transitions = int(spec.get("leaseTransitions") or 0)
        if holder == self.holder_id:
            lease["spec"].update(self._spec(acquire=False,
                                            transitions=transitions, now=now))
            status, updated = await self.client.replace(self._path, lease)
            # 409: our snapshot is stale (e.g. a takeover stole the lease
            # after our expiry) — demote and re-read next tick.
            return updated is not None

        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration_s)
        record = (holder, spec.get("renewTime"), spec.get("acquireTime"))
        mono = time.monotonic()
        if record != self._observed_record:
            # The holder is renewing — restart OUR observation clock.
            self._observed_record = record
            self._observed_at = mono
            return False
        if mono - self._observed_at < duration:
            return False  # live foreign lease (locally-observed freshness)
        # No renew observed for a full lease duration: take over.
        # resourceVersion rides along, so if another claimant got there
        # first the PUT 409s and we stay a follower.
        lease["spec"].update(self._spec(acquire=True,
                                        transitions=transitions + 1, now=now))
        status, updated = await self.client.replace(self._path, lease)
        if updated is not None:
            log.info("lease %s: took over from expired holder %s",
                     self.name, holder)
        return updated is not None

    def _set_leader(self, leading: bool) -> None:
        if leading and not self.is_leader:
            self.is_leader = True
            log.info("lease %s: %s started leading", self.name, self.holder_id)
            if self.on_started_leading:
                self.on_started_leading()
        elif not leading and self.is_leader:
            self.is_leader = False
            log.warning("lease %s: %s stopped leading", self.name,
                        self.holder_id)
            if self.on_stopped_leading:
                self.on_stopped_leading()

    async def release(self) -> None:
        """Graceful handoff (client-go release): keep holderIdentity but
        shrink the lease to one second in the past so any follower's next
        tick sees it expired."""
        try:
            status, lease = await self.client.get(self._path)
            if lease is not None and (lease.get("spec") or {}).get(
                    "holderIdentity") == self.holder_id:
                lease["spec"]["renewTime"] = _micro_time(time.time() - 1.0)
                lease["spec"]["leaseDurationSeconds"] = 1
                await self.client.replace(self._path, lease)
        except Exception:
            log.exception("lease release failed (followers will take over "
                          "after expiry)")
        self._set_leader(False)

    async def _run(self):
        try:
            while True:
                try:
                    leading = await self._try_acquire_or_renew()
                    if leading:
                        self._last_renew_ok = time.monotonic()
                    self._set_leader(leading)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # API unreachable. A leader retries within the renew
                    # deadline (a transient apiserver blip must not flip
                    # the pair unready); past it, demote — a follower may
                    # legally take over once the lease expires.
                    if (self.is_leader and time.monotonic()
                            - self._last_renew_ok < self.renew_deadline_s):
                        log.warning("lease %s: renew failed; retrying "
                                    "within renew deadline", self.name)
                    else:
                        log.exception("lease %s: renew/acquire failed; "
                                      "demoting", self.name)
                        self._set_leader(False)
                delay = self.renew_interval_s
                if not self.is_leader:
                    delay += self._rng.uniform(0, self.renew_interval_s / 2)
                await asyncio.sleep(delay)
        except asyncio.CancelledError:
            pass

    async def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self, *, graceful: bool = True):
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if graceful:
            await self.release()
        await self.client.close()
