"""Kubernetes API binding: list+watch informers driving the datastore.

The client-go analogue for the reference's controller layer
(/root/reference/pkg/epp/controller/{pod,pool,objective,modelrewrite}_reconciler.go,
wired by cmd/epp/runner/runner.go + server/controller_manager.go's
namespace-scoped caches). The reference leans on controller-runtime:
informer caches fed by the API server's list+watch protocol, reconcilers
converging the EPP datastore. Python has no client-go, so this module
implements the same protocol directly against the REST API:

- ``KubeApiClient``: GET list (items + resourceVersion) and GET
  ``watch=true`` streaming newline-delimited JSON watch events, with
  in-cluster auth convention (bearer token file) or explicit base URL.
- ``Informer``: the list→watch→relist loop. A watch picks up from the
  list's resourceVersion; disconnects resume from the last seen version;
  ``410 Gone`` (version too old) forces a fresh list — exactly client-go's
  Reflector behavior. BOOKMARK events advance the version without data.
- ``KubeBinding``: four informers converging the datastore the same way
  the reference's four reconcilers do — InferencePool (selector + target
  port), Pods (filtered by the pool selector → endpoint resync),
  InferenceObjective and InferenceModelRewrite custom resources
  (group ``llm-d.ai/v1alpha2``, mirroring apix/v1alpha2).

Standalone mode (static endpoints / ConfigReconciler file watching,
router/controlplane.py) remains the default; this binding activates with
``--kube-api-url`` on the gateway CLI.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
from typing import Any, Callable

log = logging.getLogger("router.kube")

DEFAULT_TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
CRD_GROUP = "llm-d.ai"
CRD_VERSION = "v1alpha2"


class WatchRelist(Exception):
    """Watch stream invalidated (410 Gone / decode error) — relist needed."""


class KubeApiClient:
    """Minimal k8s REST client: list + watch with bearer-token auth."""

    def __init__(self, base_url: str, token: str | None = None,
                 token_path: str | None = None):
        self.base_url = base_url.rstrip("/")
        if token is None and token_path:
            try:
                with open(token_path) as f:
                    token = f.read().strip()
            except OSError:
                token = None
        self._token = token
        self._session = None

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            headers = {}
            if self._token:
                headers["Authorization"] = f"Bearer {self._token}"
            # Watch frames for real pods (managedFields etc.) routinely
            # exceed aiohttp's default 64 KiB line buffer; a small buffer
            # turns every large event into a permanent relist loop.
            self._session = aiohttp.ClientSession(headers=headers,
                                                  read_bufsize=2 ** 22)
        return self._session

    async def close(self):
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def list(self, path: str,
                   label_selector: str | None = None) -> tuple[list[dict], str]:
        """GET a collection; returns (items, list resourceVersion)."""
        session = await self._ensure_session()
        params = {}
        if label_selector:
            params["labelSelector"] = label_selector
        async with session.get(self.base_url + path, params=params) as resp:
            resp.raise_for_status()
            body = await resp.json()
        rv = str((body.get("metadata") or {}).get("resourceVersion") or "")
        return list(body.get("items") or []), rv

    async def watch(self, path: str, resource_version: str,
                    label_selector: str | None = None,
                    on_event: Callable[[str, dict], None] | None = None,
                    timeout_s: float = 300.0) -> str:
        """Stream watch events, invoking ``on_event(type, object)``.

        Returns the last seen resourceVersion on clean stream end; raises
        WatchRelist when the server reports 410 Gone or the stream is
        undecodable (client-go Reflector semantics).
        """
        import aiohttp

        session = await self._ensure_session()
        params = {"watch": "true", "resourceVersion": resource_version,
                  "allowWatchBookmarks": "true"}
        if label_selector:
            params["labelSelector"] = label_selector
        rv = resource_version
        # Connection/auth failures (refused, 401/403/5xx) must PROPAGATE so
        # the informer's outer loop backs off and logs — only mid-stream
        # disconnects after a successful open are swallowed (resume from the
        # last seen version, client-go Reflector semantics).
        async with session.get(
                self.base_url + path, params=params,
                timeout=aiohttp.ClientTimeout(total=None,
                                              sock_read=timeout_s)) as resp:
            if resp.status == 410:
                raise WatchRelist("HTTP 410 Gone")
            resp.raise_for_status()
            it = resp.content.__aiter__()
            while True:
                # The stream read gets its own narrow exception scope: only
                # transport errors map to resume/relist — a ValueError
                # raised by an on_event callback (bad CR field) must surface
                # as the data error it is, not as a frame problem.
                try:
                    raw = await it.__anext__()
                except StopAsyncIteration:
                    break
                except ValueError as e:
                    # aiohttp raises ValueError ("Chunk too big") when a
                    # frame exceeds read_bufsize: the stream is no longer
                    # line-aligned, so relist instead of looping forever.
                    raise WatchRelist(f"oversize watch frame: {e}")
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    break  # mid-stream hiccup: resume from rv
                line = raw.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except json.JSONDecodeError as e:
                    raise WatchRelist(f"undecodable watch frame: {e}")
                etype = event.get("type", "")
                obj = event.get("object") or {}
                if etype == "ERROR":
                    code = (obj.get("code") or 0)
                    if code == 410:
                        raise WatchRelist("ERROR event 410 Gone")
                    raise WatchRelist(f"watch ERROR event: {obj}")
                new_rv = ((obj.get("metadata") or {})
                          .get("resourceVersion"))
                if new_rv:
                    rv = str(new_rv)
                if etype == "BOOKMARK":
                    continue
                if on_event is not None:
                    on_event(etype, obj)
        return rv


def _key(obj: dict) -> str:
    meta = obj.get("metadata") or {}
    return f"{meta.get('namespace', '')}/{meta.get('name', '')}"


class Informer:
    """client-go Reflector analogue: list, sync the cache, then watch;
    resume on disconnect, relist on 410."""

    def __init__(self, client: KubeApiClient, path: str,
                 on_sync: Callable[[dict[str, dict]], None],
                 on_change: Callable[[dict[str, dict]], None],
                 label_selector: str | None = None,
                 relist_backoff_s: float = 1.0):
        self.client = client
        self.path = path
        self.label_selector = label_selector
        self.on_sync = on_sync          # full cache after (re)list
        self.on_change = on_change      # full cache after each watch event
        self.relist_backoff_s = relist_backoff_s
        self.cache: dict[str, dict] = {}
        self.synced = asyncio.Event()
        self._task: asyncio.Task | None = None

    def _apply_event(self, etype: str, obj: dict) -> None:
        key = _key(obj)
        if etype == "DELETED":
            self.cache.pop(key, None)
        elif etype in ("ADDED", "MODIFIED"):
            self.cache[key] = obj
        else:
            return
        self.on_change(dict(self.cache))

    async def _run(self):
        backoff = self.relist_backoff_s
        while True:
            try:
                items, rv = await self.client.list(self.path,
                                                   self.label_selector)
                self.cache = {_key(o): o for o in items}
                self.on_sync(dict(self.cache))
                self.synced.set()
                backoff = self.relist_backoff_s
                while True:
                    rv = await self.client.watch(
                        self.path, rv, self.label_selector,
                        on_event=self._apply_event)
            except WatchRelist as e:
                log.info("informer %s: relist (%s)", self.path, e)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("informer %s: list/watch failed; retrying",
                              self.path)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

    async def start(self):
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self):
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None


@dataclasses.dataclass
class PoolSpec:
    """InferencePool essentials (selector + ports), from the CR or flags."""

    selector: dict[str, str] = dataclasses.field(default_factory=dict)
    target_port: int = 8000
    metrics_port: int | None = None


class KubeBinding:
    """Converges the datastore from the k8s API — the reference's
    reconciler set, standalone-binding edition.

    Pods are watched namespace-wide and filtered client-side against the
    pool selector (so a pool selector change re-filters the existing cache
    without restarting the watch — the reference achieves the same with a
    pool-scoped informer restart, pool_reconciler.go)."""

    def __init__(self, datastore: Any, client: KubeApiClient, namespace: str,
                 pool_name: str | None = None,
                 pool: PoolSpec | None = None):
        self.datastore = datastore
        self.client = client
        self.namespace = namespace
        self.pool_name = pool_name
        self.pool = pool or PoolSpec()
        # With a named pool, endpoint resync is gated until the pool CR has
        # been observed: the zero-value selector matches EVERY pod in the
        # namespace, which would route inference traffic to arbitrary
        # workloads during startup (or forever, if the name is wrong).
        self._pool_seen = pool_name is None
        ns = namespace
        self._informers: list[Informer] = []
        if pool_name:
            self._informers.append(Informer(
                client, f"/apis/{CRD_GROUP}/{CRD_VERSION}/namespaces/{ns}/"
                        "inferencepools",
                self._pools_changed, self._pools_changed))
        self._pod_informer = Informer(
            client, f"/api/v1/namespaces/{ns}/pods",
            self._pods_changed, self._pods_changed)
        self._informers.append(self._pod_informer)
        self._informers.append(Informer(
            client, f"/apis/{CRD_GROUP}/{CRD_VERSION}/namespaces/{ns}/"
                    "inferenceobjectives",
            self._objectives_changed, self._objectives_changed))
        self._informers.append(Informer(
            client, f"/apis/{CRD_GROUP}/{CRD_VERSION}/namespaces/{ns}/"
                    "inferencemodelrewrites",
            self._rewrites_changed, self._rewrites_changed))

    # ---- reconcile callbacks (run on the event loop) --------------------

    def _pools_changed(self, cache: dict[str, dict]) -> None:
        obj = cache.get(f"{self.namespace}/{self.pool_name}")
        if obj is None:
            return
        self._pool_seen = True
        spec = obj.get("spec") or {}
        sel = (spec.get("selector") or {}).get("matchLabels") or {}
        self.pool = PoolSpec(
            selector=dict(sel),
            target_port=int(spec.get("targetPort")
                            or spec.get("targetPortNumber") or 8000),
            metrics_port=(int(spec["metricsPort"])
                          if spec.get("metricsPort") else None))
        # Re-filter the current pod cache under the new selector.
        self._pods_changed(dict(self._pod_informer.cache))

    def _pod_matches(self, pod: dict) -> bool:
        labels = (pod.get("metadata") or {}).get("labels") or {}
        return all(labels.get(k) == v for k, v in self.pool.selector.items())

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        """PodReady condition True — a Running pod still loading weights or
        failing its readiness probe must not receive inference traffic
        (reference pod_reconciler.go:92 → util/pod.go IsPodReady)."""
        conditions = (pod.get("status") or {}).get("conditions") or []
        return any(c.get("type") == "Ready" and c.get("status") == "True"
                   for c in conditions)

    def _pods_changed(self, cache: dict[str, dict]) -> None:
        from .framework.datalayer import EndpointMetadata

        if not self._pool_seen:
            return
        metas = []
        for pod in cache.values():
            meta = pod.get("metadata") or {}
            status = pod.get("status") or {}
            ip = status.get("podIP")
            if not ip or status.get("phase") not in (None, "Running"):
                continue  # pending/terminated pods carry no routable address
            if meta.get("deletionTimestamp"):
                continue
            if not self._pod_ready(pod):
                continue
            if not self._pod_matches(pod):
                continue
            metas.append(EndpointMetadata(
                name=meta.get("name") or ip,
                address=ip,
                port=self.pool.target_port,
                metrics_port=self.pool.metrics_port,
                labels=dict(meta.get("labels") or {})))
        self.datastore.resync(metas)

    def _objectives_changed(self, cache: dict[str, dict]) -> None:
        from .datalayer.datastore import InferenceObjective

        declared = set()
        for obj in cache.values():
            name = (obj.get("metadata") or {}).get("name")
            if not name:
                continue
            declared.add(name)
            spec = obj.get("spec") or {}
            self.datastore.objective_set(InferenceObjective(
                name=name, priority=int(spec.get("priority", 0))))
        for name in [n for n in self.datastore.objective_names()
                     if n not in declared]:
            self.datastore.objective_delete(name)

    def _rewrites_changed(self, cache: dict[str, dict]) -> None:
        from .datalayer.datastore import (
            InferenceModelRewrite,
            ModelRewriteTarget,
        )

        declared = set()
        for obj in cache.values():
            meta = obj.get("metadata") or {}
            spec = obj.get("spec") or {}
            source = spec.get("sourceModel") or spec.get("source")
            if not source:
                continue
            declared.add(source)
            self.datastore.rewrite_set(InferenceModelRewrite(
                name=meta.get("name") or source,
                source_model=source,
                targets=[ModelRewriteTarget(model=t["model"],
                                            weight=int(t.get("weight", 1)))
                         for t in spec.get("targets") or []]))
        for source in [s for s in self.datastore.rewrite_sources()
                       if s not in declared]:
            self.datastore.rewrite_delete(source)

    # ---- lifecycle ------------------------------------------------------

    async def start(self):
        # Mirror the --watch-config warning: once the binding is active it
        # owns endpoints/objectives/rewrites — statically-configured entries
        # (--config-file / --endpoints) are replaced on the first sync.
        log.warning(
            "kube binding active: endpoints, objectives and model rewrites "
            "are now owned by the cluster API — entries from --config-file/"
            "--endpoints will be overwritten on sync")
        for inf in self._informers:
            await inf.start()

    async def wait_synced(self, timeout_s: float = 30.0):
        await asyncio.wait_for(
            asyncio.gather(*(inf.synced.wait() for inf in self._informers)),
            timeout_s)

    async def stop(self):
        for inf in self._informers:
            await inf.stop()
        await self.client.close()
