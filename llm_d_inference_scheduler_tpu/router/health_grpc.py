"""gRPC health service (grpc.health.v1.Health) for the gateway.

Reference parity: cmd/epp/runner/health.go — a gRPC health endpoint whose
overall status tracks pool readiness, with a per-service check for
`envoy.service.ext_proc.v3.ExternalProcessor`.

The image ships grpcio but not grpcio-health-checking, and the health/v1
proto is two one-field messages — so the wire format is encoded by hand:
  HealthCheckRequest  { string service = 1; }          (field 1, len-delim)
  HealthCheckResponse { ServingStatus status = 1; }    (field 1, varint)
"""

from __future__ import annotations

import logging

import grpc
import grpc.aio

log = logging.getLogger("router.health_grpc")

SERVICE_NAME = "grpc.health.v1.Health"
EXT_PROC_SERVICE = "envoy.service.ext_proc.v3.ExternalProcessor"

UNKNOWN, SERVING, NOT_SERVING, SERVICE_UNKNOWN = 0, 1, 2, 3


def _encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def parse_request(data: bytes) -> str:
    """Extract `service` (field 1, wire type 2) from HealthCheckRequest.
    Truncated/malformed input degrades to "" (the overall-health check)."""
    try:
        return _parse_request(data)
    except IndexError:
        return ""


def _parse_request(data: bytes) -> str:
    i = 0
    service = ""
    while i < len(data):
        tag = data[i]
        i += 1
        field, wire = tag >> 3, tag & 0x7
        if wire == 2:  # length-delimited
            ln = 0
            shift = 0
            while True:
                b = data[i]
                i += 1
                ln |= (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
            payload = data[i:i + ln]
            i += ln
            if field == 1:
                service = payload.decode("utf-8", errors="replace")
        elif wire == 0:  # varint: skip
            while data[i] & 0x80:
                i += 1
            i += 1
        else:  # unsupported wire type: stop parsing defensively
            break
    return service


def serialize_response(status: int) -> bytes:
    return b"\x08" + _encode_varint(status)


class HealthServer:
    """Serves Check/Watch; status derives from a readiness callback."""

    def __init__(self, ready_fn, host: str = "127.0.0.1", port: int = 0,
                 tls=None):
        self.ready_fn = ready_fn
        self.host, self.port = host, port
        # With secure serving, health shares the gateway's TLS identity —
        # the reference registers health on the same TLS gRPC server as
        # ext-proc (runserver.go HealthChecking branch).
        self.tls = tls
        self._server: grpc.aio.Server | None = None

    def _status_for(self, service: str) -> int:
        if service not in ("", EXT_PROC_SERVICE):
            return SERVICE_UNKNOWN
        return SERVING if self.ready_fn() else NOT_SERVING

    async def _check(self, request: str, context) -> int:
        return self._status_for(request)

    async def _watch(self, request: str, context):
        # Minimal Watch: emit the current status once, then updates on change.
        import asyncio

        last = None
        while True:
            status = self._status_for(request)
            if status != last:
                yield status
                last = status
            await asyncio.sleep(1.0)

    async def start(self) -> int:
        self._server = grpc.aio.server()
        handlers = grpc.method_handlers_generic_handler(SERVICE_NAME, {
            "Check": grpc.unary_unary_rpc_method_handler(
                self._check,
                request_deserializer=parse_request,
                response_serializer=serialize_response),
            "Watch": grpc.unary_stream_rpc_method_handler(
                self._watch,
                request_deserializer=parse_request,
                response_serializer=serialize_response),
        })
        self._server.add_generic_rpc_handlers((handlers,))
        addr = f"{self.host}:{self.port}"
        if self.tls is not None:
            self.port = self._server.add_secure_port(
                addr, self.tls.grpc_server_credentials())
        else:
            self.port = self._server.add_insecure_port(addr)
        await self._server.start()
        log.info("gRPC health on %s:%d%s", self.host, self.port,
                 " (TLS)" if self.tls else "")
        return self.port

    async def stop(self):
        if self._server:
            await self._server.stop(grace=0.5)
