"""The router half: TPU-native Endpoint Picker (EPP) + disaggregation sidecar.

Implements the capabilities of the reference's control plane
(/root/reference, llm-d/llm-d-inference-scheduler — see SURVEY.md):
scheduler with pluggable filters/scorers/pickers, data layer scraping
JetStream-style engine telemetry, flow control, request orchestration, and the
prefill/decode disaggregation protocol — re-targeted at TPU engines.
"""
